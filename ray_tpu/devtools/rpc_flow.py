"""Whole-program blocking-graph analyzer for the RPC control plane.

Every other devtools pass is local: aio_lint is per-function, lifecycle is
intraprocedural, protocols is per-state-machine, and the explore harness is
exhaustive only on closed scenario fragments. This pass closes the remaining
axis — *cross-process* blocking — statically. It reuses rpc_check's wire
inventory (literal method strings at ``conn.call(...)`` sites matched against
``Server.register(...)`` registrations) to build an interprocedural blocking
graph: for every registered handler, the closure of same-module calls it can
make, the RPCs issued along that closure (resolved to the destination
service's handler), and the local suspension points (futures, Events,
Queues, ``rpc.spawn``). The model is the Chandy–Misra–Haas wait-for graph
applied at lint time instead of detection time: a cycle among handler nodes
is the distributed-deadlock shape before it ever hangs a deployment.

Rules
-----
- ``wait-cycle``: a cycle in the cross-service blocking graph over
  synchronous edges (``call``/``call_into``/``call_with_blob`` — vias that
  suspend the issuing handler until the remote handler replies). Example
  shape: a GCS handler awaiting a raylet RPC whose handler synchronously
  re-enters the GCS. Every edge crosses a process boundary, so every cycle
  is a potential distributed deadlock (and a certain one on the single-loop
  SimCluster, where the "two processes" share an event loop).
  Spawn-crossing and ``call_nowait`` edges are recorded in the graph but
  excluded from cycles: a spawned task or an unawaited future does not
  block the handler that issued it.
- ``deadline-drop``: a request issued on a handler path through a via that
  drops the caller's remaining deadline budget. ``Connection.call`` and
  ``call_into`` fold the ambient handler deadline into the frame TTL
  (``_effective_deadline``), but ``call_nowait`` only carries a TTL when
  ``deadline=`` is passed explicitly, ``call_cb`` never carries one, and
  ``call_with_blob`` *cannot* (its fifth frame slot is the blob byte
  length). Work dispatched through those vias outlives the deadline that
  ``_run_deadlined`` enforces at the top of the calling handler. Flagged
  only when the handler's method is ever called with a budget (some call
  site passes ``timeout=``/``deadline=`` or uses an ambient-folding via).
  Remedy: pass ``deadline=rpc.current_deadline()`` (absolute loop-time
  instant) or switch to ``conn.call``; waive one-way wire shapes.
- ``unbounded-await``: a handler path awaits a future
  (``loop.create_future()`` locals, ``*.fut`` attributes), an
  ``Event.wait()``, or a queue ``get()``/``join()`` with no
  ``asyncio.wait_for`` bound, while the handler's method is *not*
  guaranteed a deadline (at least one call site sends no TTL). The await
  can park the handler forever; ``_run_deadlined`` only cancels when a
  TTL rode the frame. Only the synchronous part of the closure counts:
  across an ``rpc.spawn`` boundary the spawned task, not the handler, is
  the one parked (background pumps/reapers wait unboundedly by design).
- ``unsupervised-spawn``: a bare ``rpc.spawn(...)``/``self._spawn(...)``
  expression statement (result dropped — failure is only logged by the
  spawn machinery) on a handler path that participates in a ledgered
  pair (raylet grant ledger, ``available`` resource arithmetic) or the
  placement-group 2PC protocol (``PreparePGBundles``/``CommitPGBundles``/
  ``ReleasePGBundles``). A crashed background step strands the ledger or
  the 2PC state machine with nobody to repair it.

Static horizon: callee resolution is same-module only (``self._foo()`` and
module-level ``foo()``); cross-module helper wrappers around ``conn.call``
are not followed — direct call sites dominate this codebase. Receiver
hints (``node.conn`` → raylet, ``handle.conn`` → worker, ``self.gcs`` →
gcs) disambiguate method names registered by more than one service;
unhinted ambiguous sites fan out to every registrant (over-approximation).

Suppression: ``# rpc-flow: disable=<rule>[,<rule>]`` (or ``disable=all``)
on the flagged line or the line directly above it. The unified lint gate's
stale-suppression audit covers this family.

Run: ``python -m ray_tpu.devtools.rpc_flow [--markdown] [--mutate NAME
[--expect-violation]] [paths]``. ``--markdown`` emits the committed
``docs/rpc_flow.md`` blocking-graph inventory; ``--mutate back_call``
overlays a seeded synchronous back-call cycle (a raylet ``ReleasePGBundles``
handler re-entering the GCS) and ``--expect-violation`` inverts the exit
status so CI proves the pass has teeth.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
import textwrap
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools import rpc_check
from ray_tpu.devtools.aio_lint import (
    Finding,
    _default_root,
    _dotted,
    iter_py_files,
)

RULE_CYCLE = "wait-cycle"
RULE_DROP = "deadline-drop"
RULE_UNBOUNDED = "unbounded-await"
RULE_SPAWN = "unsupervised-spawn"

ALL_RULES = (RULE_CYCLE, RULE_DROP, RULE_UNBOUNDED, RULE_SPAWN)

_SUPPRESS_RE = re.compile(r"#\s*rpc-flow:\s*disable=([\w\-, ]+)")

# ---------------------------------------------------------------------------
# Service topology.
#
# Which OS process a file's handlers run in. Path-suffix keyed (basenames
# collide: serve has its own server.py). Files not listed are named by
# module stem — they only matter if they register handlers.
# ---------------------------------------------------------------------------

_SERVICE_MAP: Tuple[Tuple[str, str], ...] = (
    ("_private/gcs.py", "gcs"),
    ("_private/gcs_ha.py", "gcs"),
    ("_private/gcs_store.py", "gcs"),
    ("_private/raylet.py", "raylet"),
    ("_private/worker_main.py", "worker"),
    ("_private/worker_zygote.py", "worker"),
    ("_private/core_worker.py", "core"),
    ("_private/worker.py", "driver"),
    ("util/client/server.py", "client-proxy"),
)

# Receiver-chain tokens that pin an ambiguous method name to one service:
# ``handle.conn.call("CreateActor", ...)`` in the raylet dials the *worker*
# it just leased, not the GCS registration of the same method name.
_RECV_HINTS: Tuple[Tuple[str, str], ...] = (
    ("gcs", "gcs"),
    ("raylet", "raylet"),
    ("node", "raylet"),
    ("peer", "raylet"),
    ("handle", "worker"),
    ("worker", "worker"),
    ("lease", "worker"),
)

# Vias that suspend the issuing handler until the remote replies: these are
# the blocking edges cycles are computed over. call_nowait returns a future
# (blocks only if awaited later — beyond the static horizon, recorded as an
# async edge); push/push_nowait/blob_push_nowait are one-way notifications.
_SYNC_VIAS = {"call", "call_into", "call_with_blob"}
_ASYNC_VIAS = {"call_nowait", "call_cb"}

# Request-shaped vias that drop the ambient deadline budget (see module
# docstring). call_nowait only drops it when no explicit deadline= rides.
_DROP_VIAS = {"call_cb", "call_with_blob"}

_TWO_PC_METHODS = {"PreparePGBundles", "CommitPGBundles", "ReleasePGBundles"}

# Ledgered-pair participation markers (see devtools/lifecycle.py REGISTRY):
# the raylet grant-dedup ledger methods and the `available` resource
# arithmetic idiom.
_LEDGER_CALLS = {"_record_granted", "_mark_lease_released", "_burn_lease_id"}
_LEDGER_ATTR = "available"

_SPAWN_NAMES = {"spawn", "_spawn"}


def _service_for(path: str) -> str:
    norm = os.path.abspath(path).replace(os.sep, "/")
    for suffix, svc in _SERVICE_MAP:
        if norm.endswith(suffix):
            return svc
    return os.path.splitext(os.path.basename(path))[0]


# ---------------------------------------------------------------------------
# Per-function facts.
# ---------------------------------------------------------------------------


@dataclass
class RpcSite:
    method: str
    via: str
    line: int
    recv: str  # dotted receiver chain ("node.conn", "self.gcs", ...)
    timeout_src: Optional[str] = None  # unparsed timeout= argument
    deadline_src: Optional[str] = None  # unparsed deadline= argument


@dataclass
class AwaitSite:
    line: int
    kind: str  # "future" | "event" | "queue"
    desc: str  # unparsed awaited expression


@dataclass
class SpawnSite:
    line: int
    target: Optional[str]  # trailing name of the spawned callable, if a call
    desc: str
    supervised: bool  # result bound to a name (caller can observe failure)


@dataclass
class FnInfo:
    path: str
    qualname: str
    line: int
    is_async: bool
    rpc_sites: List[RpcSite] = field(default_factory=list)
    await_sites: List[AwaitSite] = field(default_factory=list)
    spawn_sites: List[SpawnSite] = field(default_factory=list)
    callees: Set[str] = field(default_factory=set)  # resolved same-module
    spawned: Set[str] = field(default_factory=set)  # spawned same-module
    ledger: bool = False
    two_pc: bool = False


def _local_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas
    (they are scanned as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _queueish(recv: str) -> bool:
    last = recv.rsplit(".", 1)[-1].lower()
    return "queue" in last or last == "q" or last.endswith("_q")


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return "<expr>"


class _ModuleScan:
    """All FnInfos of one module, plus name-based lookup for callee and
    registered-handler resolution."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.fns: Dict[str, FnInfo] = {}
        self.by_name: Dict[str, List[str]] = {}
        self._walk(tree.body, prefix="")

    def _walk(self, body: Sequence[ast.stmt], prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._walk(node.body, prefix=f"{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                if qual in self.fns:  # redefinition: keep the last
                    self.by_name[node.name].remove(qual)
                self.fns[qual] = self._scan_fn(node, qual)
                self.by_name.setdefault(node.name, []).append(qual)
                # Nested defs become their own (bare-name addressable) fns.
                self._walk(
                    [
                        n
                        for n in ast.walk(node)
                        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n is not node
                        and self._direct_child_fn(node, n)
                    ],
                    prefix=f"{qual}.",
                )

    @staticmethod
    def _direct_child_fn(parent: ast.AST, fn: ast.AST) -> bool:
        for node in _local_nodes(parent):
            if node is fn:
                return True
        return False

    def resolve(self, name: str, cls: Optional[str]) -> Optional[str]:
        """Resolve a called name to a qualname in this module."""
        if cls is not None and f"{cls}.{name}" in self.fns:
            return f"{cls}.{name}"
        quals = self.by_name.get(name, [])
        if len(quals) == 1:
            return quals[0]
        if cls is None and name in self.fns:
            return name
        return None

    def _scan_fn(self, fn: ast.AST, qual: str) -> FnInfo:
        info = FnInfo(
            path=self.path,
            qualname=qual,
            line=fn.lineno,
            is_async=isinstance(fn, ast.AsyncFunctionDef),
        )
        expr_values = set()
        spawn_args = set()
        fut_vars: Set[str] = set()
        for node in _local_nodes(fn):
            if isinstance(node, ast.Expr):
                expr_values.add(id(node.value))
            elif (
                isinstance(node, ast.Call)
                and _tail(node.func) in _SPAWN_NAMES
                and node.args
                and isinstance(node.args[0], ast.Call)
            ):
                # The coroutine call inside spawn(...) crosses the spawn
                # boundary — it must not double as a synchronous callee.
                spawn_args.add(id(node.args[0]))
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                tail = _tail(node.value.func)
                dotted = _dotted(node.value.func) or ""
                if tail == "create_future" or dotted == "asyncio.Future":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            fut_vars.add(tgt.id)

        for node in _local_nodes(fn):
            if isinstance(node, ast.Call):
                self._scan_call(node, info, expr_values, spawn_args)
            elif isinstance(node, ast.Await):
                self._scan_await(node, info, fut_vars)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                if node.value in _TWO_PC_METHODS:
                    info.two_pc = True
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in tgts:
                    if isinstance(tgt, ast.Attribute) and tgt.attr == _LEDGER_ATTR:
                        info.ledger = True
        return info

    def _scan_call(
        self,
        node: ast.Call,
        info: FnInfo,
        expr_values: Set[int],
        spawn_args: Set[int],
    ) -> None:
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        tail = _tail(func)
        if attr in rpc_check._CALL_METHODS and node.args:
            method = node.args[0]
            if isinstance(method, ast.Constant) and isinstance(method.value, str):
                timeout_src = None
                deadline_src = None
                if attr == "call" and len(node.args) > 2:
                    timeout_src = _unparse(node.args[2])
                for kw in node.keywords:
                    if kw.arg == "timeout":
                        timeout_src = _unparse(kw.value)
                    elif kw.arg == "deadline":
                        deadline_src = _unparse(kw.value)
                info.rpc_sites.append(
                    RpcSite(
                        method=method.value,
                        via=attr,
                        line=node.lineno,
                        recv=_dotted(func.value) or "?",
                        timeout_src=timeout_src,
                        deadline_src=deadline_src,
                    )
                )
                return
        if tail in _LEDGER_CALLS:
            info.ledger = True
        if tail in _SPAWN_NAMES:
            target = None
            if node.args and isinstance(node.args[0], ast.Call):
                target = _tail(node.args[0].func)
            elif node.args:
                # spawn(coro) forwarding a parameter (the spawn wrapper
                # itself) — nothing to say about an opaque coroutine.
                return
            info.spawn_sites.append(
                SpawnSite(
                    line=node.lineno,
                    target=target,
                    desc=_unparse(node)[:80],
                    supervised=id(node) not in expr_values,
                )
            )
            if target is not None:
                info.spawned.add(target)
            return
        # Same-module callee candidates: self.X(...) and bare f(...).
        if id(node) in spawn_args:
            return
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            info.callees.add(func.attr)
        elif isinstance(func, ast.Name):
            info.callees.add(func.id)

    def _scan_await(
        self, node: ast.Await, info: FnInfo, fut_vars: Set[str]
    ) -> None:
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
            recv = _dotted(v.func.value) or ""
            if v.func.attr == "wait" and not v.args and recv != "asyncio":
                info.await_sites.append(
                    AwaitSite(node.lineno, "event", _unparse(v))
                )
            elif v.func.attr in ("get", "join") and _queueish(recv):
                info.await_sites.append(
                    AwaitSite(node.lineno, "queue", _unparse(v))
                )
        elif isinstance(v, ast.Name) and v.id in fut_vars:
            info.await_sites.append(AwaitSite(node.lineno, "future", v.id))
        elif isinstance(v, ast.Attribute) and (
            v.attr in ("fut", "future") or v.attr.endswith("_fut")
        ):
            info.await_sites.append(
                AwaitSite(node.lineno, "future", _unparse(v))
            )


# ---------------------------------------------------------------------------
# Whole-program analysis.
# ---------------------------------------------------------------------------


@dataclass
class Edge:
    src_service: str
    src_method: str
    dst_service: str
    dst_method: str
    site: RpcSite
    site_path: str
    via_spawn: bool  # reached across a spawn boundary


@dataclass
class Handler:
    service: str
    method: str
    path: str
    line: int
    qualname: Optional[str]  # None when the handler body is out of reach


@dataclass
class Analysis:
    handlers: List[Handler] = field(default_factory=list)
    edges: List[Edge] = field(default_factory=list)
    # (handler, FnInfo path, site, via_spawn) for local-wait/spawn rules.
    closure_awaits: List[Tuple[Handler, str, AwaitSite]] = field(
        default_factory=list
    )
    closure_spawns: List[Tuple[Handler, str, SpawnSite, bool]] = field(
        default_factory=list
    )
    closure_drops: List[Tuple[Handler, str, RpcSite]] = field(
        default_factory=list
    )
    # method -> every RpcSite anywhere in the tree (deadline provenance).
    sites_by_method: Dict[str, List[Tuple[str, RpcSite]]] = field(
        default_factory=dict
    )
    services_by_method: Dict[str, Set[str]] = field(default_factory=dict)


def _collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(iter_py_files(path))
        else:
            files.append(path)
    return files


def _dst_services(site: RpcSite, registered_in: Set[str]) -> Set[str]:
    """Destination services for a call site, hint-disambiguated."""
    if len(registered_in) <= 1:
        return set(registered_in)
    recv = site.recv.lower()
    segments = set(recv.replace("self.", "").split("."))
    hinted = {
        svc
        for token, svc in _RECV_HINTS
        if svc in registered_in
        and any(token in seg for seg in segments)
    }
    return hinted or set(registered_in)


def build(
    paths: Optional[Sequence[str]] = None,
    extra_sources: Optional[Sequence[Tuple[str, str]]] = None,
) -> Analysis:
    paths = list(paths or [_default_root()])
    # Share rpc_check's per-file parse+scan cache: the lint gate runs
    # rpc_check, rpc_flow, and exc_flow in one process over the same tree.
    frags: List[rpc_check.Inventory] = []
    scans: Dict[str, _ModuleScan] = {}
    for f in _collect_files(paths):
        tree, frag = rpc_check._scan_file(f)
        if tree is None:
            continue
        if frag is not None:
            frags.append(frag)
        scans[f] = _ModuleScan(f, tree)
    for vpath, vsrc in extra_sources or ():
        try:
            vtree = ast.parse(vsrc, filename=vpath)
        except SyntaxError:
            continue
        frags.append(rpc_check._scan_tree(vpath, vtree))
        scans[vpath] = _ModuleScan(vpath, vtree)
    inv = rpc_check._merge_inventories(frags)

    analysis = Analysis()
    for scan in scans.values():
        for fn in scan.fns.values():
            for site in fn.rpc_sites:
                analysis.sites_by_method.setdefault(site.method, []).append(
                    (fn.path, site)
                )
    regs_by_method: Dict[str, List[rpc_check.Registration]] = {}
    for reg in inv.regs:
        regs_by_method.setdefault(reg.method, []).append(reg)
    for method, regs in regs_by_method.items():
        analysis.services_by_method[method] = {
            _service_for(r.path) for r in regs
        }

    # One handler node per (service, method, registration); the closure
    # walk below unions multiple registrations of the same node.
    for method, regs in sorted(regs_by_method.items()):
        for reg in sorted(regs, key=lambda r: (r.path, r.line)):
            scan = scans.get(reg.path)
            qual = None
            if scan is not None and reg.handler_name:
                quals = scan.by_name.get(reg.handler_name, [])
                if quals:
                    qual = quals[0]
            handler = Handler(
                service=_service_for(reg.path),
                method=method,
                path=reg.path,
                line=reg.line,
                qualname=qual,
            )
            analysis.handlers.append(handler)
            if qual is not None:
                _walk_closure(handler, scans[reg.path], analysis)
    return analysis


def _walk_closure(
    handler: Handler, scan: _ModuleScan, analysis: Analysis
) -> None:
    """BFS the handler's same-module call closure, recording RPC edges and
    local suspension points. ``via_spawn`` marks everything reached across
    a spawn boundary — still on the handler's causal path, but no longer
    blocking it."""
    assert handler.qualname is not None
    start = handler.qualname
    seen: Set[Tuple[str, bool]] = set()
    frontier: List[Tuple[str, bool]] = [(start, False)]
    path_ledger = False
    path_two_pc = False
    visited_infos: List[Tuple[FnInfo, bool]] = []
    while frontier:
        qual, via_spawn = frontier.pop()
        if (qual, via_spawn) in seen:
            continue
        seen.add((qual, via_spawn))
        info = scan.fns.get(qual)
        if info is None:
            continue
        visited_infos.append((info, via_spawn))
        path_ledger = path_ledger or info.ledger
        path_two_pc = path_two_pc or info.two_pc
        cls = qual.rsplit(".", 1)[0] if "." in qual else None
        for name in info.callees:
            nxt = scan.resolve(name, cls)
            if nxt is not None:
                frontier.append((nxt, via_spawn))
        for name in info.spawned:
            nxt = scan.resolve(name, cls)
            if nxt is not None:
                frontier.append((nxt, True))

    critical = path_ledger or path_two_pc
    for info, via_spawn in visited_infos:
        for site in info.rpc_sites:
            registered_in = analysis.services_by_method.get(site.method, set())
            for dst in sorted(_dst_services(site, registered_in)):
                analysis.edges.append(
                    Edge(
                        src_service=handler.service,
                        src_method=handler.method,
                        dst_service=dst,
                        dst_method=site.method,
                        site=site,
                        site_path=info.path,
                        via_spawn=via_spawn,
                    )
                )
            drops = site.via in _DROP_VIAS or (
                site.via == "call_nowait" and site.deadline_src is None
            )
            if drops:
                analysis.closure_drops.append((handler, info.path, site))
        # Local waits only matter on the synchronous part of the closure:
        # across a spawn boundary the handler is not the one parked.
        if not via_spawn:
            for aw in info.await_sites:
                analysis.closure_awaits.append((handler, info.path, aw))
        for sp in info.spawn_sites:
            analysis.closure_spawns.append(
                (handler, info.path, sp, critical)
            )


# ---------------------------------------------------------------------------
# Deadline provenance (shared with rpc_check --markdown's deadline column).
# ---------------------------------------------------------------------------


def deadline_sources(
    analysis: Analysis, method: str
) -> Tuple[bool, bool, List[str]]:
    """(maybe_deadlined, guaranteed_deadlined, budget sources) for a method.

    - maybe: some call site sends a TTL (explicit timeout=/deadline=, or an
      ambient-folding via under a deadlined caller).
    - guaranteed: every call site pins an explicit budget.
    """
    sites = analysis.sites_by_method.get(method, [])
    if not sites:
        return (False, False, [])
    srcs: List[str] = []
    maybe = False
    guaranteed = True
    for _, site in sites:
        explicit = site.timeout_src or site.deadline_src
        if explicit and explicit != "None":
            srcs.append(explicit)
            maybe = True
            if "None" in explicit:
                # A conditional like ``None if t is None else t + 30`` can
                # still evaluate to no-deadline — explicit, but not pinned.
                guaranteed = False
        elif site.via in ("call", "call_into"):
            maybe = True  # folds the ambient deadline when one exists
            guaranteed = False
        else:
            guaranteed = False
    return (maybe, guaranteed, sorted(set(srcs)))


# ---------------------------------------------------------------------------
# Rules.
# ---------------------------------------------------------------------------


def _cycle_findings(analysis: Analysis) -> List[Finding]:
    graph: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    edge_at: Dict[
        Tuple[Tuple[str, str], Tuple[str, str]], Tuple[str, int]
    ] = {}
    for e in analysis.edges:
        if e.via_spawn or e.site.via not in _SYNC_VIAS:
            continue
        src = (e.src_service, e.src_method)
        dst = (e.dst_service, e.dst_method)
        graph.setdefault(src, set()).add(dst)
        key = (src, dst)
        anchor = (e.site_path, e.site.line)
        if key not in edge_at or anchor < edge_at[key]:
            edge_at[key] = anchor

    # Tarjan SCC over handler nodes; any SCC with >1 node (or a self-edge)
    # is a wait cycle.
    index: Dict[Tuple[str, str], int] = {}
    low: Dict[Tuple[str, str], int] = {}
    on_stack: Set[Tuple[str, str]] = set()
    stack: List[Tuple[str, str]] = []
    sccs: List[List[Tuple[str, str]]] = []
    counter = [0]

    def strongconnect(v: Tuple[str, str]) -> None:
        # Iterative Tarjan (handler graphs are small, but no recursion cap).
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    findings: List[Finding] = []
    for comp in sccs:
        members = set(comp)
        cyclic = len(comp) > 1 or any(
            v in graph.get(v, ()) for v in comp
        )
        if not cyclic:
            continue
        # Render one concrete cycle path for the message: walk successor
        # edges inside the SCC from the smallest node.
        ordered = sorted(members)
        walk = [ordered[0]]
        while True:
            nxts = sorted(
                w for w in graph.get(walk[-1], ()) if w in members
            )
            if not nxts:
                break
            if nxts[0] in walk:
                walk.append(nxts[0])
                break
            walk.append(nxts[0])
        label = " -> ".join(f"{s}:{m}" for s, m in walk)
        anchor = min(
            edge_at[(a, b)]
            for (a, b) in edge_at
            if a in members and b in members
        )
        findings.append(
            Finding(
                anchor[0],
                anchor[1],
                0,
                RULE_CYCLE,
                f"synchronous RPC wait cycle: {label} — every hop blocks "
                "its handler until the next replies, the distributed-"
                "deadlock shape (and a guaranteed hang on the single-loop "
                "SimCluster); break it with a call_nowait continuation, a "
                "push, or a spawned follow-up",
            )
        )
    return findings


def _drop_findings(analysis: Analysis) -> List[Finding]:
    by_site: Dict[Tuple[str, int], Tuple[RpcSite, Set[str]]] = {}
    for handler, path, site in analysis.closure_drops:
        maybe, _, _ = deadline_sources(analysis, handler.method)
        if not maybe:
            continue  # nobody ever sends this handler a budget
        key = (path, site.line)
        entry = by_site.setdefault(key, (site, set()))
        entry[1].add(f"{handler.service}:{handler.method}")
    findings = []
    for (path, line), (site, handlers) in sorted(by_site.items()):
        hs = ", ".join(sorted(handlers)[:3])
        if site.via == "call_with_blob":
            why = (
                "call_with_blob cannot carry a TTL (the fifth frame slot "
                "is the blob byte length)"
            )
        elif site.via == "call_cb":
            why = "call_cb frames never carry a TTL"
        else:
            why = "call_nowait only carries a TTL when deadline= is passed"
        findings.append(
            Finding(
                path,
                line,
                0,
                RULE_DROP,
                f"{site.via}({site.method!r}) on the deadlined handler "
                f"path of {hs} drops the remaining budget — {why}; the "
                "downstream work outlives the deadline _run_deadlined "
                "enforces at the top. Pass deadline=rpc.current_deadline() "
                "or use conn.call (which folds the ambient budget)",
            )
        )
    return findings


def _unbounded_findings(analysis: Analysis) -> List[Finding]:
    by_site: Dict[Tuple[str, int], Tuple[AwaitSite, Set[str]]] = {}
    for handler, path, aw in analysis.closure_awaits:
        _, guaranteed, _ = deadline_sources(analysis, handler.method)
        if guaranteed:
            continue  # _run_deadlined cancels the handler at the deadline
        key = (path, aw.line)
        entry = by_site.setdefault(key, (aw, set()))
        entry[1].add(f"{handler.service}:{handler.method}")
    findings = []
    for (path, line), (aw, handlers) in sorted(by_site.items()):
        hs = ", ".join(sorted(handlers)[:3])
        findings.append(
            Finding(
                path,
                line,
                0,
                RULE_UNBOUNDED,
                f"handler path of {hs} awaits {aw.kind} `{aw.desc}` with "
                "no asyncio.wait_for bound and no guaranteed request "
                "deadline — the handler can park forever. Bound it with a "
                "config budget, or make every caller send a TTL",
            )
        )
    return findings


def _spawn_findings(analysis: Analysis) -> List[Finding]:
    by_site: Dict[Tuple[str, int], Tuple[SpawnSite, Set[str]]] = {}
    for handler, path, sp, critical in analysis.closure_spawns:
        if sp.supervised or not critical:
            continue
        key = (path, sp.line)
        entry = by_site.setdefault(key, (sp, set()))
        entry[1].add(f"{handler.service}:{handler.method}")
    findings = []
    for (path, line), (sp, handlers) in sorted(by_site.items()):
        hs = ", ".join(sorted(handlers)[:3])
        findings.append(
            Finding(
                path,
                line,
                0,
                RULE_SPAWN,
                f"bare spawn `{sp.desc}` on the handler path of {hs}, "
                "which participates in a ledgered pair or the PG 2PC "
                "protocol — a crashed background step is only logged, "
                "stranding ledger/2PC state. Keep the task and observe "
                "its failure (done-callback that repairs state, or await)",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# Mutation gate.
#
# A seeded synchronous back-call cycle: a raylet ReleasePGBundles handler
# that re-enters the GCS while the GCS _remove_pg handler is itself blocked
# on ReleasePGBundles. The overlay path ends in _private/raylet.py so the
# service map attributes it to the raylet; --expect-violation requires the
# pass to flag it (the PR 14 explore --mutate pattern).
# ---------------------------------------------------------------------------

# name -> (virtual overlay path, overlay source, rule the gate must raise)
_MUTATIONS: Dict[str, Tuple[str, str, str]] = {
    "back_call": (
        "<mutant>/_private/raylet.py",
        textwrap.dedent(
            '''
            class _MutantRaylet:
                def _register_handlers(self, s):
                    s.register("ReleasePGBundles", self._release_pg_mutant)

                async def _release_pg_mutant(self, conn, p):
                    # Synchronous back-call into the GCS while the GCS
                    # _remove_pg handler blocks on ReleasePGBundles.
                    return await self.gcs.call(
                        "RemovePlacementGroup", {"pg_id": p["pg_id"]}
                    )
            '''
        ),
        RULE_CYCLE,
    ),
}


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def check(
    paths: Optional[Sequence[str]] = None,
    apply_suppressions: bool = True,
    mutate: Optional[str] = None,
) -> List[Finding]:
    extra = None
    if mutate is not None:
        if mutate not in _MUTATIONS:
            raise SystemExit(
                f"unknown mutation {mutate!r} (have: {sorted(_MUTATIONS)})"
            )
        vpath, vsrc, _ = _MUTATIONS[mutate]
        extra = [(vpath, vsrc)]
    analysis = build(paths, extra_sources=extra)
    findings = (
        _cycle_findings(analysis)
        + _drop_findings(analysis)
        + _unbounded_findings(analysis)
        + _spawn_findings(analysis)
    )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if not apply_suppressions:
        return findings

    sup_cache: Dict[str, Dict[int, Set[str]]] = {}

    def suppressed(f: Finding) -> bool:
        if f.path not in sup_cache:
            sup: Dict[int, Set[str]] = {}
            try:
                with open(f.path, "r", encoding="utf-8") as fh:
                    for i, text in enumerate(fh.read().splitlines(), 1):
                        m = _SUPPRESS_RE.search(text)
                        if m:
                            sup[i] = {
                                r.strip()
                                for r in m.group(1).split(",")
                                if r.strip()
                            }
            except OSError:
                pass
            sup_cache[f.path] = sup
        for line in (f.line, f.line - 1):
            rules = sup_cache[f.path].get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return [f for f in findings if not suppressed(f)]


def markdown(paths: Optional[Sequence[str]] = None) -> str:
    """The versioned blocking-graph inventory committed to docs/."""
    analysis = build(paths)
    root = os.path.dirname(_default_root())

    def rel(p: str) -> str:
        if p.startswith("<"):
            return p
        return os.path.relpath(p, root)

    # Service-level edge summary for the mermaid graph.
    svc_edges: Dict[Tuple[str, str, str], Set[str]] = {}
    for e in analysis.edges:
        kind = (
            "sync"
            if (not e.via_spawn and e.site.via in _SYNC_VIAS)
            else "async"
        )
        svc_edges.setdefault(
            (e.src_service, e.dst_service, kind), set()
        ).add(e.dst_method)

    lines = [
        "# RPC blocking graph",
        "",
        "Generated by `python -m ray_tpu.devtools.rpc_flow --markdown`.",
        "Nodes are services (which OS process a handler runs in); an edge",
        "`A -> B (M)` means some registered handler of A can issue RPC `M`",
        "handled by B while serving a request. **Solid** edges block the",
        "issuing handler until the remote replies (`call`/`call_into`/",
        "`call_with_blob`) — cycles over solid edges are the",
        "Chandy–Misra–Haas distributed-deadlock shape and fail the",
        "`wait-cycle` lint rule. **Dashed** edges are non-blocking",
        "(`call_nowait`/`call_cb` futures, or work reached across an",
        "`rpc.spawn` boundary): still on the causal path, but the issuing",
        "handler does not wait. One-way pushes are omitted.",
        "",
        "```mermaid",
        "graph LR",
    ]
    for (src, dst, kind), methods in sorted(svc_edges.items()):
        shown = sorted(methods)
        label = ", ".join(shown[:4]) + (
            f", +{len(shown) - 4}" if len(shown) > 4 else ""
        )
        arrow = "-->" if kind == "sync" else "-.->"
        lines.append(f"    {src} {arrow}|{label}| {dst}")
    lines.append("```")
    lines.append("")
    lines.append("## Blocking edges (handler → nested RPC)")
    lines.append("")
    lines.append(
        "| Handler (service:method) | Via | Calls | Handled by | Site |"
    )
    lines.append("|---|---|---|---|---|")
    edge_rows = set()
    for e in analysis.edges:
        via = e.site.via + (" ∥spawned" if e.via_spawn else "")
        edge_rows.add(
            (
                f"`{e.src_service}:{e.src_method}`",
                f"`{via}`",
                f"`{e.dst_method}`",
                e.dst_service,
                f"`{rel(e.site_path)}:{e.site.line}`",
            )
        )
    lines.extend(_markdown_rows(edge_rows))
    lines.append("")
    lines.append("## Handler-reachable local waits")
    lines.append("")
    lines.append(
        "Futures/Events/Queues a handler path can park on with no"
    )
    lines.append(
        "`asyncio.wait_for` bound (raw inventory — the `unbounded-await`"
    )
    lines.append(
        "rule additionally requires the method to lack a guaranteed"
    )
    lines.append("request deadline before it fires).")
    lines.append("")
    lines.append("| Handler | Waits on | Kind | Site |")
    lines.append("|---|---|---|---|")
    wait_rows = set()
    for handler, path, aw in analysis.closure_awaits:
        wait_rows.add(
            (
                f"`{handler.service}:{handler.method}`",
                f"`{aw.desc}`",
                aw.kind,
                f"`{rel(path)}:{aw.line}`",
            )
        )
    lines.extend(_markdown_rows(wait_rows))
    lines.append("")
    lines.append("## Spawn points on handler paths")
    lines.append("")
    lines.append(
        "| Handler | Spawns | Supervised | Ledger/2PC path | Site |"
    )
    lines.append("|---|---|---|---|---|")
    spawn_rows = set()
    for handler, path, sp, critical in analysis.closure_spawns:
        spawn_rows.add(
            (
                f"`{handler.service}:{handler.method}`",
                f"`{sp.target or '?'}`",
                "✓" if sp.supervised else "—",
                "✓" if critical else "—",
                f"`{rel(path)}:{sp.line}`",
            )
        )
    lines.extend(_markdown_rows(spawn_rows))
    lines.append("")
    n_sync = len(
        {
            (e.src_service, e.src_method, e.dst_service, e.dst_method)
            for e in analysis.edges
            if not e.via_spawn and e.site.via in _SYNC_VIAS
        }
    )
    lines.append(
        f"{len(analysis.handlers)} registered handlers; "
        f"{len(edge_rows)} edge rows ({n_sync} distinct blocking edges); "
        f"{len(wait_rows)} local waits; {len(spawn_rows)} spawn points."
    )
    lines.append("")
    return "\n".join(lines)


def _markdown_rows(rows: Iterable[Tuple[str, ...]]) -> List[str]:
    return ["| " + " | ".join(r) + " |" for r in sorted(rows)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.rpc_flow",
        description="whole-program RPC blocking-graph analyzer",
    )
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument(
        "--markdown",
        action="store_true",
        help="emit the blocking-graph markdown inventory instead of checking",
    )
    parser.add_argument(
        "--mutate",
        default=None,
        help=f"overlay a seeded defect (have: {sorted(_MUTATIONS)})",
    )
    parser.add_argument(
        "--expect-violation",
        action="store_true",
        help="invert the exit status: succeed only if findings were raised",
    )
    args = parser.parse_args(argv)
    paths = args.paths or None
    if args.markdown:
        print(markdown(paths))
        return 0
    findings = check(paths, mutate=args.mutate)
    for f in findings:
        print(f)
    if args.expect_violation:
        # The seeded defect must raise its *own* rule — pre-existing
        # findings of other rules must not make a toothless pass look
        # sharp.
        want = (
            _MUTATIONS[args.mutate][2] if args.mutate in _MUTATIONS else None
        )
        hits = [f for f in findings if want is None or f.rule == want]
        if hits:
            print(
                f"rpc-flow: mutation detected ({len(hits)} "
                f"{want or 'any'} finding(s)) — the pass has teeth"
            )
            return 0
        print(
            f"rpc-flow: expected a {want or 'violation'} finding "
            "but found none"
        )
        return 1
    if findings:
        print(f"rpc-flow: {len(findings)} finding(s)")
        return 1
    print("rpc-flow: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
