"""Wire-protocol cross-checker for the msgpack RPC layer.

The RPC layer (:mod:`ray_tpu._private.rpc`) dispatches untyped
``[msgid, kind, method, payload]`` frames by *string* method name, and the
payloads are ad-hoc msgpack dicts — a method-name typo or a renamed payload
key fails at runtime (or worse, silently returns ``None`` from ``p.get``).
This pass makes the wire protocol checkable at lint time:

1. **Method inventory** — every literal method string at a
   ``call``/``call_nowait``/``call_cb``/``push``/``push_nowait`` call site —
   plus the blob-sidecar sends ``blob_push_nowait``/``call_with_blob``/
   ``call_into`` — is cross-checked against every handler registration
   (``Server.register``/``register_sync``/``register_blob``,
   ``@server.handler(...)``, literal
   ``handlers={...}`` dicts passed to ``rpc.connect``/``Connection``, and
   ``_handlers["X"] = fn`` / ``_handlers.setdefault("X", fn)``). Call sites
   naming a method no server registers are errors; registered handlers no
   client ever calls are reported as orphans.
2. **Payload-key drift** — for the message types declared in
   :mod:`ray_tpu._private.wire`, producer payload dict literals must carry
   every required key and nothing undeclared, and consumer handler bodies
   (``p["k"]`` / ``p.get("k")`` on the payload parameter) must only touch
   declared keys.
3. **Magic timeouts** — runtime code under ``_private/`` must not pass a
   numeric ``timeout=`` literal at a ``.call(...)`` site, nor a numeric
   literal of >= 10 s to ``asyncio.wait_for`` (that magnitude is a deadline
   *budget*, not a cleanup grace wait); budgets come from
   ``common.config`` (the ``rpc_*_timeout_s`` knobs) so they are tunable,
   greppable, and consistent with the resilience layer's deadline
   propagation. Under ``serve/_private/`` the same rule additionally covers
   numeric ``timeout_s=`` keyword literals (the serving stack's request
   budgets — ``common.config``'s ``serve_*`` knobs own those). Tests,
   devtools, and examples may use literals.

4. **Trace declaration** — every message type declared in
   :mod:`ray_tpu._private.wire` must state whether its request frames
   carry the sixth-slot trace context (``trace=True``/``trace=False``):
   an undeclared schema (``trace=None``) means nobody decided whether
   the hop joins the distributed trace, and the docs table can't say.

Non-literal method names (e.g. the dashboard's generic proxy
``conn.call(method, ...)``) are outside the static horizon and skipped.
Suppression: ``# aio-lint: disable=<rule>`` with rules
``unknown-rpc-method``, ``orphan-rpc-handler``, ``payload-key-drift``,
``rpc-magic-timeout``, ``wire-trace-undeclared``, ``wire-native-drift``.

Run: ``python -m ray_tpu.devtools.rpc_check [--markdown] [paths]``.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.aio_lint import (
    Finding,
    _suppressions,
    iter_py_files,
    _default_root,
)

RULE_UNKNOWN = "unknown-rpc-method"
RULE_ORPHAN = "orphan-rpc-handler"
RULE_DRIFT = "payload-key-drift"
RULE_TIMEOUT = "rpc-magic-timeout"
RULE_TRACE = "wire-trace-undeclared"
RULE_NATIVE = "wire-native-drift"

_CALL_METHODS = {
    "call",
    "call_nowait",
    "call_cb",
    "push",
    "push_nowait",
    # Blob-sidecar sends (rpc.py kinds 4/5): same (method, payload, ...)
    # shape, so method-name and payload-key checking apply unchanged.
    "blob_push_nowait",
    "call_with_blob",
    "call_into",
}
_REGISTER_METHODS = {"register", "register_sync", "handler", "register_blob"}
# asyncio.wait_for literals at or above this many seconds are deadline
# *budgets* (drain windows, fallback gets, spawn waits) and must come from
# config; shorter literals are bounded cleanup/grace waits and stay inline.
_WAIT_FOR_BUDGET_S = 10.0


@dataclass
class CallSite:
    method: str
    path: str
    line: int
    # Literal payload keys when the payload is a dict display with constant
    # keys; None when the payload is dynamic (or **expanded).
    payload_keys: Optional[Set[str]] = None
    via: str = "call"
    # Numeric timeout literal passed at the call site (timeout= kwarg or the
    # third positional argument of .call), if any.
    timeout_literal: Optional[float] = None


@dataclass
class Registration:
    method: str
    path: str
    line: int
    handler_name: Optional[str] = None  # simple function/method name if known
    kind: str = "register"


@dataclass
class Inventory:
    calls: List[CallSite] = field(default_factory=list)
    regs: List[Registration] = field(default_factory=list)
    # (path, handler_name) -> payload keys the handler body touches.
    handler_keys: Dict[Tuple[str, str], Set[str]] = field(default_factory=dict)
    # Every other string literal in the tree, for lenient orphan detection:
    # state/dashboard wrappers pass method names through one indirection
    # (``_call_gcs("ListActors")``), so "no other literal mentions this
    # method" is the actual dead-handler signal.
    str_literals: Set[str] = field(default_factory=set)
    # asyncio.wait_for(..., <numeric literal>) sites: (path, line, seconds).
    wait_for_literals: List[Tuple[str, int, float]] = field(default_factory=list)
    # Any-call numeric timeout_s= keyword literals: (path, line, seconds).
    # Checked only under serve/_private (the serving stack's budget kwarg).
    timeout_s_literals: List[Tuple[str, int, float]] = field(default_factory=list)


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_num(node: ast.AST) -> Optional[float]:
    """Numeric literal (incl. unary minus), or None. Booleans excluded."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        node = node.operand
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, (int, float))
        and not isinstance(node.value, bool)
    ):
        return float(node.value)
    return None


def _payload_keys(node: Optional[ast.AST]) -> Optional[Set[str]]:
    """Keys of a dict-display payload, or None when not fully literal."""
    if not isinstance(node, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in node.keys:
        if k is None:  # **spread
            return None
        s = _const_str(k)
        if s is None:
            return None
        keys.add(s)
    return keys


def _fn_simple_name(node: ast.AST) -> Optional[str]:
    """``self._foo`` / ``foo`` -> the trailing identifier."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _FileScanner(ast.NodeVisitor):
    def __init__(self, path: str, inv: Inventory):
        self.path = path
        self.inv = inv
        self._fn_stack: List[ast.AST] = []

    # -- handler payload-key usage ------------------------------------------

    def _scan_handler_body(self, fn) -> None:
        """Record ``p["k"]``/``p.get("k")`` key usage for handler-shaped
        functions ``(conn, p)`` / ``(self, conn, p)``."""
        args = fn.args.args
        if not args:
            return
        pname = args[-1].arg
        if pname == "size" and len(args) >= 2:
            # Blob sink factory shape ``(conn, p, size)`` (register_blob):
            # the payload is the second-to-last parameter.
            pname = args[-2].arg
        if pname in ("self", "conn"):
            return
        keys: Set[str] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == pname
            ):
                s = _const_str(node.slice)
                if s is not None:
                    keys.add(s)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == pname
                and node.args
            ):
                s = _const_str(node.args[0])
                if s is not None:
                    keys.add(s)
        if keys:
            self.inv.handler_keys[(self.path, fn.name)] = keys

    def visit_FunctionDef(self, node) -> None:
        self._scan_handler_body(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._scan_handler_body(node)
        self.generic_visit(node)

    # -- call sites and registrations ---------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        if attr in _CALL_METHODS and node.args:
            method = _const_str(node.args[0])
            if method is not None:
                payload = node.args[1] if len(node.args) > 1 else None
                timeout_literal = None
                if attr == "call" and len(node.args) > 2:
                    timeout_literal = _const_num(node.args[2])
                for kw in node.keywords:
                    if kw.arg == "payload":
                        payload = kw.value
                    elif kw.arg == "timeout":
                        timeout_literal = _const_num(kw.value)
                self.inv.calls.append(
                    CallSite(
                        method,
                        self.path,
                        node.lineno,
                        _payload_keys(payload),
                        via=attr,
                        timeout_literal=timeout_literal,
                    )
                )
        elif attr in _REGISTER_METHODS and node.args:
            method = _const_str(node.args[0])
            if method is not None:
                handler = (
                    _fn_simple_name(node.args[1]) if len(node.args) > 1 else None
                )
                self.inv.regs.append(
                    Registration(method, self.path, node.lineno, handler, attr)
                )
        elif (
            attr == "wait_for"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "asyncio"
        ):
            t = None
            if len(node.args) > 1:
                t = _const_num(node.args[1])
            for kw in node.keywords:
                if kw.arg == "timeout":
                    t = _const_num(kw.value)
            if t is not None:
                self.inv.wait_for_literals.append((self.path, node.lineno, t))
        elif attr == "setdefault" and len(node.args) == 2:
            # GcsClient-style: conn._handlers.setdefault("Pub", self._on_pub)
            if self._targets_handlers_dict(fn.value):
                method = _const_str(node.args[0])
                if method is not None:
                    self.inv.regs.append(
                        Registration(
                            method,
                            self.path,
                            node.lineno,
                            _fn_simple_name(node.args[1]),
                            "setdefault",
                        )
                    )
        for kw in node.keywords:
            if kw.arg == "timeout_s":
                t = _const_num(kw.value)
                if t is not None:
                    self.inv.timeout_s_literals.append(
                        (self.path, node.lineno, t)
                    )
        # Literal handlers= dicts passed to rpc.connect()/Connection().
        for kw in node.keywords:
            if kw.arg in ("handlers", "sync_handlers") and isinstance(
                kw.value, ast.Dict
            ):
                for k, v in zip(kw.value.keys, kw.value.values):
                    s = _const_str(k) if k is not None else None
                    if s is not None:
                        self.inv.regs.append(
                            Registration(
                                s, self.path, k.lineno, _fn_simple_name(v), kw.arg
                            )
                        )
        self.generic_visit(node)

    def _targets_handlers_dict(self, node: ast.AST) -> bool:
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        return name is not None and "handlers" in name

    def visit_Assign(self, node: ast.Assign) -> None:
        # _handlers["Name"] = fn
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Subscript)
                and self._targets_handlers_dict(tgt.value)
            ):
                method = _const_str(tgt.slice)
                if method is not None:
                    self.inv.regs.append(
                        Registration(
                            method,
                            self.path,
                            node.lineno,
                            _fn_simple_name(node.value),
                            "subscript",
                        )
                    )
        self.generic_visit(node)


def _collect_files(paths: List[str]) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(iter_py_files(path))
        else:
            files.append(path)
    return files


def _scan_tree(path: str, tree: ast.Module) -> Inventory:
    """Per-file Inventory fragment (scanner pass + stray string literals)."""
    frag = Inventory()
    _FileScanner(path, frag).visit(tree)
    reg_lines = {(r.path, r.line) for r in frag.regs}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and (path, node.lineno) not in reg_lines
        ):
            frag.str_literals.add(node.value)
    return frag


# Per-file parse+scan cache shared by every pass that needs the wire
# Inventory (rpc_check, rpc_flow, exc_flow): the unified lint gate runs
# them in one process, and re-parsing the tree three times is the
# difference between fitting the 120 s budget and not.  Keyed by
# (mtime_ns, size) so an edited file re-scans; holds (tree, fragment).
_FILE_CACHE: Dict[
    str, Tuple[int, int, Optional[ast.Module], Optional[Inventory]]
] = {}


def _scan_file(path: str) -> Tuple[Optional[ast.Module], Optional[Inventory]]:
    try:
        st = os.stat(path)
    except OSError:
        return None, None
    sig = (st.st_mtime_ns, st.st_size)
    ent = _FILE_CACHE.get(path)
    if ent is not None and (ent[0], ent[1]) == sig:
        return ent[2], ent[3]
    try:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree: Optional[ast.Module] = ast.parse(src, filename=path)
    except (OSError, SyntaxError):
        tree = None
    frag = _scan_tree(path, tree) if tree is not None else None
    _FILE_CACHE[path] = (sig[0], sig[1], tree, frag)
    return tree, frag


def cached_tree(path: str) -> Optional[ast.Module]:
    """Parsed AST for a file, via the shared per-file cache."""
    return _scan_file(path)[0]


def _merge_inventories(
    fragments: List[Inventory],
    extra_sources: Optional[List[Tuple[str, str]]] = None,
) -> Inventory:
    """Merge fragments (plus ad-hoc virtual sources, e.g. mutation
    overlays) into one fresh Inventory — cached fragments are never
    mutated."""
    import textwrap as _textwrap

    inv = Inventory()
    frags = list(fragments)
    for vpath, vsrc in extra_sources or ():
        try:
            vtree = ast.parse(_textwrap.dedent(vsrc), filename=vpath)
        except SyntaxError:
            continue
        frags.append(_scan_tree(vpath, vtree))
    for frag in frags:
        inv.calls.extend(frag.calls)
        inv.regs.extend(frag.regs)
        inv.handler_keys.update(frag.handler_keys)
        inv.str_literals |= frag.str_literals
        inv.wait_for_literals.extend(frag.wait_for_literals)
        inv.timeout_s_literals.extend(frag.timeout_s_literals)
    return inv


def cached_inventory(paths: List[str]) -> Inventory:
    """Tree-wide Inventory assembled from the per-file cache."""
    frags = []
    for f in _collect_files(paths):
        frag = _scan_file(f)[1]
        if frag is not None:
            frags.append(frag)
    return _merge_inventories(frags)


def build_inventory(paths: List[str]) -> Inventory:
    return cached_inventory(paths)


def _rpc_module_path() -> str:
    from ray_tpu._private import rpc

    return os.path.abspath(rpc.__file__)


def check(
    paths: Optional[List[str]] = None, apply_suppressions: bool = True
) -> List[Finding]:
    paths = paths or [_default_root()]
    inv = build_inventory(paths)
    rpc_path = _rpc_module_path()

    registered = {r.method for r in inv.regs}
    called: Dict[str, List[CallSite]] = {}
    for c in inv.calls:
        # The rpc module's own wrappers (call() delegating to call_nowait())
        # pass variables, never literals, but keep the guard explicit.
        if os.path.abspath(c.path) == rpc_path:
            continue
        called.setdefault(c.method, []).append(c)

    findings: List[Finding] = []

    for method, sites in sorted(called.items()):
        if method not in registered:
            for c in sites:
                findings.append(
                    Finding(
                        c.path,
                        c.line,
                        0,
                        RULE_UNKNOWN,
                        f"RPC {c.via}({method!r}) has no registered handler "
                        "anywhere in the tree — typo or dead protocol?",
                    )
                )

    seen_reg: Set[str] = set()
    for r in sorted(inv.regs, key=lambda r: (r.path, r.line)):
        if r.method in called or r.method in seen_reg:
            continue
        if r.method in inv.str_literals:
            continue  # referenced through a wrapper indirection
        seen_reg.add(r.method)
        findings.append(
            Finding(
                r.path,
                r.line,
                0,
                RULE_ORPHAN,
                f"handler {r.method!r} is registered but no client call "
                "site names it (dead handler, or callers build the method "
                "name dynamically — suppress if so)",
            )
        )

    findings.extend(_check_payload_drift(inv))
    findings.extend(_check_magic_timeouts(inv, rpc_path))
    findings.extend(_check_trace_declared())
    findings.extend(_check_native_wire_drift())

    # Apply inline suppressions from the source files involved.
    if not apply_suppressions:
        return findings
    sup_cache: Dict[str, Dict[int, Set[str]]] = {}

    def suppressed(f: Finding) -> bool:
        if f.path not in sup_cache:
            try:
                with open(f.path, "r", encoding="utf-8") as fh:
                    sup_cache[f.path] = _suppressions(fh.read())
            except OSError:
                sup_cache[f.path] = {}
        for line in (f.line, f.line - 1):
            rules = sup_cache[f.path].get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return [f for f in findings if not suppressed(f)]


def _check_payload_drift(inv: Inventory) -> List[Finding]:
    from ray_tpu._private import wire

    findings: List[Finding] = []
    # Producer side: literal payload dicts at call sites.
    for c in inv.calls:
        schema = wire.SCHEMAS.get(c.method)
        if schema is None or c.payload_keys is None:
            continue
        missing = schema.required - c.payload_keys
        unknown = c.payload_keys - schema.required - schema.optional
        if missing:
            findings.append(
                Finding(
                    c.path,
                    c.line,
                    0,
                    RULE_DRIFT,
                    f"{c.method} payload is missing required key(s) "
                    f"{sorted(missing)} (wire.py schema)",
                )
            )
        if unknown:
            findings.append(
                Finding(
                    c.path,
                    c.line,
                    0,
                    RULE_DRIFT,
                    f"{c.method} payload carries key(s) {sorted(unknown)} "
                    "not declared in wire.py — field-name drift, or extend "
                    "the schema",
                )
            )
    # Consumer side: key usage inside the registered handler bodies.
    for r in inv.regs:
        schema = wire.SCHEMAS.get(r.method)
        if schema is None or r.handler_name is None:
            continue
        keys = inv.handler_keys.get((r.path, r.handler_name))
        if not keys:
            continue
        unknown = keys - schema.required - schema.optional
        if unknown:
            findings.append(
                Finding(
                    r.path,
                    r.line,
                    0,
                    RULE_DRIFT,
                    f"handler for {r.method} ({r.handler_name}) reads "
                    f"payload key(s) {sorted(unknown)} not declared in "
                    "wire.py — producer/consumer drift",
                )
            )
    return findings


def _fastpath_cc_path() -> str:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(_rpc_module_path()))
    )
    return os.path.join(repo_root, "src", "fastpath.cc")


def _check_native_wire_drift(cc_path: Optional[str] = None) -> List[Finding]:
    """Every natively packed schema (wire.NATIVE_WIRE_SCHEMAS) must have a
    matching ``// NATIVE_WIRE_SCHEMA: <Method> v<N> fields=...`` marker in
    src/fastpath.cc with the SAME version and field list. A Python field
    change without a C-side version bump would ship two processes that
    pack the same method differently while both believe they match — the
    runtime gate (schema_versions) only protects processes that agree on
    wire.py, so the drift must die in lint."""
    import re

    from ray_tpu._private import wire

    cc = cc_path or _fastpath_cc_path()
    if not os.path.exists(cc):
        return []  # installed distribution without the C sources
    findings: List[Finding] = []
    pat = re.compile(
        r"//\s*NATIVE_WIRE_SCHEMA:\s*(\w+)\s+v(\d+)\s+fields=([\w,]*)"
    )
    markers: Dict[str, Tuple[int, Tuple[str, ...], int]] = {}
    with open(cc, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            m = pat.search(line)
            if m:
                fields = tuple(sorted(f for f in m.group(3).split(",") if f))
                markers[m.group(1)] = (int(m.group(2)), fields, lineno)
    for method, (ver, fields) in sorted(wire.NATIVE_WIRE_SCHEMAS.items()):
        marker = markers.pop(method, None)
        if marker is None:
            findings.append(
                Finding(
                    cc,
                    1,
                    0,
                    RULE_NATIVE,
                    f"natively packed schema {method} (wire.py v{ver}) has "
                    "no NATIVE_WIRE_SCHEMA marker in fastpath.cc — add the "
                    "marker (and the kWireSchemas entry) or remove the "
                    "method from NATIVE_WIRE_SCHEMAS",
                )
            )
            continue
        cc_ver, cc_fields, lineno = marker
        if tuple(sorted(fields)) != cc_fields:
            findings.append(
                Finding(
                    cc,
                    lineno,
                    0,
                    RULE_NATIVE,
                    f"{method} field list drifted: wire.py declares "
                    f"{sorted(fields)} but the fastpath.cc marker has "
                    f"{list(cc_fields)} — update the marker AND bump the "
                    "schema version on both sides",
                )
            )
        elif cc_ver != ver:
            findings.append(
                Finding(
                    cc,
                    lineno,
                    0,
                    RULE_NATIVE,
                    f"{method} schema version skew: wire.py v{ver} vs "
                    f"fastpath.cc v{cc_ver} — bump both sides together "
                    "(the runtime gate would silently fall back to the "
                    "Python packer on every process)",
                )
            )
    for method, (cc_ver, _fields, lineno) in sorted(markers.items()):
        findings.append(
            Finding(
                cc,
                lineno,
                0,
                RULE_NATIVE,
                f"fastpath.cc declares native schema {method} v{cc_ver} "
                "that wire.py does not list in NATIVE_WIRE_SCHEMAS — "
                "stale marker, or the registry entry was dropped without "
                "the C side",
            )
        )
    return findings


def _check_magic_timeouts(inv: Inventory, rpc_path: str) -> List[Finding]:
    """Numeric ``timeout=`` literals at RPC call sites in runtime code.

    Scope is ``_private/`` only (excluding rpc.py itself, whose defaults ARE
    the mechanism): that is the production control/data plane where a magic
    number silently diverges from the config budgets. Tests, devtools, and
    examples legitimately pin tiny timeouts.
    """
    findings: List[Finding] = []

    def _in_scope(path: str) -> bool:
        p = os.path.abspath(path)
        return "_private" in p.split(os.sep) and p != rpc_path

    for c in inv.calls:
        if c.timeout_literal is None or not _in_scope(c.path):
            continue
        findings.append(
            Finding(
                c.path,
                c.line,
                0,
                RULE_TIMEOUT,
                f"{c.via}({c.method!r}, ..., timeout={c.timeout_literal:g}) "
                "uses a numeric literal — take the budget from "
                "common.config (rpc_*_timeout_s) so it is tunable and "
                "consistent with deadline propagation",
            )
        )
    for path, line, t in inv.wait_for_literals:
        if t < _WAIT_FOR_BUDGET_S or not _in_scope(path):
            continue
        findings.append(
            Finding(
                path,
                line,
                0,
                RULE_TIMEOUT,
                f"asyncio.wait_for(..., {t:g}) uses a numeric literal of "
                f">= {_WAIT_FOR_BUDGET_S:g}s — that is a deadline budget; "
                "take it from common.config so it is tunable (short "
                "cleanup/grace waits are exempt)",
            )
        )

    def _serve_scope(path: str) -> bool:
        parts = os.path.abspath(path).split(os.sep)
        return "serve" in parts and "_private" in parts

    for path, line, t in inv.timeout_s_literals:
        if not _serve_scope(path):
            continue
        findings.append(
            Finding(
                path,
                line,
                0,
                RULE_TIMEOUT,
                f"timeout_s={t:g} uses a numeric literal in the serving "
                "stack — request budgets come from common.config (the "
                "serve_* knobs) so admission control and deadline "
                "propagation stay consistent",
            )
        )
    return findings


def _check_trace_declared() -> List[Finding]:
    """Every wire schema must declare trace propagation (trace=True/False).

    ``trace=None`` means nobody decided whether this method's request
    frames carry the sixth-slot trace context — new schemas must take a
    position so the committed protocol table stays complete.
    """
    from ray_tpu._private import wire

    findings: List[Finding] = []
    wire_path = os.path.abspath(wire.__file__)
    try:
        with open(wire_path, "r", encoding="utf-8") as fh:
            src_lines = fh.read().splitlines()
    except OSError:
        src_lines = []

    def _line_of(method: str) -> int:
        needle = f'"{method}":'
        for i, line in enumerate(src_lines, 1):
            if needle in line:
                return i
        return 1

    for method in sorted(wire.SCHEMAS):
        if wire.SCHEMAS[method].trace is None:
            findings.append(
                Finding(
                    wire_path,
                    _line_of(method),
                    0,
                    RULE_TRACE,
                    f"wire schema {method!r} does not declare trace "
                    "propagation — set trace=True (request frames carry "
                    "the trace-context slot) or trace=False (control/"
                    "background traffic, or a kind-4 blob request whose "
                    "fifth slot is the byte length)",
                )
            )
    return findings


def markdown_table(paths: Optional[List[str]] = None) -> str:
    """The versioned wire-protocol inventory committed to docs/."""
    from ray_tpu._private import wire
    from ray_tpu.devtools import rpc_flow  # deferred: rpc_flow imports us

    paths = paths or [_default_root()]
    inv = build_inventory(paths)
    flow = rpc_flow.build(paths)
    root = os.path.dirname(_default_root())

    def rel(p: str) -> str:
        return os.path.relpath(p, root)

    by_method: Dict[str, Dict[str, List]] = {}
    for r in inv.regs:
        by_method.setdefault(r.method, {"regs": [], "calls": []})["regs"].append(r)
    for c in inv.calls:
        if os.path.abspath(c.path) == _rpc_module_path():
            continue
        by_method.setdefault(c.method, {"regs": [], "calls": []})["calls"].append(c)

    lines = [
        "# RPC wire-protocol inventory",
        "",
        "Generated by `python -m ray_tpu.devtools.rpc_check --markdown`.",
        "Frames are msgpack `[msgid, kind, method, payload]`; requests may",
        "carry a fifth element, the remaining deadline budget (TTL) in",
        "seconds — the receiver reconstructs an absolute deadline from it,",
        "sheds already-expired calls, and hands handlers the remaining",
        "budget to pass downstream (see `ray_tpu/_private/rpc.py`). Blob",
        "frames (kinds 4 and 5) put the sidecar byte length in the fifth",
        "slot instead and stream that many raw bytes after the control",
        "frame — the data plane's zero-copy path. `LeaseBatch` (kind 3,",
        "schema in `wire.py`) is a transport envelope, not a handler",
        "method: `Connection.call_batched` coalesces every request bound",
        "for one peer in the same event-loop tick into one push frame whose",
        "payload is `{entries: [[msgid, method, payload, ttl?, trace_ctx?],",
        "...]}`; the receiving read loop unpacks it and dispatches each",
        "entry exactly as if it had arrived as its own request frame, so",
        "per-entry msgids keep replies, cancellation, retry dedup, and",
        "chaos fault injection addressed to individual requests (see",
        "docs/scheduling.md \"Batched lease frames\"). Request frames may also",
        "carry a sixth element, the active trace context as",
        "`[trace_id, span_id]` — the receiver re-establishes it as the",
        "ambient span parent for the handler so runtime spans recorded on",
        "the far side join the caller's trace (see `docs/observability.md`,",
        "\"Distributed tracing\"). Schemas",
        "for the starred methods live in `ray_tpu/_private/wire.py`; the",
        "lint gate fails on drift. Retry is the method's wire retry class",
        "consumed by `rpc.RetryableConnection`: `safe` = idempotent, retried",
        "freely; `dedup(key)` = retried only with the msgid-stable token;",
        "`none` = never retried. Blob is the sidecar direction: `push` =",
        "one-way kind-4 blob into a registered sink, `request` = kind-4",
        "blob the handler reads as `p[\"data\"]`, `reply` = the handler",
        "returns `rpc.Blob` and the caller's sink receives the bytes.",
        "Trace is whether request frames for the method carry the",
        "trace-context slot: ✓ = propagates (a traced caller's context",
        "rides the frame), — = control/background traffic that never",
        "joins a request trace (kind-4 blob requests cannot carry it).",
        "Deadline is the default budget the method's frames carry, derived",
        "from its call sites by `rpc_flow.deadline_sources`: `pinned (...)`",
        "= every site sends an explicit timeout/deadline (the listed",
        "sources); `ambient` = sites fold the caller's remaining budget",
        "when one is set (`_effective_deadline`), so the TTL slot is",
        "populated exactly when the caller is itself deadlined; `mixed",
        "(...)` = some sites pin a budget, others fold ambient; `never` =",
        "no site ever sends a TTL (fire-and-forget or callback vias).",
        "Errors is the schema's `errors=` declaration: the typed errors the",
        "handler can let escape as a typed error reply (reconstructed",
        "caller-side by `rpc._typed_error`; `exc_flow`'s",
        "error-wire-undeclared rule cross-checks handlers against it).",
        "Ambient machinery errors — ConnectionLost, deadline shedding — are",
        "channel facts, not per-method declarations.",
        "",
        "| Method | Schema | Retry | Blob | Trace | Deadline | Errors | Servers (handler) | Client call sites | Payload keys |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for method in sorted(by_method):
        info = by_method[method]
        schema = wire.SCHEMAS.get(method)
        servers = ", ".join(
            sorted(
                {
                    f"`{os.path.basename(r.path)}`:{r.handler_name or '?'}"
                    for r in info["regs"]
                }
            )
        ) or "—"
        nsites = len(info["calls"])
        files = sorted({os.path.basename(c.path) for c in info["calls"]})
        callers = f"{nsites} site(s) in {', '.join(files)}" if nsites else "—"
        if schema is not None:
            keys = ", ".join(
                sorted(schema.required)
                + [f"{k}?" for k in sorted(schema.optional)]
            ) or "(empty)"
            star = "★"
            if schema.retry == wire.RETRY_DEDUP:
                retry = f"dedup({schema.dedup_key})"
            else:
                retry = schema.retry
            blob = schema.blob or "—"
            trace = "✓" if schema.trace else "—"
            errors = ", ".join(f"`{e}`" for e in schema.errors) or "—"
        else:
            keys, star, retry, blob, trace, errors = "", "", "", "", "", ""
        maybe, guaranteed, srcs = rpc_flow.deadline_sources(flow, method)
        shown = ", ".join(f"`{s}`" for s in srcs[:3])
        if len(srcs) > 3:
            shown += f" +{len(srcs) - 3}"
        if not info["calls"]:
            deadline = "—"
        elif guaranteed:
            deadline = f"pinned ({shown})"
        elif maybe and srcs:
            deadline = f"mixed ({shown})"
        elif maybe:
            deadline = "ambient"
        else:
            deadline = "never"
        lines.append(
            f"| `{method}` | {star} | {retry} | {blob} | {trace} | "
            f"{deadline} | {errors} | {servers} | {callers} | {keys} |"
        )
    lines.append("")
    lines.append(
        f"{len(by_method)} methods; ★ = schema-checked "
        f"({len(wire.SCHEMAS)} declared)."
    )
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.rpc_check",
        description="RPC wire cross-checker (methods + payload schemas)",
    )
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument(
        "--markdown",
        action="store_true",
        help="emit the method-inventory markdown table instead of checking",
    )
    args = parser.parse_args(argv)
    paths = args.paths or None
    if args.markdown:
        print(markdown_table(paths))
        return 0
    findings = check(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"rpc-check: {len(findings)} finding(s)")
        return 1
    print("rpc-check: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
