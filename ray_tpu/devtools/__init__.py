"""Repo-specific static analysis for the asyncio control plane.

Five passes (run the static ones via ``python -m ray_tpu.devtools.lint``):

- :mod:`ray_tpu.devtools.aio_lint` — AST linter for asyncio hazards
  (blocking calls in ``async def``, raw ``create_task`` outside
  ``rpc.spawn()``, unawaited coroutines, await-interleaving TOCTOU).
- :mod:`ray_tpu.devtools.rpc_check` — wire-protocol cross-checker for the
  msgpack RPC layer (call-site method names vs. handler registries, payload
  key drift against the :mod:`ray_tpu._private.wire` schema registry).
- :mod:`ray_tpu.devtools.lifecycle` — paired-resource dataflow pass over an
  acquire/release registry (pull quota, lease pool, store pins, object
  holds, grant ledger, resource ledger): leaks on exception / early return,
  releases not protected by ``finally`` across ``await`` cancellation
  points, double release.
- :mod:`ray_tpu.devtools.protocols` — protocol FSM checker: the actor,
  placement-group, node, and lease-ledger state machines as data; every
  static ``.state = X`` assignment is verified as a legal edge, the spec is
  cross-checked against the chaos convergence invariants, and
  ``docs/protocols.md`` is generated from it (``make protocols``).
- :mod:`ray_tpu._private.aiocheck` — runtime interleaving probe enabled by
  ``RAY_TPU_AIOCHECK=1``; validates the static pass dynamically in tests.

Every static rule supports inline suppression on the flagged line or the
line directly above it: ``# aio-lint: disable=<rule>[,...]`` for
aio_lint/rpc_check, ``# lifecycle: disable=<rule>`` and
``# protocol: disable=<rule>`` for the lifecycle/protocol passes. Rule IDs
and examples: ``docs/static_analysis.md``.
"""
