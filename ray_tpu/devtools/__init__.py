"""Repo-specific static analysis for the asyncio control plane.

Three passes (run all of them via ``python -m ray_tpu.devtools.lint``):

- :mod:`ray_tpu.devtools.aio_lint` — AST linter for asyncio hazards
  (blocking calls in ``async def``, raw ``create_task`` outside
  ``rpc.spawn()``, unawaited coroutines, await-interleaving TOCTOU).
- :mod:`ray_tpu.devtools.rpc_check` — wire-protocol cross-checker for the
  msgpack RPC layer (call-site method names vs. handler registries, payload
  key drift against the :mod:`ray_tpu._private.wire` schema registry).
- :mod:`ray_tpu._private.aiocheck` — runtime interleaving probe enabled by
  ``RAY_TPU_AIOCHECK=1``; validates the static pass dynamically in tests.

Every static rule supports inline suppression with
``# aio-lint: disable=<rule>[,<rule>...]`` on the flagged line or the line
directly above it.
"""
