"""ray_tpu.util: user-facing utilities (reference: python/ray/util)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)


def list_named_actors(namespace=None, all_namespaces: bool = False):
    """Live named actors (reference: ray.util.list_named_actors)."""
    from ray_tpu._private import worker as worker_mod

    payload = {} if all_namespaces else {
        "namespace": namespace or worker_mod.global_worker.namespace
    }
    reply = worker_mod.global_worker.run_async(
        worker_mod._core().gcs.call("ListNamedActors", payload)
    )
    return reply["names"]


__all__ = [
    "ActorPool",
    "list_named_actors",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
