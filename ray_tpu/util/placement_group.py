"""Placement groups: gang resource reservations across nodes.

Analog of python/ray/util/placement_group.py: bundles are reserved via the
GCS's two-phase commit across raylets (reference:
gcs_placement_group_scheduler.cc); tasks/actors target a bundle via
PlacementGroupSchedulingStrategy. On TPU pods, a PG with one bundle per host
carrying the ``TPU`` resource is the gang primitive under the Train layer.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.common import PlacementGroupError, PlacementGroupSpec, ResourceSet
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a placement group."""

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id_hex = pg_id
        self.bundles = bundles
        self.strategy = strategy

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved (2PC committed)."""
        core = worker_mod._core()
        try:
            reply = worker_mod.global_worker.run_async(
                core.gcs.call(
                    "WaitPlacementGroupReady",
                    {"pg_id": self.id_hex, "timeout": timeout},
                    timeout=None if timeout is None else timeout + 5,
                ),
                timeout=None if timeout is None else timeout + 10,
            )
        except Exception as e:
            raise PlacementGroupError(str(e)) from e
        return reply.get("state") == "CREATED"

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.ready(timeout)

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __repr__(self):
        return f"PlacementGroup({self.id_hex[:12]}, {self.strategy}, {len(self.bundles)} bundles)"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    """Create a placement group asynchronously; call .ready() to await it."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError("each bundle must reserve at least one resource")
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resource amounts must be non-negative")
    core = worker_mod._core()
    pg_id = PlacementGroupID.from_random().hex()
    pg = PlacementGroup(pg_id, bundles, strategy)
    spec = PlacementGroupSpec(
        pg_id=pg_id,
        bundles=[ResourceSet(b).to_units() for b in bundles],
        strategy=strategy,
        name=name,
        job_id=core.job_id,
    )
    worker_mod.global_worker.run_async(
        core.gcs.call(
            "CreatePlacementGroup", {"spec": spec.to_wire(), "wait_ready": False}
        )
    )
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    core = worker_mod._core()
    worker_mod.global_worker.run_async(
        core.gcs.call("RemovePlacementGroup", {"pg_id": pg.id_hex})
    )


def placement_group_table(pg: Optional[PlacementGroup] = None) -> List[dict]:
    """State of every placement group, or of just ``pg`` when given (a
    targeted GetPlacementGroup instead of listing the whole table)."""
    core = worker_mod._core()
    if pg is not None:
        reply = worker_mod.global_worker.run_async(
            core.gcs.call("GetPlacementGroup", {"pg_id": pg.id_hex})
        )
        return [reply["pg"]] if reply.get("pg") else []
    return worker_mod.global_worker.run_async(core.gcs.call("ListPlacementGroups"))[
        "pgs"
    ]
