"""Distributed FIFO queue backed by an actor.

Analog of python/ray/util/queue.py: Queue with put/get (blocking with
timeout), qsize/empty/full, shared across processes by passing the handle.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu
from ray_tpu._private.common import RayTpuError


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            if timeout == 0:
                self._q.put_nowait(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except (asyncio.TimeoutError, asyncio.QueueFull):
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout == 0:
                return True, self._q.get_nowait()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except (asyncio.TimeoutError, asyncio.QueueEmpty):
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    def __init__(
        self,
        maxsize: int = 0,
        *,
        actor_options: Optional[dict] = None,
        _handle=None,
    ):
        if _handle is not None:
            self._actor = _handle
            return
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        opts.setdefault("max_concurrency", 64)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        t = (0 if not block else timeout)
        ok = ray_tpu.get(self._actor.put.remote(item, t))
        if not ok:
            raise Full("queue is full")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        t = (0 if not block else timeout)
        ok, item = ray_tpu.get(self._actor.get.remote(t))
        if not ok:
            raise Empty("queue is empty")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        maxsize = ray_tpu.get(self._actor.maxsize.remote())
        return maxsize > 0 and self.qsize() >= maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)

    def __reduce__(self):
        return (_rebuild_queue, (self._actor,))


def _rebuild_queue(handle) -> "Queue":
    return Queue(_handle=handle)
