"""State API: list/summarize live cluster state.

Analog of python/ray/util/state/api.py (list_actors/tasks/objects/nodes/
workers/placement_groups/jobs at :788-1112, summarize_* at :1382-1450), fed
by the GCS (actors/nodes/PGs/jobs/task events) and per-raylet detail queries
(workers/objects — the reference's GetTasksInfo/GetObjectsInfo path).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod


def _call_gcs(method: str, payload: Optional[dict] = None) -> dict:
    core = worker_mod._core()
    return worker_mod.global_worker.run_async(core.gcs.call(method, payload or {}))


def _filter(rows: List[dict], filters) -> List[dict]:
    """filters: list of (key, op, value) with op in ("=", "!=")."""
    for key, op, value in filters or []:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def list_nodes(filters=None, limit: int = 10000) -> List[dict]:
    rows = _call_gcs("GetAllNodes")["nodes"]
    return _filter(rows, filters)[:limit]


def list_cluster_events(
    severity: Optional[str] = None,
    label: Optional[str] = None,
    limit: int = 1000,
) -> List[dict]:
    """Structured cluster events (reference: python/ray/_private/event/ +
    `ray list cluster-events`): node membership, actor failures/restarts,
    emitted by the GCS event logger and durably appended to
    <session>/logs/events/event_GCS.log."""
    return _call_gcs(
        "ListEvents",
        {"severity": severity, "label": label, "limit": limit},
    )["events"]


def list_actors(filters=None, limit: int = 10000) -> List[dict]:
    rows = _call_gcs("ListActors")["actors"]
    return _filter(rows, filters)[:limit]


def list_placement_groups(filters=None, limit: int = 10000) -> List[dict]:
    rows = _call_gcs("ListPlacementGroups")["pgs"]
    return _filter(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 10000) -> List[dict]:
    rows = _call_gcs("ListJobs")["jobs"]
    return _filter(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 10000, job_id: Optional[str] = None) -> List[dict]:
    """Latest state per task, derived from the task-event log."""
    events = _call_gcs("ListTaskEvents", {"job_id": job_id, "limit": 100000})["events"]
    latest: Dict[str, dict] = {}
    first_ts: Dict[str, float] = {}
    for e in events:
        if e.get("state") in ("PROFILE", "SPAN"):
            continue  # phase/trace records, not lifecycle states
        tid = e["task_id"]
        first_ts.setdefault(tid, e["time"])
        cur = latest.get(tid)
        if cur is None or e["time"] >= cur["time"]:
            latest[tid] = e
    rows = [
        {
            "task_id": tid,
            "name": e.get("name"),
            "state": e.get("state"),
            "job_id": e.get("job_id"),
            "worker_id": e.get("worker_id"),
            "node_id": e.get("node_id"),
            "start_time": first_ts[tid],
            "end_time": e["time"] if e.get("state") in ("FINISHED", "FAILED") else None,
            "error": e.get("error"),
        }
        for tid, e in latest.items()
    ]
    rows.sort(key=lambda r: r["start_time"])
    return _filter(rows, filters)[:limit]


def _each_raylet(payload: dict) -> List[dict]:
    core = worker_mod._core()

    async def _collect():
        out = []
        for n in (await core.gcs.call("GetAllNodes"))["nodes"]:
            if n["state"] != "ALIVE":
                continue
            try:
                conn = await core.connect_to(tuple(n["addr"]))
                out.append(await conn.call("GetNodeStats", payload))
            except Exception:
                pass
        return out

    return worker_mod.global_worker.run_async(_collect())


def list_logs(node_id: Optional[str] = None) -> Dict[str, List[str]]:
    """Log files captured per node (reference: ray.util.state.list_logs)."""
    core = worker_mod._core()

    async def _collect():
        out = {}
        for n in (await core.gcs.call("GetAllNodes"))["nodes"]:
            if n["state"] != "ALIVE":
                continue
            if node_id is not None and n["node_id"] != node_id:
                continue
            try:
                conn = await core.connect_to(tuple(n["addr"]))
                reply = await conn.call("ListLogs", {})
                out[n["node_id"]] = reply["files"]
            except Exception:
                pass
        return out

    return worker_mod.global_worker.run_async(_collect())


def get_log(
    node_id: Optional[str] = None,
    filename: Optional[str] = None,
    worker_id: Optional[str] = None,
    stream: str = "stderr",
    tail: int = 1000,
) -> List[str]:
    """Tail of a captured worker log (reference: ray.util.state.get_log,
    python/ray/util/state/api.py:1183). Identify the log by filename (from
    list_logs) or worker_id; with no node_id every node is asked."""
    core = worker_mod._core()

    async def _collect():
        payload = {
            "filename": filename,
            "worker_id": worker_id,
            "stream": stream,
            "tail": tail,
        }
        for n in (await core.gcs.call("GetAllNodes"))["nodes"]:
            if n["state"] != "ALIVE":
                continue
            if node_id is not None and n["node_id"] != node_id:
                continue
            try:
                conn = await core.connect_to(tuple(n["addr"]))
                reply = await conn.call("GetLog", payload)
            except Exception:
                continue
            if reply.get("found"):
                return reply["lines"]
        return []

    return worker_mod.global_worker.run_async(_collect())


def list_workers(filters=None, limit: int = 10000) -> List[dict]:
    rows: List[dict] = []
    for stats in _each_raylet({"include_workers": True}):
        rows.extend(stats.get("workers", []))
    return _filter(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 10000) -> List[dict]:
    rows: List[dict] = []
    for stats in _each_raylet({"include_objects": True}):
        rows.extend(stats.get("objects", []))
    return _filter(rows, filters)[:limit]


# -- summaries ----------------------------------------------------------------


def summarize_tasks(job_id: Optional[str] = None) -> Dict[str, Any]:
    per_name: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter
    )
    for t in list_tasks(job_id=job_id):
        per_name[t["name"] or "?"][t["state"]] += 1
    return {
        "summary": {
            name: dict(states) for name, states in sorted(per_name.items())
        },
        "total_tasks": sum(sum(c.values()) for c in per_name.values()),
    }


def summarize_actors() -> Dict[str, Any]:
    per_class: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter
    )
    for a in list_actors():
        per_class[a.get("name") or a.get("class_name") or "?"][a["state"]] += 1
    return {
        "summary": {cls: dict(states) for cls, states in sorted(per_class.items())},
        "total_actors": sum(sum(c.values()) for c in per_class.values()),
    }


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    total = sum(o["size"] for o in objs)
    return {
        "total_objects": len(objs),
        "total_size_bytes": total,
        "pinned": sum(1 for o in objs if o["pinned"]),
        "sealed": sum(1 for o in objs if o["sealed"]),
    }


# -- timeline (reference: ray.timeline, _private/state.py:922) ----------------


def list_spans(trace_id: Optional[str] = None, limit: int = 10000) -> List[dict]:
    """Tracing spans (reference: the OTel spans tracing_helper.py emits),
    task-level (kind submit|execute) and runtime-internal (lease, object,
    serve, data, collective kinds) alike. Each: {span_id, parent_span_id,
    trace_id, kind, name, task_id?, start, duration, ...attrs}. The
    trace_id filter and limit run GCS-side (ListSpans), so this never
    ships the whole span ring. Requires tracing to be on
    (RAY_TPU_TASK_TRACE_SPANS=1 or RAY_TPU_TRACE_SAMPLE_RATE>0)."""
    events = _call_gcs("ListSpans", {"trace_id": trace_id, "limit": limit})[
        "spans"
    ]
    spans = []
    for e in events:
        row = dict(e)
        row.pop("state", None)
        row.setdefault("task_id", None)
        spans.append(row)
    return sorted(spans, key=lambda s: s.get("start") or 0)


def _span_timeline_events(spans: List[dict]) -> List[dict]:
    """Chrome X events for trace spans, with the trace linkage in args so
    chrome://tracing / Perfetto flows can be reconstructed."""
    out = []
    for e in spans:
        out.append(
            {
                "name": f"{e.get('name') or 'task'}::{e.get('kind')}",
                "cat": "span",
                "ph": "X",
                "ts": (e.get("start") or e.get("time") or 0.0) * 1e6,
                "dur": max(0.0, (e.get("duration") or 0.0) * 1e6),
                "pid": e.get("node_id", "node"),
                "tid": e.get("worker_id", "worker"),
                "args": {
                    "task_id": e.get("task_id"),
                    "span_id": e.get("span_id"),
                    "parent_span_id": e.get("parent_span_id"),
                    "trace_id": e.get("trace_id"),
                },
            }
        )
    return out


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-tracing events derived from the task-event log (one complete
    ("X") event per RUNNING->FINISHED/FAILED task) merged with the trace
    spans from the GCS span ring."""
    events = _call_gcs("ListTaskEvents", {"limit": 100000})["events"]
    spans: Dict[str, dict] = {}
    out: List[dict] = _span_timeline_events(
        _call_gcs("ListSpans", {"limit": 100000})["spans"]
    )
    for e in sorted(events, key=lambda x: x["time"]):
        tid = e["task_id"]
        if e["state"] == "PROFILE":
            # Worker-side phase spans (deserialize/execute/store): one X
            # event per phase, laid back-to-back from the recorded start
            # (reference: profile events in ray.timeline).
            ts = e.get("start", e["time"]) * 1e6
            for phase, dur_s in (e.get("phases") or {}).items():
                out.append(
                    {
                        "name": f"{e.get('name') or 'task'}::{phase}",
                        "cat": "profile",
                        "ph": "X",
                        "ts": ts,
                        "dur": max(0.0, dur_s * 1e6),
                        "pid": e.get("node_id", "node"),
                        "tid": e.get("worker_id", "worker"),
                        "args": {"task_id": tid},
                    }
                )
                ts += dur_s * 1e6
            continue
        if e["state"] == "RUNNING":
            spans[tid] = e
        elif e["state"] in ("FINISHED", "FAILED") and tid in spans:
            start = spans.pop(tid)
            out.append(
                {
                    "name": e.get("name") or "task",
                    "cat": "task",
                    "ph": "X",
                    "ts": start["time"] * 1e6,
                    "dur": max(0.0, (e["time"] - start["time"]) * 1e6),
                    "pid": e.get("node_id", "node"),
                    "tid": e.get("worker_id", "worker"),
                    "args": {"task_id": tid, "state": e["state"]},
                }
            )
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(out, f)
    return out


def critical_path(trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Walk a trace's span DAG and report the chain of spans that bounds
    its end-to-end latency, with per-segment *self time* (duration minus
    the on-path child's overlap) so the dominant segment is named rather
    than inferred from a timeline by eye.

    With no trace_id, the longest recorded trace (largest start->finish
    extent) is analyzed. Returns ``{trace_id, total_s, path, segments,
    dominant}`` — ``path`` in causal order, ``segments`` sorted by self
    time descending, ``dominant`` the name of the top segment."""
    spans = list_spans(trace_id=trace_id, limit=100000)
    if not spans:
        return {
            "trace_id": trace_id,
            "total_s": 0.0,
            "path": [],
            "segments": [],
            "dominant": None,
        }

    def _start(s: dict) -> float:
        return s.get("start") or 0.0

    def _end(s: dict) -> float:
        return _start(s) + (s.get("duration") or 0.0)

    by_trace: Dict[str, List[dict]] = collections.defaultdict(list)
    for s in spans:
        if s.get("trace_id"):
            by_trace[s["trace_id"]].append(s)
    if not by_trace:
        return {
            "trace_id": trace_id,
            "total_s": 0.0,
            "path": [],
            "segments": [],
            "dominant": None,
        }
    if trace_id is None:
        trace_id = max(
            by_trace,
            key=lambda t: max(_end(s) for s in by_trace[t])
            - min(_start(s) for s in by_trace[t]),
        )
    trace = by_trace[trace_id]
    ids = {s["span_id"]: s for s in trace if s.get("span_id")}
    children: Dict[str, List[dict]] = collections.defaultdict(list)
    for s in trace:
        parent = s.get("parent_span_id")
        if parent in ids and parent != s.get("span_id"):
            children[parent].append(s)
    roots = [s for s in trace if s.get("parent_span_id") not in ids]
    # The root whose subtree finishes last bounds the trace.
    root = max(roots, key=_end)

    path = [root]
    seen = {root.get("span_id")}
    cur = root
    while True:
        kids = [
            k for k in children.get(cur.get("span_id"), []) if k["span_id"] not in seen
        ]
        if not kids:
            break
        cur = max(kids, key=_end)  # the last-finishing child gates the parent
        seen.add(cur["span_id"])
        path.append(cur)

    total = max(_end(s) for s in path) - _start(root)
    segments = []
    for i, s in enumerate(path):
        dur = s.get("duration") or 0.0
        if i + 1 < len(path):
            child = path[i + 1]
            overlap = max(
                0.0, min(_end(s), _end(child)) - max(_start(s), _start(child))
            )
            self_s = max(0.0, dur - overlap)
        else:
            self_s = dur
        segments.append(
            {
                "name": s.get("name"),
                "kind": s.get("kind"),
                "span_id": s.get("span_id"),
                "duration_s": dur,
                "self_s": self_s,
            }
        )
    ranked = sorted(segments, key=lambda seg: seg["self_s"], reverse=True)
    return {
        "trace_id": trace_id,
        "total_s": total,
        "path": segments,
        "segments": ranked,
        "dominant": ranked[0]["name"] if ranked else None,
    }
