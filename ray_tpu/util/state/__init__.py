"""ray_tpu.util.state: cluster state inspection API (reference:
python/ray/util/state)."""

from ray_tpu.util.state.api import (
    list_actors,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    summarize_actors,
    summarize_objects,
    summarize_tasks,
    timeline,
)

__all__ = [
    "list_actors",
    "list_jobs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "summarize_actors",
    "summarize_objects",
    "summarize_tasks",
    "timeline",
]
