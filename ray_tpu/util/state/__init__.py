"""ray_tpu.util.state: cluster state inspection API (reference:
python/ray/util/state)."""

from ray_tpu.util.state.api import (
    critical_path,
    get_log,
    list_actors,
    list_jobs,
    list_logs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_spans,
    list_tasks,
    list_workers,
    summarize_actors,
    summarize_objects,
    summarize_tasks,
    timeline,
)

__all__ = [
    "critical_path",
    "get_log",
    "list_actors",
    "list_jobs",
    "list_logs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_spans",
    "list_tasks",
    "list_workers",
    "summarize_actors",
    "summarize_objects",
    "summarize_tasks",
    "timeline",
]
