"""Distributed task tracing: OTel-style spans with context propagation.

Analog of python/ray/util/tracing/tracing_helper.py (:36-57): when enabled
(set the ``RAY_TPU_TASK_TRACE_SPANS=1`` environment variable before
``ray_tpu.init``), every task/actor submission carries a trace context inside the task wire
dict, the submitting side emits a ``submit`` span parented to the caller's
active span, and the executing worker emits an ``execute`` span parented to
the submit span — with the active-span contextvar set for the duration of
user code, so tasks submitted FROM a task chain into the same trace.

Spans ride the existing task-event pipeline (record_task_event state="SPAN"
-> GcsTaskManager analog) and surface through the chrome timeline plus
``ray_tpu.util.state.api.list_spans()``. No OpenTelemetry SDK dependency:
the span model (trace_id / span_id / parent_span_id / kind / start /
duration) is OTLP-shaped so an exporter can translate 1:1.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Any, Dict, Optional

from ray_tpu._private.common import config

# (trace_id, active_span_id) for the current task of execution.
_trace_ctx: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


def enabled() -> bool:
    return bool(config.task_trace_spans)


def _new_id() -> str:
    return os.urandom(8).hex()


def current_context() -> Optional[tuple]:
    """(trace_id, span_id) of the active span, or None."""
    return _trace_ctx.get()


def set_context(ctx: Optional[tuple]):
    """Set the active span on the CURRENT thread/context; returns a reset
    token (or None if ctx is None). Needed because run_in_executor does not
    propagate contextvars onto pool threads — execution paths that hop
    threads re-set the span where user code actually runs."""
    if ctx is None:
        return None
    return _trace_ctx.set(ctx)


def reset_context(token) -> None:
    if token is not None:
        _trace_ctx.reset(token)


def make_submit_ctx(core, task_id: str, name: str) -> Optional[Dict[str, str]]:
    """Record the submit-side span and return the wire trace context
    ({trace_id, span_id}) the executing worker will parent to."""
    if not enabled():
        return None
    cur = _trace_ctx.get()
    trace_id = cur[0] if cur else _new_id()
    span_id = _new_id()
    core.record_task_event(
        task_id,
        name,
        "SPAN",
        span_id=span_id,
        parent_span_id=cur[1] if cur else None,
        trace_id=trace_id,
        kind="submit",
        start=time.time(),
        duration=0.0,
    )
    return {"trace_id": trace_id, "span_id": span_id}


@contextlib.contextmanager
def execute_scope(core, wire: Dict[str, Any]):
    """Worker-side span around user code execution. Sets the active-span
    contextvar so nested submissions parent correctly (the propagation the
    reference does by injecting into TaskSpec and wrapping the function)."""
    ctx = wire.get("trace_ctx")
    if not ctx:
        yield
        return
    span_id = _new_id()
    token = _trace_ctx.set((ctx["trace_id"], span_id))
    t0 = time.time()
    try:
        yield
    finally:
        _trace_ctx.reset(token)
        core.record_task_event(
            wire["task_id"],
            wire.get("name") or wire.get("actor_method") or "task",
            "SPAN",
            span_id=span_id,
            parent_span_id=ctx["span_id"],
            trace_id=ctx["trace_id"],
            kind="execute",
            start=t0,
            duration=time.time() - t0,
        )
