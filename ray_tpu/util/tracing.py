"""Distributed tracing: wire-propagated spans with runtime instrumentation.

Analog of python/ray/util/tracing/tracing_helper.py (:36-57), grown into a
full runtime tracing plane: when enabled (``RAY_TPU_TASK_TRACE_SPANS=1``
for always-on, or ``RAY_TPU_TRACE_SAMPLE_RATE`` for sampled always-on),
every task/actor submission carries a trace context inside the task wire
dict, the submitting side emits a ``submit`` span parented to the caller's
active span, and the executing worker emits an ``execute`` span parented to
the submit span — with the active-span contextvar set for the duration of
user code, so tasks submitted FROM a task chain into the same trace.

Beyond task spans, the runtime emits internal spans on its hot paths
(lease lifecycle, arg fetch, object get/put/pull/push, serve router and
batch queue, data stages, collective ops) via :func:`record_span` /
:func:`span_scope`. The active context additionally rides every RPC
request frame (``rpc.py`` slot 5, beside the deadline TTL), so a handler
on another process sees the caller's span as its ambient parent without
any per-method plumbing.

Two delivery pipelines, one store:

- task submit/execute spans ride the existing task-event pipeline
  (``record_task_event`` state="SPAN" -> AddTaskEvents), preserving the
  core worker's flush-on-exit guarantee;
- runtime spans buffer in a process-local ring (``trace_span_buffer``)
  and flush to the GCS via ``ReportSpans`` on the telemetry cadence,
  mirroring ``telemetry.start_flusher`` exactly (snapshot-and-reset
  delta, fold back on failure).

The GCS diverts both into one bounded ``spans`` ring surfaced through
``list_spans()`` / ``timeline()`` / ``critical_path()``. No OpenTelemetry
SDK dependency: the span model (trace_id / span_id / parent_span_id /
kind / start / duration) is OTLP-shaped so an exporter can translate 1:1.

The contextvar itself lives in ``ray_tpu._private.rpc`` (the bottom of
the import graph — the frame codec must read it, and importing this
module from rpc would cycle through ``ray_tpu.util``); this module owns
everything above the raw variable.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, Iterable, Iterator, List, Optional

from ray_tpu._private.common import config
from ray_tpu._private import rpc as _rpc

# The (trace_id, active_span_id) of the current task of execution — shared
# with the RPC layer, which stamps it onto outgoing request frames and
# restores it around incoming handlers.
_trace_ctx = _rpc._trace_ctx

# Span-id generation: a module-level PRNG seeded from the OS once. The
# record path is perf-gated (trace_span_record_ns); os.urandom per span is
# a ~1us syscall, getrandbits is a single GIL-atomic C call. Uniqueness,
# not unpredictability, is what span ids need. Forked workers inherit the
# parent's PRNG state and would emit identical id sequences (colliding
# span ids corrupt the trace DAG), so children reseed at fork time.
_rand = random.Random(os.urandom(8))


def _reseed() -> None:
    _rand.seed(os.urandom(8))


if hasattr(os, "register_at_fork"):  # not on Windows
    os.register_at_fork(after_in_child=_reseed)


def enabled() -> bool:
    return bool(config.task_trace_spans) or config.trace_sample_rate > 0


def _new_id() -> str:
    return "%016x" % _rand.getrandbits(64)


def _sample(key: str) -> bool:
    """Deterministic root-sampling decision: every process hashing the same
    root key independently agrees whether the trace exists, so a sampled
    trace is always complete (no half-recorded requests)."""
    if config.task_trace_spans:
        return True
    rate = config.trace_sample_rate
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return (zlib.crc32(key.encode()) & 0xFFFFFFFF) / 2**32 < rate


def current_context() -> Optional[tuple]:
    """(trace_id, span_id) of the active span, or None."""
    return _trace_ctx.get()


def set_context(ctx: Optional[tuple]):
    """Set the active span on the CURRENT thread/context; returns a reset
    token (or None if ctx is None). Needed because run_in_executor does not
    propagate contextvars onto pool threads — execution paths that hop
    threads re-set the span where user code actually runs."""
    if ctx is None:
        return None
    return _trace_ctx.set(ctx)


def reset_context(token) -> None:
    if token is not None:
        _trace_ctx.reset(token)


def ctx_from_wire(wire: Dict[str, Any]) -> Optional[tuple]:
    """(trace_id, span_id) from a task wire dict's trace_ctx, or None."""
    ctx = wire.get("trace_ctx")
    if not ctx:
        return None
    return (ctx["trace_id"], ctx["span_id"])


# ---------------------------------------------------------------------------
# Runtime-span ring + flusher (the telemetry-plane pattern: process-local
# bounded buffer, snapshot-and-reset delta flush, restore on failure).
# ---------------------------------------------------------------------------

_buf: "deque[dict]" = deque(maxlen=config.trace_span_buffer)
_buf_lock = threading.Lock()
_flusher_started = False


def record_span(
    name: str,
    kind: str,
    start: float,
    duration: float,
    ctx: Optional[tuple] = None,
    **attrs: Any,
) -> Optional[str]:
    """Record one runtime span parented into the active trace.

    ``ctx`` overrides the ambient context (for spans emitted after the
    originating context is gone, e.g. raylet grant-time spans parented to
    the lease request's captured context). Returns the new span_id, or
    None when there is no trace to join — runtime spans never create
    roots; that is :func:`root_scope`'s job."""
    if ctx is None:
        ctx = _trace_ctx.get()
        if ctx is None:
            return None
    span_id = _new_id()
    span = {
        "state": "SPAN",
        "name": name,
        "kind": kind,
        "span_id": span_id,
        "parent_span_id": ctx[1],
        "trace_id": ctx[0],
        "start": start,
        "duration": duration,
        "time": start + duration,
    }
    if attrs:
        span.update(attrs)
    with _buf_lock:
        _buf.append(span)
    return span_id


@contextlib.contextmanager
def span_scope(name: str, kind: str, ctx: Optional[tuple] = None, **attrs: Any):
    """Span around a runtime code region. Sets the active context to the
    new span for the duration, so nested spans — and RPC calls made inside
    — parent under it. No-op when tracing is off or no trace is active."""
    if not enabled():
        yield None
        return
    if ctx is None:
        ctx = _trace_ctx.get()
    if ctx is None:
        yield None
        return
    span_id = _new_id()
    token = _trace_ctx.set((ctx[0], span_id))
    t0 = time.time()
    try:
        yield (ctx[0], span_id)
    finally:
        _trace_ctx.reset(token)
        span = {
            "state": "SPAN",
            "name": name,
            "kind": kind,
            "span_id": span_id,
            "parent_span_id": ctx[1],
            "trace_id": ctx[0],
            "start": t0,
            "duration": time.time() - t0,
            "time": time.time(),
        }
        if attrs:
            span.update(attrs)
        with _buf_lock:
            _buf.append(span)


@contextlib.contextmanager
def root_scope(name: str, kind: str, key: Optional[str] = None, **attrs: Any):
    """Span that CREATES a trace when none is active (subject to the
    sampling decision on ``key``). The serve router wraps each request in
    one of these, so a bare HTTP/handle call — no task ancestry — still
    yields a connected trace. Inside an existing trace it behaves exactly
    like :func:`span_scope`."""
    if not enabled():
        yield None
        return
    cur = _trace_ctx.get()
    if cur is None:
        root_key = key if key is not None else name
        if not _sample(root_key):
            yield None
            return
        trace_id = _new_id()
        parent = None
    else:
        trace_id, parent = cur
    span_id = _new_id()
    token = _trace_ctx.set((trace_id, span_id))
    t0 = time.time()
    try:
        yield (trace_id, span_id)
    finally:
        _trace_ctx.reset(token)
        span = {
            "state": "SPAN",
            "name": name,
            "kind": kind,
            "span_id": span_id,
            "parent_span_id": parent,
            "trace_id": trace_id,
            "start": t0,
            "duration": time.time() - t0,
            "time": time.time(),
        }
        if attrs:
            span.update(attrs)
        with _buf_lock:
            _buf.append(span)


def iter_scope(it: Iterable, name: str, kind: str = "data", **attrs: Any) -> Iterator:
    """Wrap an iterator in one span covering the whole iteration, with the
    span active while the iterator body runs — so every task a streaming
    executor submits joins a single trace. Creates a root (sampled on
    ``name``) when no trace is active."""
    if not enabled():
        yield from it
        return
    cur = _trace_ctx.get()
    if cur is None:
        if not _sample(name):
            yield from it
            return
        trace_id, parent = _new_id(), None
    else:
        trace_id, parent = cur
    span_id = _new_id()
    token = _trace_ctx.set((trace_id, span_id))
    t0 = time.time()
    try:
        yield from it
    finally:
        _trace_ctx.reset(token)
        span = {
            "state": "SPAN",
            "name": name,
            "kind": kind,
            "span_id": span_id,
            "parent_span_id": parent,
            "trace_id": trace_id,
            "start": t0,
            "duration": time.time() - t0,
            "time": time.time(),
        }
        if attrs:
            span.update(attrs)
        with _buf_lock:
            _buf.append(span)


def span_flush_delta() -> List[dict]:
    """Snapshot-and-reset the runtime-span buffer. The caller owns the
    returned spans; on delivery failure fold them back with
    :func:`restore_spans` so a transient GCS outage loses nothing."""
    with _buf_lock:
        if not _buf:
            return []
        spans = list(_buf)
        _buf.clear()
    return spans


def restore_spans(spans: List[dict]) -> None:
    """Fold an undelivered flush delta back into the buffer (oldest first,
    so ring eviction still drops the oldest)."""
    if not spans:
        return
    with _buf_lock:
        _buf.extendleft(reversed(spans))


async def flush_spans_once(call, source: str, node: Optional[str] = None) -> None:
    """One flush cycle: ship the span delta via ``call`` (an async
    ``(method, payload) ->`` RPC callable, e.g. ``gcs.call``)."""
    spans = span_flush_delta()
    if not spans:
        return
    try:
        await call("ReportSpans", {"source": source, "node": node, "spans": spans})
    except Exception:
        restore_spans(spans)
        raise


def start_span_flusher(call, source: str, node: Optional[str] = None) -> None:
    """Start the periodic span flusher on the running loop (idempotent per
    process, like ``telemetry.start_flusher``). Rides the telemetry flush
    cadence; gated on tracing being enabled at all."""
    global _flusher_started
    interval = config.telemetry_flush_interval_s
    if _flusher_started or not enabled() or interval <= 0:
        return
    _flusher_started = True

    async def _loop() -> None:
        import asyncio

        while True:
            await asyncio.sleep(interval)
            try:
                await flush_spans_once(call, source, node)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # delta restored; retried next tick

    _rpc.spawn(_loop())


def flusher_active() -> bool:
    """True when this process runs a periodic span flusher (the GCS skips
    its query-time local drain in that case — the flusher owns delivery
    and carries the right source attribution)."""
    return _flusher_started


def stop_flusher() -> None:
    """Mark the flusher stopped. Called when the core worker closes: the
    flusher task dies with the event loop, and leaving the flag set would
    make a later init in the same process (tests, repeated drivers) skip
    both the restart and the GCS's query-time local drain."""
    global _flusher_started
    _flusher_started = False


def reset_flusher_for_test() -> None:
    stop_flusher()


def snapshot() -> List[dict]:
    """Non-destructive copy of the local buffer (chaos dumps)."""
    with _buf_lock:
        return list(_buf)


def reset() -> None:
    """Drop all buffered spans (chaos per-seed isolation)."""
    with _buf_lock:
        _buf.clear()


# ---------------------------------------------------------------------------
# Task-level spans (submit/execute) — these ride the task-event pipeline so
# they inherit its flush-on-exit and existing GCS plumbing.
# ---------------------------------------------------------------------------


def make_submit_ctx(core, task_id: str, name: str) -> Optional[Dict[str, str]]:
    """Record the submit-side span and return the wire trace context
    ({trace_id, span_id}) the executing worker will parent to. A submission
    with no active trace is a new root, created only when the sampling
    decision on ``task_id`` says so."""
    if not enabled():
        return None
    cur = _trace_ctx.get()
    if cur is None:
        if not _sample(task_id):
            return None
        trace_id = _new_id()
    else:
        trace_id = cur[0]
    span_id = _new_id()
    core.record_task_event(
        task_id,
        name,
        "SPAN",
        span_id=span_id,
        parent_span_id=cur[1] if cur else None,
        trace_id=trace_id,
        kind="submit",
        start=time.time(),
        duration=0.0,
    )
    return {"trace_id": trace_id, "span_id": span_id}


@contextlib.contextmanager
def execute_scope(core, wire: Dict[str, Any]):
    """Worker-side span around user code execution. Sets the active-span
    contextvar so nested submissions parent correctly (the propagation the
    reference does by injecting into TaskSpec and wrapping the function)."""
    ctx = wire.get("trace_ctx")
    if not ctx:
        yield
        return
    span_id = _new_id()
    token = _trace_ctx.set((ctx["trace_id"], span_id))
    t0 = time.time()
    try:
        yield
    finally:
        _trace_ctx.reset(token)
        core.record_task_event(
            wire["task_id"],
            wire.get("name") or wire.get("actor_method") or "task",
            "SPAN",
            span_id=span_id,
            parent_span_id=ctx["span_id"],
            trace_id=ctx["trace_id"],
            kind="execute",
            start=t0,
            duration=time.time() - t0,
        )
