"""Collective communication across actors/tasks.

API surface mirrors the reference's ray.util.collective
(python/ray/util/collective/collective.py:120-615: init_collective_group,
allreduce/allgather/reducescatter/broadcast/send/recv/barrier) with TPU-native
backends instead of NCCL/Gloo:

- "xla": multi-controller JAX. Ranks rendezvous through the GCS KV for a
  coordinator address, call jax.distributed.initialize, and every collective
  lowers to a `jax.lax` op under shard_map over the group's named mesh — ICI
  when the ranks are TPU hosts, the JAX coordination fabric otherwise. This
  is the performance path; the group IS a mesh (see
  ray_tpu/util/collective/mesh_ops.py and docs/collectives.md).
- "store": pure control-plane fallback (the pygloo-analog): a named async
  rendezvous actor reduces numpy payloads. Correct anywhere, including CPU
  actors; bandwidth-bound by the object path, so use it for small tensors and
  coordination, not gradient traffic.

Like NCCL, all ranks must issue collectives in the same order; a per-group
sequence number enforces matching.

On the xla backend every module-level op runs zero `_CollectiveStore` actor
round trips: inputs stage onto the group's ici mesh (one device per process,
cached by buffer identity so repeated calls on the same array skip the
host->device copy), and the op itself is one cached compiled program. That
makes these functions fine for rendezvous, bootstrap and moderate tensors;
per-step gradient traffic should still live INSIDE one jit/shard_map training
program over `get_group_mesh` (see ray_tpu.parallel.mesh and
models/transformer.py's make_train_step), where XLA overlaps collectives with
compute instead of dispatching one program per op.

A rank that dies mid-collective must not hang the survivors: store-backend
ops poll peer actor liveness through the GCS while blocked and raise
`CollectiveGroupDiedError` (typed, within ~one health-check interval of the
GCS marking the actor dead) instead of waiting out the full timeout.
"""

from __future__ import annotations

import logging
import os
import time as _time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu._private.common import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    RayTpuError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)

SUM, PRODUCT, MIN, MAX = "sum", "product", "min", "max"
_OPS = {
    SUM: lambda arrs: np.sum(arrs, axis=0),
    PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    MIN: lambda arrs: np.min(arrs, axis=0),
    MAX: lambda arrs: np.max(arrs, axis=0),
}

# Blocked store-backend ops re-check peer liveness at this cadence; the
# overall op deadline stays RAY_TPU_COLLECTIVE_TIMEOUT_S.
_HEALTH_INTERVAL_S = float(
    os.environ.get("RAY_TPU_COLLECTIVE_HEALTH_INTERVAL_S", "0.5")
)
_OP_TIMEOUT_S = float(os.environ.get("RAY_TPU_COLLECTIVE_TIMEOUT_S", "300"))


class CollectiveGroupDiedError(RayTpuError):
    """A participant (rank actor or the rendezvous store) died while a group
    op was in flight. The whole group op fails — collectives are
    all-or-nothing, exactly like a NCCL communicator abort."""

    def __init__(self, group_name: str, detail: str = ""):
        self.group_name = group_name
        self.detail = detail
        super().__init__(
            f"collective group {group_name!r} died mid-op: {detail}"
        )

    def __reduce__(self):
        # Default Exception.__reduce__ would replay the composed message as
        # group_name; rebuild from the original parts so the error survives
        # the worker->driver serialization boundary intact.
        return (type(self), (self.group_name, self.detail))


def _store_actor_cls():
    import ray_tpu

    @ray_tpu.remote
    class _CollectiveStore:
        """Async rendezvous actor: one per group; reduces contributions."""

        def __init__(self, world_size: int):
            import asyncio

            self.world = world_size
            self.pending: Dict[int, Dict[int, Any]] = {}
            self.results: Dict[int, Any] = {}
            self.events: Dict[int, asyncio.Event] = {}
            self.reads: Dict[int, int] = {}
            self.p2p: Dict[tuple, Any] = {}
            self.p2p_events: Dict[tuple, asyncio.Event] = {}

        def _event(self, seq):
            import asyncio

            if seq not in self.events:
                self.events[seq] = asyncio.Event()
            return self.events[seq]

        async def contribute(self, seq: int, rank: int, arr, op: str, mode: str):
            ev = self._event(seq)
            bucket = self.pending.setdefault(seq, {})
            bucket[rank] = arr
            if len(bucket) == self.world:
                arrs = [bucket[r] for r in sorted(bucket)]
                if mode == "allreduce":
                    self.results[seq] = _OPS[op](np.stack(arrs))
                elif mode == "allgather":
                    self.results[seq] = arrs
                elif mode == "broadcast":
                    src = int(op)
                    self.results[seq] = bucket[src]
                elif mode == "barrier":
                    self.results[seq] = True
                elif mode == "reducescatter":
                    red = _OPS[SUM if op == "barrier" else op](np.stack(arrs))
                    self.results[seq] = np.array_split(red, self.world, axis=0)
                del self.pending[seq]
                ev.set()
            else:
                await ev.wait()
            res = self.results[seq]
            if mode == "reducescatter":
                res = res[rank]
            # Evict once every rank has read its result.
            self.reads[seq] = self.reads.get(seq, 0) + 1
            if self.reads[seq] == self.world:
                self.results.pop(seq, None)
                self.events.pop(seq, None)
                self.reads.pop(seq, None)
            return res

        async def send(self, src: int, dst: int, tag: int, arr):
            import asyncio

            # Per-key FIFO so back-to-back sends never overwrite each other.
            key = (src, dst, tag)
            self.p2p.setdefault(key, []).append(arr)
            if key not in self.p2p_events:
                self.p2p_events[key] = asyncio.Event()
            self.p2p_events[key].set()

        async def recv(self, src: int, dst: int, tag: int):
            import asyncio

            key = (src, dst, tag)
            while not self.p2p.get(key):
                if key not in self.p2p_events:
                    self.p2p_events[key] = asyncio.Event()
                await self.p2p_events[key].wait()
                self.p2p_events[key].clear()
            queue = self.p2p[key]
            arr = queue.pop(0)
            if not queue:
                self.p2p.pop(key, None)
                self.p2p_events.pop(key, None)
            return arr

    return _CollectiveStore


class _Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.seq = 0
        self.store = None  # store backend: actor handle
        self.mesh = None  # xla backend: global ("world", "local") mesh
        self.engine = None  # xla backend: MeshCollectives over the ici mesh
        self._p2p_engines: Dict[tuple, Any] = {}
        self._p2p_seq: Dict[tuple, int] = {}
        self._members: Optional[Dict[int, str]] = None  # rank -> actor_id

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq


class GroupManager:
    """Per-process registry (reference: collective.py:40)."""

    def __init__(self):
        self.groups: Dict[str, _Group] = {}

    def get(self, name: str) -> _Group:
        if name not in self.groups:
            raise ValueError(f"collective group {name!r} is not initialized")
        return self.groups[name]


_manager = GroupManager()


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _manager.groups


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "store",
    group_name: str = "default",
) -> None:
    """Join a collective group. Must be called by every rank (typically from
    inside each participating actor)."""
    import ray_tpu

    if group_name in _manager.groups:
        raise ValueError(f"group {group_name!r} already initialized")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world size {world_size}")
    group = _Group(group_name, world_size, rank, backend)
    if backend == "store":
        cls = _store_actor_cls()
        group.store = cls.options(
            name=f"__collective_{group_name}", get_if_exists=True, num_cpus=0.1
        ).remote(world_size)
        _register_member(group)
    elif backend == "xla":
        group.mesh, group.engine = _init_xla_backend(
            world_size, rank, group_name
        )
    else:
        raise ValueError(f"unknown collective backend {backend!r}")
    _manager.groups[group_name] = group


def _register_member(group: _Group) -> None:
    """Publish this rank's actor id in the GCS KV so blocked peers can watch
    for its death (ns=collective, key member_{group}_{rank}). Driver-side
    ranks have no actor id and publish an empty value (unwatchable)."""
    try:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        aid = getattr(core, "current_actor_id", None) or ""
        worker_mod.global_worker.run_async(
            core.gcs.kv_put(
                f"member_{group.name}_{group.rank}",
                aid.encode(),
                ns="collective",
            )
        )
    except Exception:
        logger.debug("collective member registration failed", exc_info=True)


def _init_xla_backend(world_size: int, rank: int, group_name: str):
    """Multi-controller JAX bootstrap: coordinator address rendezvous via GCS
    KV, jax.distributed.initialize, then the group's named meshes — the full
    ("world", "local") mesh for user SPMD programs and a 1-device-per-process
    "world" ici mesh carrying the compiled module-level collectives."""
    import socket

    import jax

    from ray_tpu.util.collective.mesh_ops import MeshCollectives

    if world_size > 1:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod._core()
        key = f"xla_coord_{group_name}"
        if rank == 0:
            # Advertise this node's address (not loopback) so ranks on other
            # hosts can reach the coordinator; raylet_addr holds the node IP.
            host = core.raylet_addr[0] if core.raylet_addr else socket.gethostbyname(
                socket.gethostname()
            )
            sock = socket.socket()
            sock.bind((host if host != "127.0.0.1" else "0.0.0.0", 0))
            port = sock.getsockname()[1]
            sock.close()
            coord = f"{host}:{port}"
            worker_mod.global_worker.run_async(
                core.gcs.kv_put(key, coord.encode(), ns="collective")
            )
        else:
            coord = None
            for _ in range(300):
                val = worker_mod.global_worker.run_async(
                    core.gcs.kv_get(key, ns="collective")
                )
                if val:
                    coord = val.decode()
                    break
                _time.sleep(0.1)
            if coord is None:
                raise TimeoutError(
                    "xla collective coordinator rendezvous timed out"
                )
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=world_size, process_id=rank
        )
    # world_size == 1 needs no distributed runtime: the "group" is this
    # process's devices (this also keeps single-process groups usable after
    # the jax backend is already initialized, e.g. in tests and benchmarks).
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()).reshape(world_size, -1)
    mesh = Mesh(devices, ("world", "local"))
    # ici mesh: rank i <-> devices[i, 0]. One device per process keeps
    # staging one device_put per call-site (the full mesh would replicate
    # every module-level payload across all local devices).
    ici_mesh = Mesh(devices[:, 0], ("world",))
    engine = MeshCollectives(ici_mesh, axis="world", group_name=group_name)
    return mesh, engine


def destroy_collective_group(group_name: str = "default") -> None:
    group = _manager.groups.pop(group_name, None)
    if group is not None and group.backend == "xla" and group.world_size > 1:
        # Tear down the jax.distributed runtime so a later xla group can
        # initialize again in this process.
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    if group is not None and group.rank == 0:
        # Rank 0 reaps the rendezvous state so a later group with the same
        # name starts clean (fresh seq/result tables, coordinator address).
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        if group.store is not None:
            try:
                ray_tpu.kill(group.store)
            except Exception:
                pass
        try:
            core = worker_mod._core()

            async def _reap():
                await core.gcs.kv_del(
                    f"xla_coord_{group_name}", ns="collective"
                )
                for r in range(group.world_size):
                    await core.gcs.kv_del(
                        f"member_{group_name}_{r}", ns="collective"
                    )

            worker_mod.global_worker.run_async(_reap())
        except Exception:
            pass


# -- store backend: liveness-watched round trips ------------------------------


def _group_members(group: _Group) -> Dict[int, str]:
    """rank -> actor_id map published at init; cached once complete."""
    if group._members is not None and len(group._members) == group.world_size:
        return group._members
    from ray_tpu._private import worker as worker_mod

    core = worker_mod._core()

    async def _fetch():
        out = {}
        for r in range(group.world_size):
            val = await core.gcs.kv_get(
                f"member_{group.name}_{r}", ns="collective"
            )
            if val is not None:
                out[r] = val.decode()
        return out

    try:
        group._members = worker_mod.global_worker.run_async(_fetch(), timeout=10)
    except Exception:
        group._members = group._members or {}
    return group._members


def _dead_members(group: _Group) -> List[int]:
    """Ranks whose registered actors the GCS has marked DEAD."""
    from ray_tpu._private import worker as worker_mod

    members = _group_members(group)
    core = worker_mod._core()

    async def _check():
        dead = []
        for rank, aid in members.items():
            if not aid or rank == group.rank:
                continue
            try:
                resp = await core.gcs.call("GetActor", {"actor_id": aid})
            except Exception:
                continue
            actor = resp.get("actor")
            if actor is not None and actor.get("state") == "DEAD":
                dead.append(rank)
        return dead

    try:
        return worker_mod.global_worker.run_async(_check(), timeout=10)
    except Exception:
        return []


def _watched_get(group: _Group, ref, what: str):
    """ray_tpu.get with a death watch: while the result is pending, poll the
    GCS for dead group members and fail fast with CollectiveGroupDiedError
    instead of hanging until the 300s op deadline."""
    import ray_tpu

    deadline = _time.monotonic() + _OP_TIMEOUT_S
    while True:
        try:
            return ray_tpu.get(ref, timeout=_HEALTH_INTERVAL_S)
        except GetTimeoutError:
            dead = _dead_members(group)
            if dead:
                raise CollectiveGroupDiedError(
                    group.name,
                    f"rank(s) {sorted(dead)} died while {what} was in flight",
                ) from None
            if _time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"collective {what} on group {group.name!r} timed out "
                    f"after {_OP_TIMEOUT_S:.0f}s"
                ) from None
        except (ActorDiedError, WorkerCrashedError, ActorUnavailableError) as e:
            # The rendezvous store itself is gone: the group cannot complete
            # any op again.
            raise CollectiveGroupDiedError(
                group.name, f"rendezvous store died: {e}"
            ) from None


def _roundtrip(group: _Group, arr, op: str, mode: str):
    t0 = _time.perf_counter()
    np_arr = np.asarray(arr)
    seq = group.next_seq()
    ref = group.store.contribute.remote(seq, group.rank, np_arr, op, mode)
    out = _watched_get(group, ref, mode)
    from ray_tpu.util.collective.mesh_ops import _observe

    _observe(mode, group.name, np_arr.nbytes, _time.perf_counter() - t0)
    return out


# -- xla backend: compiled mesh ops ------------------------------------------


def _staged_input(group: _Group, arr):
    """Stage this rank's contribution onto the group's ici mesh. Repeat calls
    with the same (identity) buffer hit the engine's device cache — no
    np.asarray, no device_put."""
    return group.engine.stage_local(arr, group.rank)


def allreduce(tensor, group_name: str = "default", op: str = SUM):
    """Reduce across all ranks; returns the reduced array on every rank."""
    group = _manager.get(group_name)
    if group.backend == "xla":
        out = group.engine.allreduce(_staged_input(group, tensor), op)
        return np.asarray(out)
    return _roundtrip(group, tensor, op, "allreduce")


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    group = _manager.get(group_name)
    if group.backend == "xla":
        # lax.all_gather inside the compiled program: each rank stages only
        # its own shard (the old one-hot path allocated and reduced a
        # world x |tensor| host buffer per call).
        out = group.engine.allgather(_staged_input(group, tensor))
        host = np.asarray(out)
        return [host[i] for i in range(group.world_size)]
    return _roundtrip(group, tensor, SUM, "allgather")


def reducescatter(tensor, group_name: str = "default", op: str = SUM):
    group = _manager.get(group_name)
    if group.backend == "xla":
        np_arr = np.asarray(tensor)
        if np_arr.shape and np_arr.shape[0] % group.world_size == 0:
            out = group.engine.reducescatter(_staged_input(group, tensor), op)
            return group.engine.rank_shard(out, group.rank)
        # Uneven split: reduce on-mesh, slice on host (store-backend parity
        # via np.array_split; still zero store round trips).
        red = np.asarray(
            group.engine.allreduce(_staged_input(group, tensor), op)
        )
        return np.array_split(red, group.world_size, axis=0)[group.rank]
    return _roundtrip(group, tensor, op, "reducescatter")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _manager.get(group_name)
    if group.backend == "xla":
        out = group.engine.broadcast(_staged_input(group, tensor), src_rank)
        return group.engine.rank_shard(out, group.rank)[0]
    return _roundtrip(group, tensor, str(src_rank), "broadcast")


def barrier(group_name: str = "default") -> None:
    group = _manager.get(group_name)
    if group.backend == "xla":
        group.engine.barrier()
        return
    _roundtrip(group, np.zeros(1), "barrier", "barrier")


def _p2p_engine(group: _Group, src: int, dst: int):
    """Compiled 2-rank submesh for a (src, dst) pair: only those two
    processes participate in the permute program (a full-group ppermute
    would require every rank to join each send/recv)."""
    key = (src, dst)
    eng = group._p2p_engines.get(key)
    if eng is None:
        from jax.sharding import Mesh

        from ray_tpu.util.collective.mesh_ops import MeshCollectives

        ici = group.engine.mesh
        devices = np.asarray(
            [ici.devices.flat[src], ici.devices.flat[dst]]
        )
        eng = MeshCollectives(
            Mesh(devices, ("p2p",)),
            axis="p2p",
            group_name=f"{group.name}:p2p",
        )
        group._p2p_engines[key] = eng
    return eng


def _p2p_meta_key(group: _Group, src: int, dst: int, tag: int, seq: int) -> str:
    return f"p2p_{group.name}_{src}_{dst}_{tag}_{seq}"


def _xla_send(group: _Group, tensor, dst_rank: int, tag: int) -> None:
    import json

    from ray_tpu._private import worker as worker_mod

    np_arr = np.asarray(tensor)
    key = (group.rank, dst_rank, tag)
    seq = group._p2p_seq[key] = group._p2p_seq.get(key, 0) + 1
    # Publish shape/dtype so the receiver can stage its half of the program.
    core = worker_mod._core()
    meta = json.dumps({"shape": list(np_arr.shape), "dtype": np_arr.dtype.str})
    worker_mod.global_worker.run_async(
        core.gcs.kv_put(
            _p2p_meta_key(group, group.rank, dst_rank, tag, seq),
            meta.encode(),
            ns="collective",
        )
    )
    eng = _p2p_engine(group, group.rank, dst_rank)
    eng.permute(eng.stage_local(np_arr, 0), [(0, 1)])


def _xla_recv(group: _Group, src_rank: int, tag: int):
    import json

    from ray_tpu._private import worker as worker_mod

    key = (src_rank, group.rank, tag)
    seq = group._p2p_seq[key] = group._p2p_seq.get(key, 0) + 1
    core = worker_mod._core()
    kv_key = _p2p_meta_key(group, src_rank, group.rank, tag, seq)
    meta = None
    deadline = _time.monotonic() + _OP_TIMEOUT_S
    while meta is None:
        val = worker_mod.global_worker.run_async(
            core.gcs.kv_get(kv_key, ns="collective")
        )
        if val:
            meta = json.loads(val.decode())
            break
        if _time.monotonic() > deadline:
            raise GetTimeoutError(f"recv from rank {src_rank} timed out")
        _time.sleep(0.05)
    worker_mod.global_worker.run_async(
        core.gcs.kv_del(kv_key, ns="collective")
    )
    eng = _p2p_engine(group, src_rank, group.rank)
    zeros = np.zeros(tuple(meta["shape"]), dtype=np.dtype(meta["dtype"]))
    out = eng.permute(eng.stage_local(zeros, 1, cache=False), [(0, 1)])
    return eng.rank_shard(out, 1)[0]


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0) -> None:
    group = _manager.get(group_name)
    if group.backend == "xla":
        _xla_send(group, tensor, dst_rank, tag)
        return
    ref = group.store.send.remote(group.rank, dst_rank, tag, np.asarray(tensor))
    _watched_get(group, ref, "send")


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    group = _manager.get(group_name)
    if group.backend == "xla":
        return _xla_recv(group, src_rank, tag)
    ref = group.store.recv.remote(src_rank, group.rank, tag)
    return _watched_get(group, ref, "recv")


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def get_group_mesh(group_name: str = "default"):
    """The xla group's global jax.sharding.Mesh (axes ("world", "local")).
    None on the store backend — the group there is a rendezvous actor, not a
    device mesh."""
    return _manager.get(group_name).mesh


def get_group_collectives(group_name: str = "default"):
    """The xla group's MeshCollectives engine (compiled-program cache over
    the ici mesh); None on the store backend."""
    return _manager.get(group_name).engine
