"""Collective communication across actors/tasks.

API surface mirrors the reference's ray.util.collective
(python/ray/util/collective/collective.py:120-615: init_collective_group,
allreduce/allgather/reducescatter/broadcast/send/recv/barrier) with TPU-native
backends instead of NCCL/Gloo:

- "xla": multi-controller JAX. Ranks rendezvous through the GCS KV for a
  coordinator address, call jax.distributed.initialize, and every collective
  lowers to a jitted `jax.lax` op over the global device mesh — ICI when the
  ranks are TPU hosts, the JAX coordination fabric otherwise. This is the
  performance path; the group IS a mesh.
- "store": pure control-plane fallback (the pygloo-analog): a named async
  rendezvous actor reduces numpy payloads. Correct anywhere, including CPU
  actors; bandwidth-bound by the object path, so use it for small tensors and
  coordination, not gradient traffic.

Like NCCL, all ranks must issue collectives in the same order; a per-group
sequence number enforces matching.

PERFORMANCE NOTE (read this before putting col.allreduce in a loop): on
TPU, collectives only ride ICI when they execute INSIDE one compiled SPMD
program. These module-level functions are host-mediated per call — each
builds a global array and runs a freshly dispatched jitted reduce — which
is exactly right for rendezvous, bootstrap, and occasional small tensors
(it is how JaxTrainer seeds its mesh), and ~1000x too slow for per-step
gradient traffic. The gradient path is: get the group's mesh
(`get_group_mesh`) and write the training step as one jit/shard_map
program whose `jax.lax.psum/all_gather/psum_scatter/ppermute` ops XLA
schedules over ICI; see ray_tpu.parallel.mesh and models/transformer.py's
make_train_step for the pattern.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

SUM, PRODUCT, MIN, MAX = "sum", "product", "min", "max"
_OPS = {
    SUM: lambda arrs: np.sum(arrs, axis=0),
    PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    MIN: lambda arrs: np.min(arrs, axis=0),
    MAX: lambda arrs: np.max(arrs, axis=0),
}


def _store_actor_cls():
    import ray_tpu

    @ray_tpu.remote
    class _CollectiveStore:
        """Async rendezvous actor: one per group; reduces contributions."""

        def __init__(self, world_size: int):
            import asyncio

            self.world = world_size
            self.pending: Dict[int, Dict[int, Any]] = {}
            self.results: Dict[int, Any] = {}
            self.events: Dict[int, asyncio.Event] = {}
            self.reads: Dict[int, int] = {}
            self.p2p: Dict[tuple, Any] = {}
            self.p2p_events: Dict[tuple, asyncio.Event] = {}

        def _event(self, seq):
            import asyncio

            if seq not in self.events:
                self.events[seq] = asyncio.Event()
            return self.events[seq]

        async def contribute(self, seq: int, rank: int, arr, op: str, mode: str):
            ev = self._event(seq)
            bucket = self.pending.setdefault(seq, {})
            bucket[rank] = arr
            if len(bucket) == self.world:
                arrs = [bucket[r] for r in sorted(bucket)]
                if mode == "allreduce":
                    self.results[seq] = _OPS[op](np.stack(arrs))
                elif mode == "allgather":
                    self.results[seq] = arrs
                elif mode == "broadcast":
                    src = int(op)
                    self.results[seq] = bucket[src]
                elif mode == "barrier":
                    self.results[seq] = True
                elif mode == "reducescatter":
                    red = _OPS[SUM if op == "barrier" else op](np.stack(arrs))
                    self.results[seq] = np.array_split(red, self.world, axis=0)
                del self.pending[seq]
                ev.set()
            else:
                await ev.wait()
            res = self.results[seq]
            if mode == "reducescatter":
                res = res[rank]
            # Evict once every rank has read its result.
            self.reads[seq] = self.reads.get(seq, 0) + 1
            if self.reads[seq] == self.world:
                self.results.pop(seq, None)
                self.events.pop(seq, None)
                self.reads.pop(seq, None)
            return res

        async def send(self, src: int, dst: int, tag: int, arr):
            import asyncio

            # Per-key FIFO so back-to-back sends never overwrite each other.
            key = (src, dst, tag)
            self.p2p.setdefault(key, []).append(arr)
            if key not in self.p2p_events:
                self.p2p_events[key] = asyncio.Event()
            self.p2p_events[key].set()

        async def recv(self, src: int, dst: int, tag: int):
            import asyncio

            key = (src, dst, tag)
            while not self.p2p.get(key):
                if key not in self.p2p_events:
                    self.p2p_events[key] = asyncio.Event()
                await self.p2p_events[key].wait()
                self.p2p_events[key].clear()
            queue = self.p2p[key]
            arr = queue.pop(0)
            if not queue:
                self.p2p.pop(key, None)
                self.p2p_events.pop(key, None)
            return arr

    return _CollectiveStore


class _Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.seq = 0
        self.store = None  # store backend: actor handle
        self.mesh = None  # xla backend: global mesh
        self._jit_cache: Dict[tuple, Any] = {}

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq


class GroupManager:
    """Per-process registry (reference: collective.py:40)."""

    def __init__(self):
        self.groups: Dict[str, _Group] = {}

    def get(self, name: str) -> _Group:
        if name not in self.groups:
            raise ValueError(f"collective group {name!r} is not initialized")
        return self.groups[name]


_manager = GroupManager()


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _manager.groups


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "store",
    group_name: str = "default",
) -> None:
    """Join a collective group. Must be called by every rank (typically from
    inside each participating actor)."""
    import ray_tpu

    if group_name in _manager.groups:
        raise ValueError(f"group {group_name!r} already initialized")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world size {world_size}")
    group = _Group(group_name, world_size, rank, backend)
    if backend == "store":
        cls = _store_actor_cls()
        group.store = cls.options(
            name=f"__collective_{group_name}", get_if_exists=True, num_cpus=0.1
        ).remote(world_size)
    elif backend == "xla":
        group.mesh = _init_xla_backend(world_size, rank, group_name)
    else:
        raise ValueError(f"unknown collective backend {backend!r}")
    _manager.groups[group_name] = group


def _init_xla_backend(world_size: int, rank: int, group_name: str):
    """Multi-controller JAX bootstrap: coordinator address rendezvous via GCS
    KV, jax.distributed.initialize, global 1-axis mesh over all devices."""
    import socket

    import jax

    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    core = worker_mod._core()
    key = f"xla_coord_{group_name}"
    if rank == 0:
        # Advertise this node's address (not loopback) so ranks on other
        # hosts can reach the coordinator; raylet_addr holds the node IP.
        host = core.raylet_addr[0] if core.raylet_addr else socket.gethostbyname(
            socket.gethostname()
        )
        sock = socket.socket()
        sock.bind((host if host != "127.0.0.1" else "0.0.0.0", 0))
        port = sock.getsockname()[1]
        sock.close()
        coord = f"{host}:{port}"
        worker_mod.global_worker.run_async(
            core.gcs.kv_put(key, coord.encode(), ns="collective")
        )
    else:
        import time

        coord = None
        for _ in range(300):
            val = worker_mod.global_worker.run_async(
                core.gcs.kv_get(key, ns="collective")
            )
            if val:
                coord = val.decode()
                break
            time.sleep(0.1)
        if coord is None:
            raise TimeoutError("xla collective coordinator rendezvous timed out")
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=world_size, process_id=rank
    )
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()).reshape(world_size, -1)
    return Mesh(devices, ("world", "local"))


def destroy_collective_group(group_name: str = "default") -> None:
    group = _manager.groups.pop(group_name, None)
    if group is not None and group.backend == "xla":
        # Tear down the jax.distributed runtime so a later xla group can
        # initialize again in this process.
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    if group is not None and group.rank == 0:
        # Rank 0 reaps the rendezvous state so a later group with the same
        # name starts clean (fresh seq/result tables, coordinator address).
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        if group.store is not None:
            try:
                ray_tpu.kill(group.store)
            except Exception:
                pass
        try:
            core = worker_mod._core()
            worker_mod.global_worker.run_async(
                core.gcs.kv_del(f"xla_coord_{group_name}", ns="collective")
            )
        except Exception:
            pass


def _roundtrip(group: _Group, arr, op: str, mode: str):
    import ray_tpu

    np_arr = np.asarray(arr)
    seq = group.next_seq()
    ref = group.store.contribute.remote(seq, group.rank, np_arr, op, mode)
    return ray_tpu.get(ref, timeout=300)


def _xla_allreduce(group: _Group, arr, op: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = group.mesh
    key = ("allreduce", op, tuple(np.shape(arr)), str(np.asarray(arr).dtype))
    fn = group._jit_cache.get(key)
    if fn is None:
        reducer = {SUM: jnp.sum, PRODUCT: jnp.prod, MIN: jnp.min, MAX: jnp.max}[op]

        @jax.jit
        def _reduce(g):
            return reducer(g, axis=0)

        fn = _reduce
        group._jit_cache[key] = fn
    local = jnp.asarray(arr)
    global_shape = (group.world_size,) + local.shape
    sharding = NamedSharding(mesh, P("world"))
    # P("world") replicates over the "local" axis, so every addressable
    # device in this process's mesh row needs a copy of the shard.
    garr = jax.make_array_from_single_device_arrays(
        global_shape,
        sharding,
        [jax.device_put(local[None], d) for d in mesh.local_devices],
    )
    out = fn(garr)
    return np.asarray(jax.device_get(out))


def allreduce(tensor, group_name: str = "default", op: str = SUM):
    """Reduce across all ranks; returns the reduced array on every rank."""
    group = _manager.get(group_name)
    if group.backend == "xla":
        return _xla_allreduce(group, tensor, op)
    return _roundtrip(group, tensor, op, "allreduce")


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    group = _manager.get(group_name)
    if group.backend == "xla":
        # One-hot placement + sum-allreduce: correct on any mesh; XLA fuses
        # this into an all-gather when profitable.
        np_arr = np.asarray(tensor)
        world = group.world_size
        expanded = np.zeros((world,) + np_arr.shape, dtype=np_arr.dtype)
        expanded[group.rank] = np_arr
        out = _xla_allreduce(group, expanded, SUM)
        return [out[i] for i in range(world)]
    return _roundtrip(group, tensor, SUM, "allgather")


def reducescatter(tensor, group_name: str = "default", op: str = SUM):
    group = _manager.get(group_name)
    if group.backend == "xla":
        red = _xla_allreduce(group, tensor, op)
        return np.array_split(red, group.world_size, axis=0)[group.rank]
    return _roundtrip(group, tensor, op, "reducescatter")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _manager.get(group_name)
    if group.backend == "xla":
        np_arr = np.asarray(tensor)
        contrib = np_arr if group.rank == src_rank else np.zeros_like(np_arr)
        return _xla_allreduce(group, contrib, SUM)
    return _roundtrip(group, tensor, str(src_rank), "broadcast")


def barrier(group_name: str = "default") -> None:
    group = _manager.get(group_name)
    if group.backend == "xla":
        _xla_allreduce(group, np.zeros(1, dtype=np.float32), SUM)
        return
    _roundtrip(group, np.zeros(1), "barrier", "barrier")


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0) -> None:
    import ray_tpu

    group = _manager.get(group_name)
    if group.store is None:
        raise NotImplementedError(
            "point-to-point send/recv requires the store backend; on the xla "
            "backend use in-program ppermute via ray_tpu.parallel"
        )
    ray_tpu.get(
        group.store.send.remote(group.rank, dst_rank, tag, np.asarray(tensor)),
        timeout=300,
    )


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    import ray_tpu

    group = _manager.get(group_name)
    if group.store is None:
        raise NotImplementedError(
            "point-to-point send/recv requires the store backend; on the xla "
            "backend use in-program ppermute via ray_tpu.parallel"
        )
    return ray_tpu.get(
        group.store.recv.remote(src_rank, group.rank, tag), timeout=300
    )


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def get_group_mesh(group_name: str = "default"):
    """The xla group's global jax.sharding.Mesh (axes ("world", "local")).
    None on the store backend — the group there is a rendezvous actor, not a
    device mesh."""
    return _manager.get(group_name).mesh
