"""ICI-native collective engine: the group IS a named Mesh, and every group
op is one compiled shard_map program over it.

SURVEY.md §7: on TPU, ICI collectives only exist *inside compiled programs* —
a host-mediated rendezvous actor can be correct but never fast. This module
lowers each collective to the corresponding `jax.lax` primitive under
shard_map:

    allreduce      -> lax.psum / lax.pmax / lax.pmin
                      (PRODUCT: all_gather + prod — jax has no pprod)
    allgather      -> lax.all_gather          (retires the one-hot world×
                                               host buffer the old path built)
    reducescatter  -> lax.psum_scatter        (SUM; other ops reduce+slice)
    broadcast      -> log2(world) ppermute tree (jax.lax.ppermute requires
                      unique sources, so one-to-many is a doubling tree)
    send/recv      -> lax.ppermute [(src, dst)]
    barrier        -> tiny psum

Compiled programs are cached per `(op, shape, dtype, extras)` on the engine,
and device staging is cached by input-buffer identity so repeated collectives
on the same host buffer skip the per-call np.asarray + device_put round trip
entirely (`stage_local` / `stage_parts`).

Single-controller (tests, benchmarks): build the engine over a 1-D mesh of
all local devices and stage every rank's contribution with `stage_parts`.
Multi-controller (TPU pods): each jax.distributed process owns one device of
the group's ici mesh and stages only its own shard with `stage_local`.

NOTE on the staging cache: a hit requires the SAME array object (identity,
held by weakref) — mutating a cached buffer in place and re-issuing the
collective is safe because numpy arrays passed to jax are copied at
device_put time, but the cache would then serve the OLD bytes. Call
`invalidate(arr)` (or pass a fresh array) after in-place mutation.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

SUM, PRODUCT, MIN, MAX = "sum", "product", "min", "max"

_STAGE_CACHE_CAP = 32

# -- telemetry (docs/observability.md) ----------------------------------------
_LAT = None
_BYTES = None


def _observe(op: str, group: str, nbytes: int, dt: float) -> None:
    global _LAT, _BYTES
    if _LAT is None:
        from ray_tpu._private import telemetry

        _LAT = telemetry.histogram(
            "collective",
            "op_latency_s",
            "wall time of one compiled group op (stage + dispatch + sync)",
            buckets=telemetry.LATENCY_BUCKETS_S,
        )
        _BYTES = telemetry.counter(
            "collective",
            "bytes",
            "payload bytes contributed per rank through group ops",
        )
    _LAT.cell(op=op, group=group).observe(dt)
    _BYTES.cell(op=op, group=group).inc(nbytes)
    # Every collective funnels through here (store + xla backends), so this
    # is the one place a group op becomes a trace span when the caller is
    # inside a traced task.
    from ray_tpu._private import rpc

    if rpc._trace_ctx.get() is not None:
        import time as _time

        from ray_tpu.util import tracing

        tracing.record_span(
            f"collective.{op}",
            "collective",
            _time.time() - dt,
            dt,
            group=group,
            nbytes=nbytes,
        )


def _shard_map():
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm

    return sm


class MeshCollectives:
    """Compiled group ops over one mesh axis.

    The mesh's `axis` dimension is the rank dimension: device i along it is
    rank i. All op inputs are "staged" global arrays of shape
    ``(world,) + local_shape`` sharded ``P(axis)`` — one row per rank.
    """

    def __init__(self, mesh, axis: str = "world", group_name: str = "default"):
        self.mesh = mesh
        self.axis = axis
        self.group_name = group_name
        self.world = int(mesh.shape[axis])
        self._programs: Dict[tuple, Any] = {}
        self._shardings: Dict[tuple, Any] = {}
        # identity-keyed device staging cache: (id, rank) -> (wref, staged)
        self._staged: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._barrier_input = None
        # host-staging accounting, asserted by the allgather regression test:
        # staged_bytes counts host->device bytes actually copied (cache
        # misses only), so an allgather of a 1 MiB shard adds 1 MiB — not
        # world x 1 MiB like the retired one-hot expansion did.
        self.stats = {"staged_bytes": 0, "stage_hits": 0, "stage_misses": 0}

    # -- sharding / program caches -------------------------------------------

    def _sharding(self, *parts):
        key = parts
        s = self._shardings.get(key)
        if s is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            s = NamedSharding(self.mesh, P(*parts))
            self._shardings[key] = s
        return s

    def _program(self, key: tuple, build):
        fn = self._programs.get(key)
        if fn is None:
            fn = build()
            self._programs[key] = fn
        return fn

    def _smap(self, body, out_parts):
        import jax
        from jax.sharding import PartitionSpec as P

        return jax.jit(
            _shard_map()(
                body,
                mesh=self.mesh,
                in_specs=P(self.axis),
                out_specs=P(*out_parts),
                check_rep=False,
            )
        )

    # -- staging --------------------------------------------------------------

    def _row_devices(self, rank: int):
        """Devices forming rank's row of the mesh (1 for the ici mesh)."""
        devs = np.asarray(self.mesh.devices)
        axis_pos = self.mesh.axis_names.index(self.axis)
        row = np.moveaxis(devs, axis_pos, 0)[rank]
        return list(np.atleast_1d(row).flat)

    def _cache_get(self, arr, rank: int):
        key = (id(arr), rank)
        ent = self._staged.get(key)
        if ent is not None:
            ref, staged = ent
            if ref() is arr:
                self._staged.move_to_end(key)
                self.stats["stage_hits"] += 1
                return staged
            del self._staged[key]
        return None

    def _cache_put(self, arr, rank: int, staged) -> None:
        import weakref

        try:
            ref = weakref.ref(arr)
        except TypeError:
            return  # not weakref-able (e.g. plain list): skip caching
        self._staged[(id(arr), rank)] = (ref, staged)
        while len(self._staged) > _STAGE_CACHE_CAP:
            self._staged.popitem(last=False)

    def invalidate(self, arr) -> None:
        """Drop any staged copies of `arr` (call after in-place mutation)."""
        for key in [k for k in self._staged if k[0] == id(arr)]:
            self._staged.pop(key, None)

    def _is_staged(self, arr) -> bool:
        import jax

        return (
            isinstance(arr, jax.Array)
            and arr.ndim >= 1
            and arr.shape[0] == self.world
            and arr.sharding == self._sharding(self.axis)
        )

    def stage_local(self, arr, rank: int, cache: bool = True):
        """Stage THIS rank's contribution into the global (world,)+S array.

        Multi-controller: only this process's addressable row is filled;
        peers stage their own rows and the runtime stitches the global view.
        Device-resident jax.Arrays already carrying the staged sharding pass
        through untouched.
        """
        import jax

        if self._is_staged(arr):
            return arr
        if cache:
            hit = self._cache_get(arr, rank)
            if hit is not None:
                return hit
        local = np.asarray(arr)
        global_shape = (self.world,) + local.shape
        sharding = self._sharding(self.axis)
        row = set(self._row_devices(rank))
        # Multi-controller: only this rank's row is addressable, so exactly
        # the local payload is copied. Single-controller: the sharding spans
        # every device, so the other rows are zero-filled (the reduce
        # identity for the psum/ppermute paths that consume stage_local).
        zeros = None
        shards, copied = [], 0
        for d in sharding.addressable_devices:
            if d in row:
                shards.append(jax.device_put(local[None], d))
                copied += local.nbytes
            else:
                if zeros is None:
                    zeros = np.zeros((1,) + local.shape, local.dtype)
                shards.append(jax.device_put(zeros, d))
        staged = jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards
        )
        self.stats["stage_misses"] += 1
        self.stats["staged_bytes"] += copied
        if cache:
            self._cache_put(arr, rank, staged)
        return staged

    def stage_parts(self, parts: Sequence[Any], cache_token=None):
        """Single-controller staging: one contribution per rank (tests and
        benchmarks drive all `world` ranks from one process)."""
        import jax

        if len(parts) != self.world:
            raise ValueError(
                f"stage_parts wants {self.world} rank contributions, "
                f"got {len(parts)}"
            )
        if cache_token is not None:
            hit = self._cache_get(cache_token, -1)
            if hit is not None:
                return hit
        rows = [np.asarray(p) for p in parts]
        shards = []
        for rank, row in enumerate(rows):
            for d in self._row_devices(rank):
                shards.append(jax.device_put(row[None], d))
        global_shape = (self.world,) + rows[0].shape
        staged = jax.make_array_from_single_device_arrays(
            global_shape, self._sharding(self.axis), shards
        )
        self.stats["stage_misses"] += 1
        self.stats["staged_bytes"] += sum(r.nbytes for r in rows)
        if cache_token is not None:
            self._cache_put(cache_token, -1, staged)
        return staged

    def rank_shard(self, garr, rank: int) -> np.ndarray:
        """Host copy of rank's block of a P(axis)-sharded result."""
        block = garr.shape[0] // self.world
        for s in garr.addressable_shards:
            idx = s.index[0]
            start = 0 if idx.start is None else idx.start
            if start == rank * block:
                return np.asarray(s.data)
        raise ValueError(
            f"rank {rank}'s shard is not addressable from this process"
        )

    # -- compiled ops ---------------------------------------------------------

    def _timed(self, op: str, garr, fn):
        t0 = time.perf_counter()
        out = fn(garr)
        out.block_until_ready()
        _observe(
            op,
            self.group_name,
            garr.nbytes // max(self.world, 1),
            time.perf_counter() - t0,
        )
        return out

    def allreduce(self, garr, op: str = SUM):
        """(world,)+S staged -> replicated S."""
        import jax
        import jax.numpy as jnp

        axis = self.axis
        key = ("allreduce", op, garr.shape, str(garr.dtype))

        def build():
            if op == SUM:
                body = lambda x: jax.lax.psum(jnp.squeeze(x, 0), axis)
            elif op == MAX:
                body = lambda x: jax.lax.pmax(jnp.squeeze(x, 0), axis)
            elif op == MIN:
                body = lambda x: jax.lax.pmin(jnp.squeeze(x, 0), axis)
            elif op == PRODUCT:
                # no pprod primitive: gather the rank dimension and reduce
                body = lambda x: jnp.prod(
                    jax.lax.all_gather(jnp.squeeze(x, 0), axis, axis=0),
                    axis=0,
                )
            else:
                raise ValueError(f"unknown reduce op {op!r}")
            return self._smap(body, ())

        return self._timed("allreduce", garr, self._program(key, build))

    def allgather(self, garr):
        """(world,)+S staged -> replicated (world,)+S. Each rank stages only
        its own shard; the gather happens inside the compiled program (no
        world× host allocation anywhere)."""
        import jax
        import jax.numpy as jnp

        axis = self.axis
        key = ("allgather", garr.shape, str(garr.dtype))

        def build():
            body = lambda x: jax.lax.all_gather(
                jnp.squeeze(x, 0), axis, axis=0
            )
            return self._smap(body, ())

        return self._timed("allgather", garr, self._program(key, build))

    def reducescatter(self, garr, op: str = SUM):
        """(world,)+T staged (full tensor per rank, T[0] divisible by world)
        -> P(axis) global T; rank i's block is rank_shard(out, i)."""
        import jax
        import jax.numpy as jnp

        axis, world = self.axis, self.world
        if garr.shape[1] % world != 0:
            raise ValueError(
                f"reducescatter needs dim0 {garr.shape[1]} divisible by "
                f"world {world}"
            )
        key = ("reducescatter", op, garr.shape, str(garr.dtype))

        def build():
            if op == SUM:
                body = lambda x: jax.lax.psum_scatter(
                    jnp.squeeze(x, 0), axis, scatter_dimension=0, tiled=True
                )
            else:
                block = garr.shape[1] // world

                def body(x):
                    v = jnp.squeeze(x, 0)
                    if op == MAX:
                        red = jax.lax.pmax(v, axis)
                    elif op == MIN:
                        red = jax.lax.pmin(v, axis)
                    elif op == PRODUCT:
                        red = jnp.prod(
                            jax.lax.all_gather(v, axis, axis=0), axis=0
                        )
                    else:
                        raise ValueError(f"unknown reduce op {op!r}")
                    idx = jax.lax.axis_index(axis)
                    return jax.lax.dynamic_slice_in_dim(
                        red, idx * block, block
                    )

            return self._smap(body, (axis,))

        return self._timed("reducescatter", garr, self._program(key, build))

    def broadcast(self, garr, src: int):
        """(world,)+S staged -> P(axis) (world,)+S where every row is src's.

        jax.lax.ppermute forbids duplicate sources, so one-to-many runs as a
        doubling tree: round r moves the value from the 2^r ranks that hold
        it to the next 2^r (log2(world) ppermute hops — on TPU each is one
        ICI traversal, exactly how XLA lowers collective-broadcast)."""
        import jax
        import jax.numpy as jnp

        axis, world = self.axis, self.world
        key = ("broadcast", src, garr.shape, str(garr.dtype))

        def build():
            def body(x):
                v = x  # keep the (1,)+S block so out P(axis) re-stacks rows
                idx = jax.lax.axis_index(axis)
                t = (idx - src) % world  # shifted rank: src is t=0
                span = 1
                while span < world:
                    perm = [
                        ((u + src) % world, (u + span + src) % world)
                        for u in range(span)
                        if u + span < world
                    ]
                    moved = jax.lax.ppermute(v, axis, perm=perm)
                    recv = (t >= span) & (t < 2 * span)
                    v = jnp.where(recv, moved, v)
                    span *= 2
                return v

            return self._smap(body, (axis,))

        return self._timed("broadcast", garr, self._program(key, build))

    def permute(self, garr, perm: Sequence[Tuple[int, int]]):
        """(world,)+S staged -> P(axis) (world,)+S: row dst takes row src for
        each (src, dst) pair; rows that are no pair's destination get zeros.
        This is the send/recv and compiled-channel payload hop."""
        import jax

        axis = self.axis
        perm = tuple((int(s), int(d)) for s, d in perm)
        key = ("permute", perm, garr.shape, str(garr.dtype))

        def build():
            # no squeeze: the (1,)+S block shape survives the hop so the
            # P(axis) output re-stacks to (world,)+S
            body = lambda x: jax.lax.ppermute(x, axis, perm=list(perm))
            return self._smap(body, (axis,))

        return self._timed("permute", garr, self._program(key, build))

    def barrier(self) -> None:
        """All ranks rendezvous inside one tiny compiled psum."""
        import jax

        if self._barrier_input is None:
            if jax.process_count() > 1:
                self._barrier_input = self.stage_local(
                    np.ones(1, dtype=np.float32), jax.process_index()
                )
            else:
                self._barrier_input = self.stage_parts(
                    [np.ones(1, dtype=np.float32)] * self.world
                )
        out = self.allreduce(self._barrier_input, SUM)
        out.block_until_ready()

    # -- mesh-rebased attention (parallel/ring_attention.py, ulysses.py) ------

    def _stage_seq(self, x, seq_dim: int = 1):
        """Stage a [B, T, H, D]-style array sequence-sharded over the group
        axis. Single-controller: x is the global array. Multi-controller: x
        is this process's local sequence shard."""
        import jax

        sharding_parts = [None] * np.asarray(x).ndim
        sharding_parts[seq_dim] = self.axis
        sharding = self._sharding(*sharding_parts)
        if jax.process_count() <= 1:
            return jax.device_put(np.asarray(x), sharding)
        local = np.asarray(x)
        rank = jax.process_index()
        global_shape = list(local.shape)
        global_shape[seq_dim] = local.shape[seq_dim] * self.world
        shards = [jax.device_put(local, d) for d in self._row_devices(rank)]
        return jax.make_array_from_single_device_arrays(
            tuple(global_shape), sharding, shards
        )

    def ring_attention(self, q, k, v, causal: bool = False):
        """Ring attention over the group mesh with the group's compiled
        program cache (parallel/ring_attention.py rebased onto the engine:
        same kernel, but the shard_map program is built once per
        (shape, dtype, causal) instead of re-traced per call)."""
        import functools

        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.ring_attention import ring_attention as _ring

        qs = self._stage_seq(q)
        ks = self._stage_seq(k)
        vs = self._stage_seq(v)
        key = ("ring_attention", qs.shape, ks.shape, str(qs.dtype), causal)

        def build():
            import jax

            fn = functools.partial(
                _ring,
                axis_name=self.axis,
                axis_size=self.world,
                causal=causal,
                pvary_axes=(self.axis,),
            )
            spec = P(None, self.axis, None, None)
            return jax.jit(
                _shard_map()(
                    fn,
                    mesh=self.mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    check_rep=False,
                )
            )

        t0 = time.perf_counter()
        out = self._program(key, build)(qs, ks, vs)
        out.block_until_ready()
        _observe(
            "ring_attention",
            self.group_name,
            qs.nbytes // max(self.world, 1),
            time.perf_counter() - t0,
        )
        return out

    def ulysses_attention(self, q, k, v, causal: bool = False):
        """Ulysses all-to-all attention over the group mesh, compiled and
        cached like ring_attention. Heads must divide by world."""
        import functools

        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.ulysses import ulysses_attention as _ulysses

        qs = self._stage_seq(q)
        ks = self._stage_seq(k)
        vs = self._stage_seq(v)
        key = ("ulysses", qs.shape, ks.shape, str(qs.dtype), causal)

        def build():
            import jax

            fn = functools.partial(
                _ulysses, axis_name=self.axis, causal=causal
            )
            spec = P(None, self.axis, None, None)
            return jax.jit(
                _shard_map()(
                    fn,
                    mesh=self.mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    check_rep=False,
                )
            )

        t0 = time.perf_counter()
        out = self._program(key, build)(qs, ks, vs)
        out.block_until_ready()
        _observe(
            "ulysses_attention",
            self.group_name,
            qs.nbytes // max(self.world, 1),
            time.perf_counter() - t0,
        )
        return out
