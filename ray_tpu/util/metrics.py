"""Application metrics API: Counter / Gauge / Histogram.

Analog of python/ray/util/metrics (backed by the reference's OpenCensus C++
pipeline, src/ray/stats/metric.h): metrics recorded anywhere in the cluster
are aggregated in the GCS KV by (name, labels) and exported in Prometheus
text format by the dashboard's /metrics endpoint (the reference's
MetricsAgent role, python/ray/_private/metrics_agent.py:483).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

METRICS_NS = "_metrics"
_FLUSH_INTERVAL_S = 2.0

_registry_lock = threading.Lock()
_registry: List["Metric"] = []
_flusher_started = False


def _labels_key(labels: Dict[str, str]) -> str:
    return json.dumps(sorted(labels.items()))


class Metric:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Optional[Sequence[str]] = None,
    ):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[str, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"undeclared tag keys {extra} for metric {self.name}")
        return merged

    def _snapshot(self) -> List[Tuple[str, float]]:
        with self._lock:
            return list(self._values.items())


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = _labels_key(self._resolve_tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = _labels_key(self._resolve_tags(tags))
        with self._lock:
            self._values[key] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[Sequence[float]] = None,
        tag_keys: Optional[Sequence[str]] = None,
    ):
        super().__init__(name, description, tag_keys)
        self.boundaries = list(boundaries or [0.01, 0.1, 1, 10, 100])
        self._counts: Dict[str, List[int]] = {}
        self._sums: Dict[str, float] = {}
        self._totals: Dict[str, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = _labels_key(self._resolve_tags(tags))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def _snapshot_hist(self):
        with self._lock:
            return (
                {k: list(v) for k, v in self._counts.items()},
                dict(self._sums),
                dict(self._totals),
            )


def _collect_local() -> Dict[str, dict]:
    """Serialize this process's metric state for the GCS."""
    out: Dict[str, dict] = {}
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        entry = out.setdefault(
            m.name,
            {"kind": m.kind, "description": m.description, "series": {}},
        )
        if isinstance(m, Histogram):
            counts, sums, totals = m._snapshot_hist()
            entry["boundaries"] = m.boundaries
            for key in counts:
                entry["series"][key] = {
                    "counts": counts[key],
                    "sum": sums[key],
                    "total": totals[key],
                }
        else:
            for key, v in m._snapshot():
                entry["series"][key] = v
    return out


def _flush_once() -> None:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if not w.connected:
        return
    core = w.core
    payload = _collect_local()
    if not payload:
        return
    # Stamped so the dashboard can age out snapshots from dead workers
    # (a worker that stops flushing must not serve its last values forever).
    payload["_ts"] = time.time()
    key = f"{core.worker_id}"

    async def _push():
        await core.gcs.kv_put(key, json.dumps(payload).encode(), ns=METRICS_NS)

    try:
        w.run_async(_push(), timeout=5)
    except Exception:
        pass


def _ensure_flusher() -> None:
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            _flush_once()

    threading.Thread(target=loop, name="ray_tpu_metrics_flush", daemon=True).start()


# -- export (dashboard side) ---------------------------------------------------


def render_prometheus(per_worker: Dict[str, dict]) -> str:
    """Merge per-worker snapshots into Prometheus text exposition format."""
    merged: Dict[str, dict] = {}
    for snapshot in per_worker.values():
        for name, entry in snapshot.items():
            if name.startswith("_"):  # bookkeeping keys ("_ts"), not metrics
                continue
            dst = merged.setdefault(
                name,
                {
                    "kind": entry["kind"],
                    "description": entry.get("description", ""),
                    "boundaries": entry.get("boundaries"),
                    "series": {},
                },
            )
            for key, v in entry["series"].items():
                if entry["kind"] == "histogram":
                    cur = dst["series"].setdefault(
                        key,
                        {"counts": [0] * (len(entry["boundaries"]) + 1), "sum": 0.0, "total": 0},
                    )
                    cur["counts"] = [
                        a + b for a, b in zip(cur["counts"], v["counts"])
                    ]
                    cur["sum"] += v["sum"]
                    cur["total"] += v["total"]
                elif entry["kind"] == "counter":
                    dst["series"][key] = dst["series"].get(key, 0.0) + v
                else:
                    dst["series"][key] = v  # gauge: last writer wins

    lines: List[str] = []
    for name, entry in sorted(merged.items()):
        pname = name.replace(".", "_").replace("-", "_")
        if entry["description"]:
            lines.append(f"# HELP {pname} {entry['description']}")
        lines.append(f"# TYPE {pname} {entry['kind']}")
        for key, v in entry["series"].items():
            labels = dict(json.loads(key))
            label_str = ",".join(f'{k}="{val}"' for k, val in sorted(labels.items()))
            braces = f"{{{label_str}}}" if label_str else ""
            if entry["kind"] == "histogram":
                cum = 0
                for bound, c in zip(entry["boundaries"], v["counts"]):
                    cum += c
                    lb = label_str + ("," if label_str else "") + f'le="{bound}"'
                    lines.append(f"{pname}_bucket{{{lb}}} {cum}")
                cum += v["counts"][-1]
                lb = label_str + ("," if label_str else "") + 'le="+Inf"'
                lines.append(f"{pname}_bucket{{{lb}}} {cum}")
                lines.append(f"{pname}_sum{braces} {v['sum']}")
                lines.append(f"{pname}_count{braces} {v['total']}")
            else:
                lines.append(f"{pname}{braces} {v}")
    return "\n".join(lines) + "\n"
