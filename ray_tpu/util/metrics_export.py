"""Prometheus file-based service discovery + generated Grafana dashboards
(reference: python/ray/_private/metrics_agent.py:595
PrometheusServiceDiscoveryWriter and dashboard/modules/metrics/ — the
grafana_*_dashboard generators + file-SD output a stock Prometheus config
consumes via:

    scrape_configs:
      - job_name: ray_tpu
        file_sd_configs:
          - files: ['/tmp/ray_tpu/prom_metrics_service_discovery.json']
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

DEFAULT_SD_FILENAME = "prom_metrics_service_discovery.json"


class PrometheusServiceDiscoveryWriter:
    """Periodically writes the cluster's metrics endpoints in Prometheus
    <file_sd_config> format: a JSON list of {"targets": [...], "labels":
    {...}} groups. Writes are atomic (tmp + rename) so Prometheus never
    reads a torn file."""

    def __init__(
        self,
        get_targets: Callable[[], List[str]],
        out_dir: str,
        filename: str = DEFAULT_SD_FILENAME,
        labels: Optional[Dict[str, str]] = None,
        interval_s: float = 5.0,
    ):
        self._get_targets = get_targets
        self.path = os.path.join(out_dir, filename)
        self.labels = {"job": "ray_tpu", **(labels or {})}
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def write_once(self) -> str:
        targets = sorted(set(self._get_targets()))
        payload = [{"labels": self.labels, "targets": targets}]
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)
        return self.path

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.write_once()
                except Exception:
                    pass

        self.write_once()
        self._thread = threading.Thread(
            target=loop, daemon=True, name="prom-file-sd"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


# -- Grafana ---------------------------------------------------------------

# Core panels generated for every cluster (reference:
# dashboard/modules/metrics/dashboards/default_dashboard_panels.py).
_DEFAULT_PANELS = [
    ("Scheduler Tasks", "ray_tpu_tasks_total", "rate(ray_tpu_tasks_total[1m])"),
    ("Live Actors", "ray_tpu_actors", "ray_tpu_actors"),
    ("Object Store Used Bytes", "ray_tpu_object_store_used_bytes",
     "ray_tpu_object_store_used_bytes"),
    ("Pending Worker Leases", "ray_tpu_pending_leases",
     "ray_tpu_pending_leases"),
    ("Node Count", "ray_tpu_nodes", "ray_tpu_nodes"),
]


def generate_grafana_dashboard(
    extra_metrics: Optional[List[str]] = None, title: str = "Ray TPU Core"
) -> dict:
    """A stock-importable Grafana dashboard JSON covering the core metrics
    plus any caller-registered metric names (each becomes a graph panel
    querying Prometheus for the metric)."""
    panels = []
    specs = list(_DEFAULT_PANELS) + [
        (name, name, name) for name in (extra_metrics or [])
    ]
    for i, (ptitle, _metric, expr) in enumerate(specs):
        panels.append(
            {
                "id": i + 1,
                "title": ptitle,
                "type": "timeseries",
                "datasource": {"type": "prometheus", "uid": "${datasource}"},
                "targets": [{"expr": expr, "refId": "A"}],
                "gridPos": {"h": 8, "w": 12, "x": 12 * (i % 2), "y": 8 * (i // 2)},
            }
        )
    return {
        "title": title,
        "uid": "ray-tpu-core",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [
                {
                    "name": "datasource",
                    "type": "datasource",
                    "query": "prometheus",
                }
            ]
        },
        "panels": panels,
    }


def write_grafana_dashboards(out_dir: str, extra_metrics=None) -> str:
    """Write the generated dashboard JSON where a Grafana provisioning
    config can pick it up (reference: metrics head writes
    grafana/dashboards/*.json under the session dir)."""
    path = os.path.join(out_dir, "grafana", "dashboards")
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "ray_tpu_core_dashboard.json")
    with open(out, "w") as f:
        json.dump(generate_grafana_dashboard(extra_metrics), f, indent=2)
    return out
