"""Scheduling strategies (analog of python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


# Label match operators (reference: python/ray/util/scheduling_strategies.py
# In/NotIn/Exists/DoesNotExist used by NodeLabelSchedulingStrategy).


class In:
    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def to_wire(self):
        return {"op": "in", "values": self.values}


class NotIn:
    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def to_wire(self):
        return {"op": "not_in", "values": self.values}


class Exists:
    def to_wire(self):
        return {"op": "exists"}


class DoesNotExist:
    def to_wire(self):
        return {"op": "does_not_exist"}


def _expr_to_wire(expr):
    if isinstance(expr, (In, NotIn, Exists, DoesNotExist)):
        return expr.to_wire()
    # Plain value = equality (sugar over In(value)).
    return {"op": "in", "values": [str(expr)]}


class NodeLabelSchedulingStrategy:
    """Schedule on nodes matching label expressions (reference:
    NodeLabelSchedulingStrategy + the NODE_LABEL policy in
    src/ray/raylet/scheduling/policy/scheduling_options.h:30-44).

    hard: every expression must match or the node is ineligible.
    soft: preferred — among hard-eligible nodes, those also matching soft
    win; if none match soft, hard-eligible nodes are still used.
    """

    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        if not hard and not soft:
            raise ValueError("NodeLabelSchedulingStrategy needs hard or soft")
        self.hard = dict(hard or {})
        self.soft = dict(soft or {})

    def to_wire(self) -> dict:
        return {
            "labels": {
                "hard": {k: _expr_to_wire(v) for k, v in self.hard.items()},
                "soft": {k: _expr_to_wire(v) for k, v in self.soft.items()},
            }
        }


def match_label_expr(expr: dict, labels: dict, key: str) -> bool:
    """Evaluate one wire expression against a node's label map."""
    op = expr.get("op")
    present = key in labels
    if op == "exists":
        return present
    if op == "does_not_exist":
        return not present
    if op == "in":
        return present and str(labels[key]) in expr.get("values", [])
    if op == "not_in":
        # Reference semantics: a missing label trivially satisfies NotIn.
        return not present or str(labels[key]) not in expr.get("values", [])
    return False


def node_matches_labels(exprs: dict, labels: dict) -> bool:
    return all(match_label_expr(e, labels or {}, k) for k, e in exprs.items())
