"""Shared pieces of the client protocol (both ends import this).

Analog of python/ray/util/client/common.py: ClientObjectRef /
ClientActorHandle are thin handles around ids owned by a server-side proxy
session; the session's CoreWorker is the real owner of every object the
client touches (src/ray/protobuf/ray_client.proto:326 message shapes).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ray_tpu._private import serialization


class ClientObjectRef:
    """Client-side handle to an object owned by the proxy session's core
    worker. Serializes exactly like a plain ObjectRef (hex + owner addr) so
    it can ride inside task args and deserialize cluster-side as a real,
    resolvable reference."""

    __slots__ = ("_hex", "_owner_addr", "_ctx", "__weakref__")

    def __init__(self, hex_id: str, owner_addr: Tuple[str, int], ctx=None):
        self._hex = hex_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._ctx = ctx

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    @property
    def owner_addr(self):
        return self._owner_addr

    def __repr__(self):
        return f"ClientObjectRef({self._hex})"

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return getattr(other, "_hex", None) == self._hex and (
            isinstance(other, ClientObjectRef) or type(other).__name__ == "ObjectRef"
        )

    def __reduce__(self):
        # Record for dependency counting during client-side serialize, then
        # pickle to a plain cluster-side ref (the session core is the owner).
        serialization.record_contained_ref(self)
        from ray_tpu._private.core_worker import _plain_ref

        return (_plain_ref, (self._hex, self._owner_addr))

    def __del__(self):
        ctx = self._ctx
        if ctx is not None and not ctx.closed:
            try:
                ctx._schedule_release(self._hex)
            except Exception:
                pass


def payload_to_bytes(payload) -> bytes:
    return bytes(payload) if isinstance(payload, memoryview) else payload
