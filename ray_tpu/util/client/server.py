"""Client proxy server: the cluster side of the remote-driver protocol.

Analog of python/ray/util/client/server/server.py + proxier.py: ONE endpoint
on the head node through which a remote interactive driver reaches the whole
cluster. Each connected client gets a server-side Session holding a real
driver CoreWorker (own job id, own object ownership); client handles are ids
into that session. Values never deserialize in the proxy — puts, task args,
and get results move as opaque serialized payloads, because only the client
and the task workers have the user's code (reference: ray_client.proto:326
dataplane messages).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

from ray_tpu._private import rpc, serialization
from ray_tpu._private.common import ResourceSet, config
from ray_tpu._private.core_worker import CoreWorker, ObjectRef
from ray_tpu._private.ids import (
    JobID,
    ObjectID,
    WorkerID,
    fast_unique_hex,
    return_object_ids,
)

logger = logging.getLogger(__name__)


class Session:
    """One connected client's server-side driver state."""

    def __init__(self, core: CoreWorker, namespace: Optional[str]):
        self.core = core
        self.namespace = namespace
        # Pin every ref the client can still name; CRelease drops them.
        self.refs: Dict[str, ObjectRef] = {}

    def pin(self, refs) -> None:
        for r in refs:
            self.refs[r.hex()] = r

    def lookup(self, oid: str, owner_addr=None) -> ObjectRef:
        r = self.refs.get(oid)
        if r is not None:
            return r
        # Borrowed ref (e.g. returned nested inside a result): resolve via
        # the recorded owner.
        return ObjectRef(oid, tuple(owner_addr) if owner_addr else self.core.addr, self.core)

    async def close(self) -> None:
        self.refs.clear()
        try:
            await asyncio.wait_for(
                self.core.gcs.call("JobFinished", {"job_id": self.core.job_id}), 5
            )
        except Exception:
            pass
        try:
            await self.core.close()
        except Exception:
            pass


class ClientServer:
    """The proxy endpoint. Run on (or near) the head node:
    ``ClientServer(gcs_addr).start(port)``; clients connect with
    ``ray_tpu.init(address="ray-tpu://host:port")``."""

    def __init__(self, gcs_addr: Tuple[str, int], host: str = "127.0.0.1", port: int = 0):
        self.gcs_addr = tuple(gcs_addr)
        self.server = rpc.Server(host, port)
        self.sessions: Dict[int, Session] = {}
        s = self.server
        s.register("CHello", self._hello)
        s.register("CPut", self._put)
        s.register("CTask", self._task)
        s.register("CActorCreate", self._actor_create)
        s.register("CActorCall", self._actor_call)
        s.register("CGet", self._get)
        s.register("CWait", self._wait)
        s.register("CRelease", self._release)
        s.register("CKill", self._kill)
        s.register("CCancel", self._cancel)
        s.register("CGetActor", self._get_actor)
        s.register("CClusterInfo", self._cluster_info)
        s.on_disconnect(self._on_disconnect)

    async def start(self) -> Tuple[str, int]:
        self.addr = await self.server.start()
        logger.info("client server on %s:%s -> gcs %s", *self.addr, self.gcs_addr)
        return self.addr

    async def stop(self) -> None:
        for sess in list(self.sessions.values()):
            await sess.close()
        self.sessions.clear()
        await self.server.stop()

    # -- session lifecycle ---------------------------------------------------

    def _session(self, conn) -> Session:
        sess = self.sessions.get(id(conn))
        if sess is None:
            raise rpc.RpcError("no session; send CHello first")
        return sess

    def _on_disconnect(self, conn) -> None:
        sess = self.sessions.pop(id(conn), None)
        if sess is not None:
            rpc.spawn(sess.close())

    async def _hello(self, conn, p):
        gcs_conn0 = await rpc.connect(*self.gcs_addr)
        reply = await gcs_conn0.call("GetAllNodes")
        await gcs_conn0.close()
        alive = [n for n in reply["nodes"] if n["state"] == "ALIVE"]
        if not alive:
            raise rpc.RpcError("no alive nodes in cluster")
        raylet_addr = tuple(alive[0]["addr"])
        server = rpc.Server("127.0.0.1", 0)
        addr = await server.start()
        raylet_conn = await rpc.connect(*raylet_addr, handlers=server._handlers)
        gcs_conn = await rpc.connect(*self.gcs_addr, handlers=server._handlers)
        job_id = JobID.from_random().hex()
        core = CoreWorker(
            job_id=job_id,
            session_name="client",
            node_id="client-proxy",
            gcs_conn=gcs_conn,
            raylet_conn=raylet_conn,
            is_driver=True,
            worker_id=WorkerID.from_random().hex(),
            server=server,
        )
        core.addr = addr
        core.raylet_addr = raylet_addr
        core.start_background()
        # Register the session BEFORE any further awaits: a client that
        # drops mid-handshake must be findable by _on_disconnect, or the
        # proxy-side CoreWorker and its job leak forever.
        sess = Session(core, p.get("namespace"))
        self.sessions[id(conn)] = sess
        await core.gcs.call("RegisterJob", {"job_id": job_id, "driver_addr": list(addr)})
        if config.log_to_driver:
            # Forward this job's worker logs to the remote client.
            def fwd(msg, _conn=conn, _job=job_id):
                if msg.get("job_id") in (None, _job):
                    try:
                        _conn.push_nowait("CLog", msg)
                    except rpc.ConnectionLost:
                        pass

            await core.gcs.subscribe("logs", fwd)
        if conn.closed:
            # Dropped during the handshake awaits; disconnect may have fired
            # before our registration landed.
            if self.sessions.pop(id(conn), None) is not None:
                await sess.close()
            raise rpc.RpcError("client disconnected during handshake")
        return {"job_id": job_id, "owner_addr": list(addr)}

    # -- data plane ----------------------------------------------------------

    async def _put(self, conn, p):
        sess = self._session(conn)
        core = sess.core
        oid = ObjectID.from_random().hex()
        # Small puts carry "payload" inline in the control frame; large puts
        # arrive as a blob sidecar injected by the RPC layer as "data".
        payload = p["data"] if "data" in p else p["payload"]
        if len(payload) <= config.max_direct_call_object_size:
            core.memory_store.put_inline(oid, payload)
        else:
            await core.plasma.put_bytes(oid, payload)
            core.memory_store.put_plasma_marker(oid, core.raylet_addr)
        core.reference_table.mark_owned(oid)
        ref = ObjectRef(oid, core.addr, core)
        sess.pin([ref])
        return {"oid": oid, "owner_addr": list(core.addr)}

    async def _task(self, conn, p):
        sess = self._session(conn)
        core = sess.core
        fn_blob = p.get("fn_blob")
        func_id = p["func_id"]
        if func_id not in core._func_ids_exported:
            if fn_blob is None:
                return {"need_fn": True}
            exported = await core.export_function(fn_blob)
            if exported != func_id:
                raise rpc.RpcError("function id mismatch")
        num_returns = p.get("num_returns", 1)
        if num_returns == "dynamic":
            num_returns = -1
        task_id = fast_unique_hex()
        return_ids = return_object_ids(
            task_id, 1 if num_returns == -1 else num_returns
        )
        res = ResourceSet(p.get("resources") or {"CPU": 1.0})
        args_blob, args_object = await self._stage_args(core, p["args_payload"])
        wire = core._task_wire(
            task_id=task_id,
            name=p.get("name", "task"),
            func_id=func_id,
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=p.get("ref_positions") or [],
            kw_ref_keys=p.get("kw_ref_keys") or [],
            dependencies=[(d[0], tuple(d[1])) for d in p.get("dependencies") or []],
            num_returns=num_returns,
            return_ids=return_ids,
            resources=res.to_units(),
            max_retries=(
                p["max_retries"]
                if p.get("max_retries") is not None
                else config.default_max_task_retries
            ),
            retry_exceptions=p.get("retry_exceptions", False),
            pg_id=p.get("pg_id"),
            bundle_index=p.get("bundle_index", -1),
            scheduling_strategy=p.get("scheduling_strategy"),
            runtime_env=p.get("runtime_env"),
        )
        refs = core._launch_task(wire)
        sess.pin(refs)
        return {"oids": return_ids, "owner_addr": list(core.addr)}

    async def _stage_args(self, core, payload):
        """Return (args_blob, args_object): small payloads inline; large ones
        into the session's plasma store."""
        if payload is None or len(payload) <= config.max_direct_call_object_size:
            return payload, None
        args_object = ObjectID.from_random().hex()
        await core.plasma.put_bytes(args_object, payload)
        core.memory_store.put_plasma_marker(args_object, core.raylet_addr)
        return None, args_object

    async def _actor_create(self, conn, p):
        sess = self._session(conn)
        core = sess.core
        opts = p.get("opts") or {}
        actor_id = await core.create_actor(
            p["cls_blob"],
            p.get("name", "Actor"),
            (),
            {},
            resources=opts.get("resources"),
            max_restarts=opts.get("max_restarts", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            max_task_retries=opts.get("max_task_retries", 0),
            concurrency_groups=opts.get("concurrency_groups"),
            name=opts.get("name"),
            namespace=opts.get("namespace") or sess.namespace,
            lifetime=opts.get("lifetime"),
            get_if_exists=opts.get("get_if_exists", False),
            scheduling_strategy=opts.get("scheduling_strategy"),
            runtime_env=opts.get("runtime_env"),
            prepared_args=(
                p.get("args_payload"),
                p.get("ref_positions") or [],
                p.get("kw_ref_keys") or [],
                [(d[0], tuple(d[1])) for d in p.get("dependencies") or []],
            ),
        )
        return {"actor_id": actor_id}

    async def _actor_call(self, conn, p):
        sess = self._session(conn)
        core = sess.core
        refs = await core.submit_actor_task(
            p["actor_id"],
            p["method"],
            (),
            {},
            num_returns=p.get("num_returns", 1),
            max_task_retries=p.get("max_task_retries", 0),
            concurrency_group=p.get("concurrency_group"),
            prepared_args=(
                p.get("args_payload"),
                p.get("ref_positions") or [],
                p.get("kw_ref_keys") or [],
                [(d[0], tuple(d[1])) for d in p.get("dependencies") or []],
            ),
        )
        sess.pin(refs)
        return {"oids": [r.hex() for r in refs], "owner_addr": list(core.addr)}

    async def _get(self, conn, p):
        sess = self._session(conn)
        core = sess.core
        deadline = None
        if p.get("timeout") is not None:
            import time as _time

            deadline = _time.monotonic() + p["timeout"]
        owners = p.get("owners") or [None] * len(p["oids"])
        fetched = await asyncio.gather(
            *(
                core._resolve_payload(sess.lookup(oid, owner), deadline)
                for oid, owner in zip(p["oids"], owners)
            )
        )
        payloads = {
            oid: bytes(pl) if isinstance(pl, memoryview) else pl
            for oid, pl in zip(p["oids"], fetched)
        }
        return {"payloads": payloads}

    async def _wait(self, conn, p):
        sess = self._session(conn)
        refs = [sess.lookup(oid, owner) for oid, owner in zip(p["oids"], p["owners"])]
        ready, not_ready = await sess.core.wait(
            refs, p.get("num_returns", 1), p.get("timeout")
        )
        return {
            "ready": [r.hex() for r in ready],
            "not_ready": [r.hex() for r in not_ready],
        }

    async def _release(self, conn, p):
        sess = self._session(conn)
        for oid in p["oids"]:
            sess.refs.pop(oid, None)
        return {"ok": True}

    async def _kill(self, conn, p):
        sess = self._session(conn)
        await sess.core.kill_actor(p["actor_id"], no_restart=p.get("no_restart", True))
        return {"ok": True}

    async def _cancel(self, conn, p):
        sess = self._session(conn)
        ref = sess.lookup(p["oid"], p.get("owner"))
        ok = await sess.core.cancel(ref, force=p.get("force", False))
        return {"ok": ok}

    async def _get_actor(self, conn, p):
        sess = self._session(conn)
        reply = await sess.core.gcs.call(
            "GetNamedActor",
            {
                "name": p["name"],
                "namespace": p.get("namespace") or sess.namespace or "default",
            },
        )
        actor = reply.get("actor")
        if actor is None or actor.get("state") == "DEAD":
            raise rpc.RpcError(f"no live actor named {p['name']!r}")
        return {
            "actor_id": actor["actor_id"],
            "max_task_retries": actor.get("max_task_retries", 0),
        }

    async def _cluster_info(self, conn, p):
        sess = self._session(conn)
        reply = await sess.core.gcs.call("GetAllNodes")
        return {"nodes": reply["nodes"]}


async def serve(gcs_addr, host: str = "127.0.0.1", port: int = 10001) -> ClientServer:
    srv = ClientServer(gcs_addr, host, port)
    await srv.start()
    return srv
