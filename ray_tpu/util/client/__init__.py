"""Remote interactive driver ("Ray Client" equivalent).

Analog of python/ray/util/client: a laptop/notebook process drives a remote
cluster through ONE proxy endpoint (`ray_tpu.init(address="ray-tpu://host:port")`)
— it never dials raylets or workers, holds only opaque handles, and all
values live cluster-side in the proxy session's object store. The top-level
API (`put/get/wait/remote/actors`) transparently routes here when the
session is in client mode (reference: ray_client.proto:326,
util/client/worker.py).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc, serialization
from ray_tpu._private.common import GetTimeoutError, RayTpuError, config
from ray_tpu.util.client.common import ClientObjectRef

__all__ = ["ClientContext", "ClientObjectRef", "connect"]


class ClientContext:
    """Client side of the proxy protocol. Owns a private event loop thread
    and one connection to the client server."""

    def __init__(self, host: str, port: int, namespace: Optional[str] = None):
        self.addr = (host, int(port))
        self.namespace = namespace
        self.closed = False
        self._release_buf: List[str] = []
        self._release_lock = threading.Lock()
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name="ray_tpu_client", daemon=True
        )
        self._thread.start()
        self.conn = self._run(self._connect(), timeout=30)
        hello = self._run(
            self.conn.call("CHello", {"namespace": namespace}), timeout=30
        )
        self.job_id = hello["job_id"]
        self.owner_addr = tuple(hello["owner_addr"])
        self._fn_ids_known: set = set()

    # -- loop plumbing -------------------------------------------------------

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def _run(self, coro, timeout=None):
        import concurrent.futures

        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError as e:
            # Not an alias of builtin TimeoutError until 3.11; name it.
            fut.cancel()
            raise GetTimeoutError(str(e) or "client call timed out") from e
        except rpc.RpcError as e:
            # Server-side errors arrive stringified as "TypeName: msg";
            # re-raise timeouts under their real type so `except
            # GetTimeoutError` behaves identically in client mode.
            if str(e).startswith("GetTimeoutError"):
                raise GetTimeoutError(str(e)) from e
            raise

    async def _connect(self):
        conn = await rpc.connect(
            *self.addr, handlers={"CLog": self._on_log}, retry=10
        )
        return conn

    async def _on_log(self, conn, msg):
        import sys

        tag = f"(pid={msg.get('pid')}, worker={str(msg.get('worker_id'))[:8]})"
        for line in msg.get("lines") or []:
            print(f"{tag} {line}", file=sys.stderr)

    # -- serialization helpers ----------------------------------------------

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Client-side analog of CoreWorker._prepare_args: find top-level
        client refs, serialize the rest, collect contained-ref deps."""
        ref_positions = [
            i for i, a in enumerate(args) if isinstance(a, ClientObjectRef)
        ]
        kw_ref_keys = [
            k for k, v in kwargs.items() if isinstance(v, ClientObjectRef)
        ]
        serialized = serialization.serialize((tuple(args), kwargs))
        deps = []
        seen = set()
        for r in serialized.contained_refs:
            if r.hex() not in seen:
                seen.add(r.hex())
                deps.append([r.hex(), list(r.owner_addr or self.owner_addr)])
        return serialized.to_bytes(), ref_positions, kw_ref_keys, deps

    def _make_refs(self, oids: List[str], owner) -> List[ClientObjectRef]:
        owner = tuple(owner) if owner else self.owner_addr
        return [ClientObjectRef(oid, owner, self) for oid in oids]

    # -- public API ----------------------------------------------------------

    def put(self, value: Any) -> ClientObjectRef:
        payload = serialization.serialize(value).to_bytes()
        if len(payload) > config.max_direct_call_object_size:
            # Large values ride as a blob sidecar: the serialized region goes
            # to the socket as raw bytes (no msgpack re-pack of the payload)
            # and lands server-side as p["data"].
            reply = self._run(
                self.conn.call_with_blob("CPut", {}, payload, timeout=300),
                timeout=310,
            )
        else:
            reply = self._run(
                self.conn.call("CPut", {"payload": payload}), timeout=300
            )
        return ClientObjectRef(reply["oid"], tuple(reply["owner_addr"]), self)

    def get(self, refs, timeout: Optional[float] = None):
        single = not isinstance(refs, (list, tuple))
        if single:
            refs = [refs]
        oids = [r.hex() for r in refs]
        owners = [list(getattr(r, "owner_addr", None) or self.owner_addr) for r in refs]
        reply = self._run(
            self.conn.call(
                "CGet", {"oids": oids, "owners": owners, "timeout": timeout},
                timeout=None if timeout is None else timeout + 30,
            ),
            timeout=None if timeout is None else timeout + 60,
        )
        values = []
        for oid in oids:
            value, is_exc = serialization.deserialize(reply["payloads"][oid])
            if is_exc:
                raise value
            values.append(value)
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        oids = [r.hex() for r in refs]
        owners = [list(getattr(r, "owner_addr", None) or self.owner_addr) for r in refs]
        reply = self._run(
            self.conn.call(
                "CWait",
                {
                    "oids": oids,
                    "owners": owners,
                    "num_returns": num_returns,
                    "timeout": timeout,
                    "fetch_local": fetch_local,
                },
            ),
            timeout=None if timeout is None else timeout + 60,
        )
        by_hex = {r.hex(): r for r in refs}
        return (
            [by_hex[h] for h in reply["ready"]],
            [by_hex[h] for h in reply["not_ready"]],
        )

    def submit_remote_function(self, rf, args: tuple, kwargs: dict):
        from ray_tpu._private.core_worker import function_id_of
        from ray_tpu.remote_function import _build_resources, _strategy_fields

        opts = rf._options
        pickled = rf._get_pickled()
        func_id = function_id_of(pickled)
        payload, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        pg_id, bundle_index, strategy = _strategy_fields(opts)
        req = {
            "func_id": func_id,
            "name": opts.get("name") or getattr(rf._fn, "__name__", "task"),
            "args_payload": payload,
            "ref_positions": ref_pos,
            "kw_ref_keys": kw_refs,
            "dependencies": deps,
            "num_returns": opts.get("num_returns", 1),
            "resources": _build_resources(opts),
            "max_retries": opts.get("max_retries"),
            "retry_exceptions": opts.get("retry_exceptions", False),
            "pg_id": pg_id,
            "bundle_index": bundle_index,
            "scheduling_strategy": strategy,
            "runtime_env": opts.get("runtime_env"),
        }
        if func_id not in self._fn_ids_known:
            req["fn_blob"] = pickled
        reply = self._run(self.conn.call("CTask", req), timeout=300)
        if reply.get("need_fn"):
            req["fn_blob"] = pickled
            reply = self._run(self.conn.call("CTask", req), timeout=300)
        self._fn_ids_known.add(func_id)
        return self._make_refs(reply["oids"], reply.get("owner_addr"))

    def create_actor(self, actor_cls, args: tuple, kwargs: dict):
        from ray_tpu.actor import ActorHandle
        from ray_tpu.remote_function import _build_resources, _strategy_fields

        opts = actor_cls._options
        payload, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        pg_id, bundle_index, strategy = _strategy_fields(opts)
        reply = self._run(
            self.conn.call(
                "CActorCreate",
                {
                    "cls_blob": actor_cls._get_pickled(),
                    "name": actor_cls._cls.__name__,
                    "args_payload": payload,
                    "ref_positions": ref_pos,
                    "kw_ref_keys": kw_refs,
                    "dependencies": deps,
                    "opts": {
                        "resources": _build_resources(opts),
                        "max_restarts": opts.get("max_restarts", 0),
                        "max_concurrency": opts.get("max_concurrency", 1),
                        "max_task_retries": opts.get("max_task_retries", 0),
                        "concurrency_groups": opts.get("concurrency_groups"),
                        "name": opts.get("name"),
                        "namespace": opts.get("namespace") or self.namespace,
                        "lifetime": opts.get("lifetime"),
                        "get_if_exists": opts.get("get_if_exists", False),
                        "scheduling_strategy": strategy,
                        "runtime_env": opts.get("runtime_env"),
                    },
                },
            ),
            timeout=300,
        )
        return ActorHandle(reply["actor_id"], opts.get("max_task_retries", 0))

    def call_actor_method(
        self, actor_id: str, method: str, args, kwargs,
        num_returns=1, max_task_retries=0, concurrency_group=None,
    ):
        payload, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        reply = self._run(
            self.conn.call(
                "CActorCall",
                {
                    "actor_id": actor_id,
                    "method": method,
                    "args_payload": payload,
                    "ref_positions": ref_pos,
                    "kw_ref_keys": kw_refs,
                    "dependencies": deps,
                    "num_returns": num_returns,
                    "max_task_retries": max_task_retries,
                    "concurrency_group": concurrency_group,
                },
            ),
            timeout=300,
        )
        return self._make_refs(reply["oids"], reply.get("owner_addr"))

    def kill(self, actor_id: str, no_restart: bool = True) -> None:
        self._run(
            self.conn.call("CKill", {"actor_id": actor_id, "no_restart": no_restart}),
            timeout=60,
        )

    def cancel(self, ref, force: bool = False) -> None:
        self._run(
            self.conn.call(
                "CCancel",
                {
                    "oid": ref.hex(),
                    "owner": list(getattr(ref, "owner_addr", None) or self.owner_addr),
                    "force": force,
                },
            ),
            timeout=60,
        )

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.actor import ActorHandle

        reply = self._run(
            self.conn.call(
                "CGetActor", {"name": name, "namespace": namespace}
            ),
            timeout=60,
        )
        return ActorHandle(reply["actor_id"], reply.get("max_task_retries", 0))

    def nodes(self) -> List[dict]:
        reply = self._run(self.conn.call("CClusterInfo", {}), timeout=60)
        return reply["nodes"]

    # -- ref releases --------------------------------------------------------

    def _schedule_release(self, oid: str) -> None:
        with self._release_lock:
            self._release_buf.append(oid)
            if len(self._release_buf) == 1:
                try:
                    self.loop.call_soon_threadsafe(
                        lambda: self.loop.call_later(0.2, self._flush_releases)
                    )
                except RuntimeError:
                    pass

    def _flush_releases(self) -> None:
        with self._release_lock:
            oids, self._release_buf = self._release_buf, []
        if oids and not self.conn.closed:
            try:
                self.conn.push_nowait("CRelease", {"oids": oids})
            except rpc.ConnectionLost:
                pass

    def disconnect(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self._run(self.conn.close(), timeout=5)
        except Exception:
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


def connect(address: str, namespace: Optional[str] = None) -> ClientContext:
    """Connect to a cluster's client server. ``address`` is ``host:port`` or
    ``ray-tpu://host:port``."""
    for prefix in ("ray-tpu://", "ray://"):
        if address.startswith(prefix):
            address = address[len(prefix):]
    host, port = address.rsplit(":", 1)
    return ClientContext(host, int(port), namespace=namespace)
