"""ActorPool: load-balance tasks over a fixed set of actors.

Analog of python/ray/util/actor_pool.py: submit/get_next[_unordered],
map/map_unordered over a pool of actor handles.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def has_free(self) -> bool:
        return len(self._idle) > 0

    def has_next(self) -> bool:
        return len(self._future_to_actor) > 0

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn: (actor, value) -> ObjectRef, e.g. lambda a, v: a.work.remote(v)."""
        if not self._idle:
            raise RuntimeError("no idle actors; call get_next() first")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        actor = self._future_to_actor.pop(ref)
        try:
            return ray_tpu.get(ref, timeout=timeout)
        finally:
            self._idle.append(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next completed result, any order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        for idx, fut in list(self._index_to_future.items()):
            if fut == ref:
                del self._index_to_future[idx]
                break
        try:
            return ray_tpu.get(ref)
        finally:
            self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            if not self.has_free():
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            if not self.has_free():
                yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
