"""Block layer: a block is a pyarrow.Table (reference: python/ray/data —
blocks are Arrow tables in plasma; block_accessor.py provides the row/batch
views). Helpers here convert between rows, batches, and tables and implement
the per-block kernels (slice, sort, hash-partition) that map tasks run."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from ray_tpu.data.tensor_extension import (
    ArrowTensorArray,
    is_tensor_type,
    tensor_column_to_numpy,
)

# A batch/table column name used when the data is just values, not mappings
# (reference: ray.data uses __value__ the same way via TENSOR_COLUMN_NAME).
VALUE_COL = "__value__"


@dataclass(frozen=True)
class BlockMeta:
    """Per-block metadata that travels NEXT TO the block ref, not inside it
    (reference: BlockMetadata in block.py riding RefBundles through the
    streaming executor). Stage tasks return ``(block, meta)`` via
    ``num_returns=2`` so dispatch decisions (limit cutoffs, zip alignment,
    repartition ranges, row counts) read a tiny inline object instead of
    paying a counter-task round trip per block."""

    num_rows: int
    size_bytes: int


def meta_for(block: pa.Table) -> BlockMeta:
    return BlockMeta(num_rows=block.num_rows, size_bytes=block.nbytes)


def rows_to_block(rows: Sequence[Any]) -> pa.Table:
    """Build a block from python rows (dicts or bare values)."""
    if rows and isinstance(rows[0], dict):
        cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
        for r in rows:
            if set(r.keys()) != set(cols.keys()):
                for k in r:
                    if k not in cols:
                        cols[k] = [None] * (len(next(iter(cols.values()))) - 0)
            for k in cols:
                cols[k].append(r.get(k))
        return pa.table({k: _to_arrow_array(v) for k, v in cols.items()})
    return pa.table({VALUE_COL: _to_arrow_array(list(rows))})


def _to_arrow_array(values: List[Any]):
    if values and isinstance(values[0], np.ndarray):
        first = values[0]
        if (
            first.dtype != object
            and first.ndim >= 1
            and all(
                isinstance(v, np.ndarray)
                and v.shape == first.shape
                and v.dtype == first.dtype
                for v in values
            )
        ):
            # Uniform ndarray rows -> ONE contiguous tensor column
            # (zero-copy through serialization and back to numpy), not
            # per-row Arrow lists.
            return ArrowTensorArray.from_numpy(np.stack(values))
        try:
            return pa.array([np.asarray(v).tolist() for v in values])
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            # Mixed nesting depth (e.g. (H, W) grayscale next to (H, W, 3)
            # RGB) cannot become one Arrow list column; pickle per row.
            import cloudpickle

            return pa.array([cloudpickle.dumps(v) for v in values])
    try:
        return pa.array(values)
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
        import cloudpickle

        return pa.array([cloudpickle.dumps(v) for v in values])


def block_to_rows(block: pa.Table) -> List[Any]:
    cols = block.column_names
    pydict = {}
    for c in cols:
        col = block.column(c)
        if is_tensor_type(col.type):
            stacked = tensor_column_to_numpy(col)
            pydict[c] = [stacked[i] for i in range(len(stacked))]
        else:
            pydict[c] = col.to_pylist()
    if cols == [VALUE_COL]:
        return pydict[VALUE_COL]
    return [dict(zip(cols, vals)) for vals in zip(*(pydict[c] for c in cols))]


def block_to_batch(block: pa.Table, batch_format: str = "numpy"):
    """Materialize a block in the requested batch format (reference:
    batch formats of map_batches/iter_batches)."""
    if batch_format in ("pyarrow", "arrow"):
        return block
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format in ("numpy", "dict", "default"):
        out = {}
        for name in block.column_names:
            col = block.column(name)
            if is_tensor_type(col.type):
                out[name] = tensor_column_to_numpy(col)
                continue
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                out[name] = np.asarray(col.to_pylist(), dtype=object)
        return out
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch: Any) -> pa.Table:
    """Accept whatever a map_batches UDF returned."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            if isinstance(v, np.ndarray) and v.ndim >= 2 and v.dtype != object:
                # Columnar fast path: a stacked array IS the tensor column.
                cols[k] = ArrowTensorArray.from_numpy(v)
            else:
                cols[k] = _to_arrow_array(_as_list(v))
        return pa.table(cols)
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, (list, np.ndarray)):
        return rows_to_block(list(batch))
    raise TypeError(
        f"map_batches UDF must return dict/pyarrow.Table/pandas.DataFrame/"
        f"list, got {type(batch)}"
    )


def _as_list(v):
    if isinstance(v, np.ndarray):
        return list(v)
    return list(v)


def empty_block() -> pa.Table:
    return pa.table({})


def _detensorize(block: pa.Table) -> pa.Table:
    """Replace tensor-extension columns with plain list<...> arrays (used
    when blocks with mismatched tensor shapes/encodings must concatenate)."""
    cols = {}
    changed = False
    for name in block.column_names:
        col = block.column(name)
        if is_tensor_type(col.type):
            stacked = tensor_column_to_numpy(col)
            cols[name] = pa.array([row.tolist() for row in stacked])
            changed = True
        else:
            cols[name] = col
    return pa.table(cols) if changed else block


def concat_blocks(blocks: List[pa.Table]) -> pa.Table:
    blocks = [b for b in blocks if b.num_rows > 0]
    if not blocks:
        return empty_block()
    # Unify trivially-divergent schemas (e.g. int vs float) via promote.
    # ArrowTypeError subclasses TypeError, so it must be caught first —
    # it signals genuinely incompatible columns (e.g. one block's rows were
    # uniform ndarrays -> tensor column, another's were ragged -> list
    # column, or two tensor columns with different element shapes); those
    # concatenate after downgrading tensor columns to plain lists.
    try:
        return pa.concat_tables(blocks, promote_options="permissive")
    except pa.ArrowTypeError:
        blocks = [_detensorize(b) for b in blocks]
        return pa.concat_tables(blocks, promote_options="permissive")
    except TypeError:  # older pyarrow signature
        return pa.concat_tables(blocks, promote=True)


def slice_block(block: pa.Table, start: int, end: int) -> pa.Table:
    return block.slice(start, end - start)


def sort_block(block: pa.Table, key: str, descending: bool = False) -> pa.Table:
    order = "descending" if descending else "ascending"
    if block.num_rows == 0:
        return block
    import pyarrow.compute as pc  # submodule: not loaded by "import pyarrow"

    return block.take(pc.sort_indices(block, sort_keys=[(key, order)]))


def hash_partition_block(
    block: pa.Table, key: Optional[str], num_partitions: int, seed: int = 0
) -> List[pa.Table]:
    """Split a block into hash partitions (by key column, or uniformly at
    random when key is None — the random_shuffle/repartition path)."""
    n = block.num_rows
    if n == 0:
        return [block] * num_partitions
    if key is None:
        rng = np.random.RandomState(seed)
        assignment = rng.randint(0, num_partitions, size=n)
    else:
        # Deterministic cross-process hash: python hash() is randomized per
        # process, which would scatter one key across merge partitions.
        import zlib

        vals = block.column(key).to_pylist()
        assignment = np.array(
            [zlib.crc32(repr(v).encode()) % num_partitions for v in vals]
        )
    out = []
    for p in range(num_partitions):
        idx = np.nonzero(assignment == p)[0]
        out.append(block.take(pa.array(idx)))
    return out


def range_partition_block(
    block: pa.Table, key: str, boundaries: List[Any]
) -> List[pa.Table]:
    """Partition by sorted boundaries → len(boundaries)+1 parts."""
    vals = block.column(key).to_pylist()
    import bisect

    assignment = np.array([bisect.bisect_right(boundaries, v) for v in vals])
    out = []
    for p in range(len(boundaries) + 1):
        idx = np.nonzero(assignment == p)[0]
        out.append(block.take(pa.array(idx)))
    return out
