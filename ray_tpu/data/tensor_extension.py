"""Arrow extension type for fixed-shape tensor columns.

The reference stores image/array columns as ArrowTensorType extension arrays
(python/ray/air/util/tensor_extensions/arrow.py) so a block holds ONE
contiguous buffer per tensor column instead of per-row objects. Same design
here, minimal surface: storage is FixedSizeList<storage_dtype>[prod(shape)],
element shape rides in the extension metadata, and conversion to/from numpy
is zero-copy (a reshape view over the flat values buffer).

This is what makes the ingest data plane cheap: a (N, H*W*C) uint8 image
column serializes as one out-of-band pickle-5 buffer into shm and comes back
as a zero-copy numpy view — no per-row bytes, no frombuffer/stack on the
trainer's hot loop.
"""

from __future__ import annotations

import json
import math
from typing import Tuple

import numpy as np
import pyarrow as pa


class ArrowTensorType(pa.ExtensionType):
    """Fixed-shape tensor column: each row is an ndarray of `element_shape`."""

    def __init__(self, element_shape: Tuple[int, ...], storage_dtype: pa.DataType):
        self._element_shape = tuple(int(s) for s in element_shape)
        size = int(math.prod(self._element_shape)) if self._element_shape else 1
        super().__init__(
            pa.list_(storage_dtype, size), "ray_tpu.data.tensor"
        )

    @property
    def element_shape(self) -> Tuple[int, ...]:
        return self._element_shape

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps(list(self._element_shape)).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        shape = tuple(json.loads(serialized.decode()))
        return cls(shape, storage_type.value_type)

    def __arrow_ext_class__(self):
        return ArrowTensorArray

    def __reduce__(self):
        return (
            ArrowTensorType,
            (self._element_shape, self.storage_type.value_type),
        )


class ArrowTensorArray(pa.ExtensionArray):
    @staticmethod
    def from_numpy(arr: np.ndarray) -> "ArrowTensorArray":
        """Build a tensor column from a stacked (N, *element_shape) array.
        Zero-copy when `arr` is C-contiguous."""
        arr = np.ascontiguousarray(arr)
        if arr.ndim < 2:
            raise ValueError(
                f"tensor column needs a stacked (N, ...) array, got {arr.shape}"
            )
        element_shape = arr.shape[1:]
        size = int(math.prod(element_shape))
        flat_np = arr.reshape(-1)
        try:
            # Wrap the numpy buffer instead of pa.array(), which memcpys the
            # whole thing (~30 ms per 38 MB image block — the single biggest
            # ingest-path copy). py_buffer holds a reference to the numpy
            # memory, so the column keeps it alive.
            flat = pa.Array.from_buffers(
                pa.from_numpy_dtype(flat_np.dtype),
                len(flat_np),
                [None, pa.py_buffer(flat_np)],
            )
        except (pa.ArrowNotImplementedError, pa.ArrowInvalid, ValueError):
            flat = pa.array(flat_np)  # non-primitive dtypes
        storage = pa.FixedSizeListArray.from_arrays(flat, size)
        typ = ArrowTensorType(element_shape, flat.type)
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy_tensor(self) -> np.ndarray:
        """(N, *element_shape) numpy view — zero-copy when the storage is a
        single contiguous non-null chunk."""
        storage = self.storage
        values = storage.values
        # Respect a sliced storage array (offset/length in list elements).
        size = self.type.storage_type.list_size
        start = storage.offset * size
        flat = values.slice(start, len(storage) * size).to_numpy(
            zero_copy_only=False
        )
        return flat.reshape((len(storage),) + self.type.element_shape)


def tensor_column_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    """Materialize a (possibly chunked) tensor column as (N, *shape)."""
    if isinstance(col, pa.ChunkedArray):
        if col.num_chunks == 1:
            return col.chunk(0).to_numpy_tensor()
        return np.concatenate(
            [c.to_numpy_tensor() for c in col.chunks], axis=0
        )
    return col.to_numpy_tensor()


def is_tensor_type(t: pa.DataType) -> bool:
    return isinstance(t, ArrowTensorType)


_registered = False


def ensure_registered() -> None:
    """Register the extension type with pyarrow (idempotent; required for
    IPC/pickle round-trips to reconstruct ArrowTensorArray)."""
    global _registered
    if _registered:
        return
    try:
        pa.register_extension_type(ArrowTensorType((1,), pa.int64()))
    except pa.ArrowKeyError:
        pass  # already registered in this process
    _registered = True


ensure_registered()
