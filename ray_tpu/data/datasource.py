"""Datasources: read task construction + writers (reference:
python/ray/data/datasource/ + read_api.py)."""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data import block as B


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    f
                    for f in glob.glob(os.path.join(p, "**", "*"), recursive=True)
                    if os.path.isfile(f)
                )
            )
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range_tasks(n: int, parallelism: int) -> List[Callable[[], pa.Table]]:
    parallelism = max(1, min(parallelism, n or 1))
    tasks = []
    for i in range(parallelism):
        lo = n * i // parallelism
        hi = n * (i + 1) // parallelism

        def task(lo=lo, hi=hi):
            return pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

        tasks.append(task)
    return tasks


def range_tensor_tasks(n: int, shape, parallelism: int):
    parallelism = max(1, min(parallelism, n or 1))
    tasks = []
    for i in range(parallelism):
        lo = n * i // parallelism
        hi = n * (i + 1) // parallelism

        def task(lo=lo, hi=hi, shape=tuple(shape)):
            data = [
                (np.ones(shape, dtype=np.int64) * j).tolist()
                for j in range(lo, hi)
            ]
            return pa.table({"data": data})

        tasks.append(task)
    return tasks


def items_tasks(items: List[Any], parallelism: int):
    parallelism = max(1, min(parallelism, len(items) or 1))
    tasks = []
    for i in range(parallelism):
        chunk = items[len(items) * i // parallelism : len(items) * (i + 1) // parallelism]

        def task(chunk=chunk):
            return B.rows_to_block(chunk)

        tasks.append(task)
    return tasks


def csv_read_tasks(paths, **read_options):
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f, read_options=read_options):
            from pyarrow import csv as pacsv

            return pacsv.read_csv(f, **read_options)

        tasks.append(task)
    return tasks


def parquet_read_tasks(paths, columns: Optional[List[str]] = None):
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f, columns=columns):
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)

        tasks.append(task)
    return tasks


def json_read_tasks(paths):
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f):
            from pyarrow import json as pajson

            return pajson.read_json(f)

        tasks.append(task)
    return tasks


def text_read_tasks(paths, encoding: str = "utf-8", drop_empty_lines: bool = True):
    """One block per file; one row per line (reference: read_text)."""
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f, encoding=encoding, drop=drop_empty_lines):
            with open(f, encoding=encoding) as fh:
                lines = fh.read().splitlines()
            if drop:
                lines = [ln for ln in lines if ln]
            return pa.table({"text": lines})

        tasks.append(task)
    return tasks


def binary_read_tasks(paths, include_paths: bool = False):
    """One block per file; the file's bytes as one row (reference:
    read_binary_files)."""
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f, include_paths=include_paths):
            with open(f, "rb") as fh:
                data = fh.read()
            cols = {"bytes": pa.array([data], type=pa.binary())}
            if include_paths:
                cols["path"] = pa.array([f])
            return pa.table(cols)

        tasks.append(task)
    return tasks


def numpy_read_tasks(paths, column: str = "data"):
    """One block per .npy file (reference: read_numpy)."""
    files = _expand_paths(paths)
    tasks = []
    for f in files:

        def task(f=f, column=column):
            import numpy as np

            arr = np.load(f, allow_pickle=False)
            return pa.table({column: list(arr)})

        tasks.append(task)
    return tasks


# -- writers (run as remote tasks, one file per block) -----------------------


def write_block_parquet(table: pa.Table, path: str, idx: int) -> str:
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.parquet")
    pq.write_table(table, out)
    return out


def write_block_csv(table: pa.Table, path: str, idx: int) -> str:
    from pyarrow import csv as pacsv

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.csv")
    pacsv.write_csv(table, out)
    return out


def write_block_json(table: pa.Table, path: str, idx: int) -> str:
    import json

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.json")
    with open(out, "w") as f:
        for row in B.block_to_rows(table):
            f.write(json.dumps(row) + "\n")
    return out


def image_read_tasks(
    paths,
    size: Optional[tuple] = None,
    mode: Optional[str] = None,
    include_paths: bool = False,
    parallelism: int = 8,
) -> List[Callable[[], pa.Table]]:
    """PIL-decoded images, one tensor-column block per file group
    (reference: ray.data.read_images / datasource/image_datasource.py).
    size=(H, W) resizes — required for a stacked fixed-shape tensor column
    when the files vary; mode forces a PIL conversion ("RGB", "L", ...)."""
    files = [
        f
        for f in _expand_paths(paths)
        if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"))
    ]
    if not files:
        raise FileNotFoundError(f"no image files matched {paths}")
    parallelism = max(1, min(parallelism, len(files)))
    tasks = []
    for i in range(parallelism):
        chunk = files[i::parallelism]

        def task(chunk=tuple(chunk), size=size, mode=mode, include=include_paths):
            from PIL import Image

            from ray_tpu.data.tensor_extension import ArrowTensorArray

            arrs, names = [], []
            for path in chunk:
                with Image.open(path) as im:
                    if mode:
                        im = im.convert(mode)
                    if size:
                        im = im.resize((size[1], size[0]))  # PIL is (W, H)
                    arrs.append(np.asarray(im))
                names.append(path)
            shapes = {a.shape for a in arrs}
            if len(shapes) != 1:
                # A deterministic dataset-wide representation is impossible
                # with heterogeneous shapes (blocks would disagree on the
                # column type depending on file striping): fail loudly with
                # the fix, like the reference's image datasource.
                raise ValueError(
                    f"images have differing shapes {sorted(shapes)}; pass "
                    "size=(H, W) (and mode=) to read_images to decode into "
                    "a uniform tensor column"
                )
            col = ArrowTensorArray.from_numpy(np.stack(arrs))
            cols = {"image": col}
            if include:
                cols["path"] = pa.array(names)
            return pa.table(cols)

        tasks.append(task)
    return tasks


def webdataset_read_tasks(
    paths, parallelism: int = 8
) -> List[Callable[[], pa.Table]]:
    """WebDataset-style tar shards (reference: ray.data.read_webdataset):
    files inside each tar are grouped into samples by basename — everything
    up to the first dot is the sample key, the rest is the field name. Each
    row gets "__key__" plus one bytes column per field; .txt/.cls/.json
    fields are decoded to str/int/object like the webdataset defaults."""
    files = _expand_paths(paths)
    tars = [f for f in files if f.endswith((".tar", ".tar.gz", ".tgz"))]
    if not tars:
        raise FileNotFoundError(f"no tar shards matched {paths}")
    parallelism = max(1, min(parallelism, len(tars)))
    tasks = []
    for i in range(parallelism):
        chunk = tars[i::parallelism]

        def task(chunk=tuple(chunk)):
            import json as _json
            import tarfile

            rows: List[dict] = []
            for tar_path in chunk:
                # Samples group PER SHARD, keyed by the tar-internal path
                # stem (directory included): equal keys in different shards
                # or directories are different samples, never merged
                # (reference read_webdataset semantics).
                samples: dict = {}
                order: List[str] = []
                with tarfile.open(tar_path) as tf:
                    for member in tf:
                        if not member.isfile():
                            continue
                        base = os.path.basename(member.name)
                        if base.startswith("."):
                            continue  # AppleDouble/.DS_Store and kin
                        stem, _, field = base.partition(".")
                        if not field:
                            continue
                        key = os.path.join(os.path.dirname(member.name), stem)
                        data = tf.extractfile(member).read()
                        if key not in samples:
                            samples[key] = {"__key__": key}
                            order.append(key)
                        if field in ("txt", "text"):
                            samples[key][field] = data.decode("utf-8")
                        elif field == "cls":
                            samples[key][field] = int(data.decode().strip())
                        elif field == "json":
                            samples[key][field] = _json.loads(data)
                        else:
                            samples[key][field] = data
                rows.extend(samples[k] for k in order)
            return B.rows_to_block(rows)

        tasks.append(task)
    return tasks
