"""DataIterator + streaming_split coordination (reference:
python/ray/data/iterator.py DataIterator and
_internal/execution/streaming_split coordination via
StreamSplitDataIterator — an actor serves blocks to N consumers).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

import ray_tpu
from ray_tpu._private import telemetry
from ray_tpu._private.common import config
from ray_tpu.data import block as B

# docs/observability.md: component "data".
_BATCH_ASSEMBLY = telemetry.histogram(
    "data", "batch_assembly_s", "slice+concat+format per emitted batch"
)
_PREFETCH_DEPTH = telemetry.gauge(
    "data", "prefetch_queue_depth", "batches ready ahead of the consumer"
)
_BYTES_FETCHED = telemetry.counter(
    "data", "bytes_fetched", "block bytes materialized on the consumer"
)
_SPLIT_QUEUE_DEPTH = telemetry.gauge(
    "data", "split_queue_depth", "blocks buffered across split queues"
)
_SPLIT_DISPATCHED = telemetry.counter(
    "data", "split_blocks_dispatched", "blocks routed to a split queue"
)
_SPLIT_STEALS = telemetry.counter(
    "data", "split_steals", "tail blocks claimed from a lagging split"
)


def batches_from_blocks(
    blocks: Iterator[pa.Table],
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a stream of blocks into fixed-size batches.

    Copy budget (docs/perf.md): an offset cursor walks the queued tables and
    emits each batch from zero-copy ``pa.Table.slice`` views, concatenating
    ONLY when a batch spans a block boundary. The remainder of a block is
    never re-copied per batch (the old path paid concat + two slice copies
    of the whole buffer for every emitted batch).
    """
    if batch_size is None:
        for blk in blocks:
            if blk.num_rows:
                yield B.block_to_batch(blk, batch_format)
        return
    hist = _BATCH_ASSEMBLY.cell()
    buf: collections.deque = collections.deque()
    off = 0  # rows of buf[0] already emitted
    buffered = 0  # unemitted rows across buf
    for blk in blocks:
        if blk.num_rows == 0:
            continue
        buf.append(blk)
        buffered += blk.num_rows
        while buffered >= batch_size:
            t0 = time.perf_counter()
            need = batch_size
            parts: List[pa.Table] = []
            while need:
                head = buf[0]
                take = min(head.num_rows - off, need)
                parts.append(head.slice(off, take))
                off += take
                need -= take
                if off == head.num_rows:
                    buf.popleft()
                    off = 0
            buffered -= batch_size
            batch = parts[0] if len(parts) == 1 else B.concat_blocks(parts)
            out = B.block_to_batch(batch, batch_format)
            hist.observe(time.perf_counter() - t0)
            yield out
    if buffered and not drop_last:
        t0 = time.perf_counter()
        parts = [buf[0].slice(off)] + list(buf)[1:]
        batch = parts[0] if len(parts) == 1 else B.concat_blocks(parts)
        out = B.block_to_batch(batch, batch_format)
        hist.observe(time.perf_counter() - t0)
        yield out


def iter_blocks_pipelined(
    refs: Iterator[Any], lookahead: Optional[int] = None
) -> Iterator[pa.Table]:
    """Fetch blocks with up to ``lookahead`` gets in flight, yielding in
    input order — object-store pull overlaps batch assembly instead of
    serializing against it (reference: prefetch_blocks in the iterator
    path). ``ray_tpu.get`` is thread-safe (worker.run_async bridges onto
    the owner's event loop), so a small thread pool is all this needs."""
    if lookahead is None:
        lookahead = config.data_fetch_lookahead
    bytes_cell = _BYTES_FETCHED.cell()

    def _fetch(ref):
        blk = ray_tpu.get(ref)
        bytes_cell.inc(blk.nbytes)
        return blk

    refs = iter(refs)
    if lookahead <= 1:
        try:
            for ref in refs:
                yield _fetch(ref)
        finally:
            close = getattr(refs, "close", None)
            if close is not None:
                close()
        return
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(
        max_workers=lookahead, thread_name_prefix="block-fetch"
    )
    pending: collections.deque = collections.deque()
    try:
        for ref in refs:
            pending.append(pool.submit(_fetch, ref))
            if len(pending) >= lookahead:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()
    finally:
        for f in pending:
            f.cancel()
        pool.shutdown(wait=False)
        close = getattr(refs, "close", None)
        if close is not None:
            close()


def prefetch_iterator(it: Iterator[Any], n: int) -> Iterator[Any]:
    """Run `it` in a background thread, keeping up to `n` items ready.
    Overlaps batch assembly (block fetch + slice + format conversion) with
    the consumer's compute — the reference's prefetch_batches semantics
    (python/ray/data/iterator.py iter_batches)."""
    if n <= 0:
        yield from it
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=n)
    _END = object()
    stop = threading.Event()
    depth = _PREFETCH_DEPTH.cell()

    def _put(item) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # iterator — otherwise the fill thread would block on a full queue
        # forever, pinning the buffered batches and the upstream iterator.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                depth.set(q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def fill():
        try:
            for item in it:
                if not _put(item):
                    break
            else:
                _put(_END)
        except BaseException as e:  # surfaced on the consumer side
            _put(e)
        finally:
            if stop.is_set():
                # Run upstream generators' finally-blocks promptly.
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass

    t = threading.Thread(target=fill, daemon=True, name="batch-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            depth.set(q.qsize())
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def _mapped_with_close(fn, it):
    """map() that forwards close() to the source generator — the prefetch
    thread relies on close() to run upstream finally-blocks promptly when
    the consumer abandons iteration (plain map objects have no close)."""
    try:
        for item in it:
            yield fn(item)
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()


class _SplitCoordinator:
    """Actor owning one dataset execution, streaming blocks to N splits.

    True streaming (reference: _internal/execution/operators/output_splitter.py
    + streaming_executor.py:48): a producer thread drives the pull-based
    StreamingExecutor and deposits block refs into per-split queues; splits
    drain their queue on demand. The producer blocks when every queue is at
    its cap, so backpressure reaches the executor's submit window and the
    dataset never has to fit in the object store. First-batch latency is one
    block, not one epoch. The executor runs with
    ``preserve_order=config.data_split_preserve_order`` (default False):
    splits shard the stream anyway, so blocks dispatch in completion order
    and a straggler read task delays only itself.

    Dispatch (the reference OutputSplitter's equal=False load balancing):
    each block goes to the least-loaded non-full queue, so a stalled or
    slow split only ever pins cap-many blocks while healthy splits keep
    streaming. Once the producer finishes, an idle split steals the tail
    from lagging splits — immediately from splits that joined the epoch
    (they are racing it anyway), and only after a grace period from splits
    that never showed up (protects a late-starting trainer worker's share).

    Epochs: a split calls start_epoch before each pass. Joining a running
    epoch is immediate; asking for the NEXT epoch blocks until every split
    that joined the current epoch has drained it (barrier — prevents a fast
    split's relaunch from leaking next-epoch blocks into a slow split's
    current iteration), then relaunches the execution. A split that
    abandoned an epoch mid-way (consumer broke out of iter_batches) has its
    leftover share discarded when it asks for a fresh pass.
    """

    # Handed-out refs are pinned for this many subsequent next_refs calls of
    # the same split: the owner (this actor) must keep a ref alive until the
    # borrower has fetched the payload. 3 (not 2) because the consumer
    # requests group k+1 while group k's fetches are still in flight (the
    # DataIterator RPC lookahead) — groups k, k+1, k+2 may all have
    # unfinished fetches when k+2 is handed out.
    _PIN_GROUPS = 3
    # Seconds after producer completion before an idle split may steal from
    # a split that never joined this epoch. Trade-off: shorter means a
    # sole sequential consumer finishes sooner; longer protects a
    # slow-starting trainer worker's share (reference equal=False makes no
    # reservation at all — any grace here is stricter fairness than the
    # reference's demand dispatch).
    _STEAL_GRACE = 10.0

    def __init__(self, plan_blob: bytes, n: int, parallelism: int):
        import collections
        import threading

        import cloudpickle

        self.ops = cloudpickle.loads(plan_blob)
        self.n = n
        self.parallelism = parallelism
        self._cond = threading.Condition()
        self._queues: List[Any] = [collections.deque() for _ in range(n)]
        self._queue_cap = max(2, -(-parallelism // n) + 1)
        self._buffered = 0
        self._rr = 0  # tie-break rotation for least-loaded dispatch
        self._epoch = -1
        self._producer: Optional[Any] = None
        self._producer_done = True
        self._done_at: float = 0.0  # monotonic time the producer finished
        self._producer_error: Optional[BaseException] = None
        # Epoch membership: splits that called start_epoch for the current
        # epoch, and splits that observed it exhausted.
        self._joined: set = set()
        self._finished: set = set()
        # split_idx -> deque of recently handed-out ref groups (pinning).
        self._handed: Dict[int, Any] = {
            i: collections.deque(maxlen=self._PIN_GROUPS) for i in range(n)
        }
        self._depth_cell = _SPLIT_QUEUE_DEPTH.cell()
        self._dispatched_cell = _SPLIT_DISPATCHED.cell()
        self._steals_cell = _SPLIT_STEALS.cell()

    # -- producer ------------------------------------------------------------

    def _launch(self, joined_by: int) -> None:
        """Start execution for a new epoch. Caller holds self._cond."""
        import threading
        import time as _time

        self._epoch += 1
        for q in self._queues:
            q.clear()
        self._buffered = 0
        self._producer_done = False
        self._producer_error = None
        self._joined = {joined_by}
        self._finished = set()
        epoch = self._epoch

        def run():
            from ray_tpu.data._execution import StreamingExecutor

            try:
                ex = StreamingExecutor(
                    self.parallelism,
                    preserve_order=config.data_split_preserve_order,
                )
                for bundle in ex.execute(self.ops):
                    ref = bundle.block
                    with self._cond:
                        while (
                            self._epoch == epoch
                            and min(len(q) for q in self._queues)
                            >= self._queue_cap
                        ):
                            self._cond.wait(1.0)
                        if self._epoch != epoch:
                            return  # superseded; drop the rest
                        # Least-loaded non-full queue; rotate ties so an
                        # all-empty start round-robins.
                        order = sorted(
                            range(self.n),
                            key=lambda i: (
                                len(self._queues[i]),
                                (i - self._rr) % self.n,
                            ),
                        )
                        dest = order[0]
                        self._rr = (dest + 1) % self.n
                        self._queues[dest].append(ref)
                        self._buffered += 1
                        self._dispatched_cell.inc()
                        self._depth_cell.set(self._buffered)
                        self._cond.notify_all()
            except BaseException as e:  # surfaced to every consumer
                with self._cond:
                    if self._epoch == epoch:  # a superseded producer's late
                        self._producer_error = e  # failure must not poison
                        # the relaunched epoch.
            finally:
                with self._cond:
                    if self._epoch == epoch:
                        self._producer_done = True
                        self._done_at = _time.monotonic()
                        self._cond.notify_all()

        self._producer = threading.Thread(
            target=run, daemon=True, name=f"split-producer-{epoch}"
        )
        self._producer.start()

    # -- split-facing API ----------------------------------------------------

    def start_epoch(self, split_idx: int, timeout: float = 600.0) -> int:
        """Begin (or join) an epoch for this split; returns the epoch id.

        Blocks (barrier) when asking for a new epoch while peers are still
        draining the current one.
        """
        import time as _time

        with self._cond:
            if self._producer is None:
                self._launch(split_idx)
                return self._epoch
            if split_idx not in self._joined:
                # Fresh join of the running (or just-drained) epoch. Its
                # reserved share is still in its queue — un-joined splits
                # are protected from stealing by the grace period.
                self._joined.add(split_idx)
                return self._epoch
            if split_idx not in self._finished:
                # Abandoned mid-epoch (consumer broke out of iteration):
                # discard this split's leftover share so the epoch can
                # drain, then fall through to request a fresh pass.
                q = self._queues[split_idx]
                self._buffered -= len(q)
                q.clear()
                self._depth_cell.set(self._buffered)
                self._finished.add(split_idx)
                self._cond.notify_all()
            # Wants the NEXT epoch: wait until every joined split drained
            # the current one, then relaunch (one waiter wins; the rest see
            # the epoch advance and join it).
            target = self._epoch + 1
            deadline = _time.monotonic() + timeout
            while self._epoch < target:
                # Ready when every joined split drained the epoch. The
                # producer need not be done: if all consumers abandoned, the
                # relaunch supersedes it (the producer thread observes the
                # epoch bump and exits instead of producing to nobody).
                ready = self._buffered == 0 and self._joined <= self._finished
                if ready:
                    self._launch(split_idx)
                    return self._epoch
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"split {split_idx} waited {timeout}s for peers "
                        f"{sorted(self._joined - self._finished)} to finish "
                        f"epoch {self._epoch}"
                    )
                self._cond.wait(min(remaining, 1.0))
            self._joined.add(split_idx)
            return self._epoch

    def next_refs(
        self,
        split_idx: int,
        max_n: int = 4,
        timeout: float = 300.0,
        epoch: Optional[int] = None,
    ):
        """Claim up to max_n block refs for this split.

        Returns (refs, done): done=True means the epoch is exhausted and no
        further refs will arrive. Blocks until at least one ref is available
        or the epoch ends; raises the producer's error if execution failed.

        ``epoch`` (from start_epoch) fences stale calls: the DataIterator
        keeps one next_refs RPC in flight ahead, so a consumer that abandons
        iteration can leave a blocked call behind — when the epoch advances,
        that call must return empty instead of eating the new epoch's
        blocks.
        """
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cond:
            while True:
                if epoch is not None and self._epoch != epoch:
                    return [], True  # stale pre-fetch from a finished pass
                if self._producer_error is not None:
                    raise self._producer_error
                src = self._queues[split_idx]
                if not src and self._producer_done and self._buffered:
                    # Tail steal: production finished and this split is
                    # idle. Joined peers are fair game (they are actively
                    # racing); never-joined peers keep their share for a
                    # grace period in case they are still spawning.
                    grace_over = (
                        _time.monotonic() - self._done_at >= self._STEAL_GRACE
                    )
                    candidates = [
                        q
                        for j, q in enumerate(self._queues)
                        if q and (j in self._joined or grace_over)
                    ]
                    if candidates:
                        src = max(candidates, key=len)
                        self._steals_cell.inc()
                if src:
                    refs = []
                    while src and len(refs) < max_n:
                        refs.append(src.popleft())
                    self._buffered -= len(refs)
                    self._depth_cell.set(self._buffered)
                    done = self._producer_done and self._buffered == 0
                    if done:
                        self._finished.add(split_idx)
                    # Pin: the bounded deque drops groups handed out
                    # _PIN_GROUPS calls ago — by then the consumer has
                    # fetched them (with the RPC lookahead, group k's
                    # fetches finish before group k+2 is requested).
                    self._handed[split_idx].append(refs)
                    self._cond.notify_all()  # wake the producer (queue space)
                    return refs, done
                if self._producer_done and self._buffered == 0:
                    self._finished.add(split_idx)
                    self._cond.notify_all()  # release the epoch barrier
                    return [], True
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"split {split_idx} waited {timeout}s for a block"
                    )
                self._cond.wait(min(remaining, 1.0))


class DataIterator:
    """Per-consumer view of a streaming split; picklable (ships the
    coordinator actor handle).

    Single-split fast path: ``streaming_split(1)`` constructs this with a
    plan blob and NO coordinator — one consumer needs no cross-consumer
    queueing, so iteration drives the StreamingExecutor in-process (each
    pass is a fresh epoch, same semantics) and skips the actor spawn plus
    a per-group RPC round trip. Pickling ships the plan blob itself: the
    receiving process (a trainer worker is a full ray worker, exactly what
    the coordinator actor would have been) drives its own local execution.
    With one split there is one consumer, so "each consumer executes the
    plan" and "one shared execution" coincide; pickling stays free of side
    effects (no actor spawn mid-serialization, which may run on the event
    loop thread)."""

    def __init__(
        self,
        coordinator,
        split_idx: int,
        _local_plan: Optional[bytes] = None,
        _parallelism: int = 8,
    ):
        self._coord = coordinator
        self._idx = split_idx
        self._local_plan = _local_plan
        self._par = _parallelism

    def _local_blocks(self) -> Iterator[pa.Table]:
        import cloudpickle

        from ray_tpu.data._execution import StreamingExecutor

        ops = cloudpickle.loads(self._local_plan)
        ex = StreamingExecutor(
            self._par, preserve_order=config.data_split_preserve_order
        )

        def refs():
            for bundle in ex.execute(ops):
                yield bundle.block

        yield from iter_blocks_pipelined(refs())

    def _ref_stream(self, epoch: int) -> Iterator[Any]:
        """Yield this split's block refs, keeping ONE next_refs RPC in
        flight ahead: the request for group k+1 rides the wire while group
        k's blocks are fetched (coordinator pinning covers the overlap —
        see _SplitCoordinator._PIN_GROUPS)."""
        nxt = self._coord.next_refs.remote(self._idx, epoch=epoch)
        while True:
            refs, done = ray_tpu.get(nxt)
            if not done:
                nxt = self._coord.next_refs.remote(self._idx, epoch=epoch)
            for ref in refs:
                yield ref
            if done:
                return

    def _blocks(self) -> Iterator[pa.Table]:
        if self._coord is None:
            yield from self._local_blocks()
            return
        epoch = ray_tpu.get(self._coord.start_epoch.remote(self._idx))
        # Direct object-store fetch: zero-copy shm view for local blocks,
        # chunked pull for remote ones — the data plane never flows through
        # the coordinator actor. Pipelined so fetch overlaps assembly.
        yield from iter_blocks_pipelined(self._ref_stream(epoch))

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
        _finalize_fn: Optional[Any] = None,
    ) -> Iterator[Any]:
        """Iterate fixed-size batches over this split's stream of blocks.

        _finalize_fn (reference: python/ray/data/iterator.py iter_batches
        _finalize_fn) runs on each batch INSIDE the prefetch thread — put
        `jax.device_put` there and the host->device copy of batch k+1
        overlaps the consumer's device compute on batch k (double
        buffering).
        """
        it = batches_from_blocks(
            self._blocks(), batch_size, batch_format, drop_last
        )
        if _finalize_fn is not None:
            it = _mapped_with_close(_finalize_fn, it)
        yield from prefetch_iterator(it, prefetch_batches)

    def iter_rows(self) -> Iterator[Any]:
        for blk in self._blocks():
            yield from B.block_to_rows(blk)

    def materialize(self):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data._execution import FromBlocks

        return Dataset([FromBlocks(list(self._blocks()))])

    def __reduce__(self):
        return (DataIterator, (self._coord, self._idx, self._local_plan, self._par))
