"""DataIterator + streaming_split coordination (reference:
python/ray/data/iterator.py DataIterator and
_internal/execution/streaming_split coordination via
StreamSplitDataIterator — an actor serves blocks to N consumers).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B


def batches_from_blocks(
    blocks: Iterator[pa.Table],
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a stream of blocks into fixed-size batches."""
    if batch_size is None:
        for blk in blocks:
            if blk.num_rows:
                yield B.block_to_batch(blk, batch_format)
        return
    buf: List[pa.Table] = []
    buffered = 0
    for blk in blocks:
        if blk.num_rows == 0:
            continue
        buf.append(blk)
        buffered += blk.num_rows
        while buffered >= batch_size:
            merged = B.concat_blocks(buf)
            batch = B.slice_block(merged, 0, batch_size)
            rest = B.slice_block(merged, batch_size, merged.num_rows)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
            yield B.block_to_batch(batch, batch_format)
    if buffered and not drop_last:
        yield B.block_to_batch(B.concat_blocks(buf), batch_format)


class _SplitCoordinator:
    """Actor owning one dataset execution, serving blocks to N splits.

    Blocks are assigned round-robin at execution time; each epoch restarts
    iteration over the materialized block refs (first epoch materializes).
    """

    def __init__(self, plan_blob: bytes, n: int, parallelism: int):
        import threading

        import cloudpickle

        self.ops = cloudpickle.loads(plan_blob)
        self.n = n
        self.parallelism = parallelism
        self.refs: Optional[List[Any]] = None
        self.positions: Dict[int, int] = {}
        self._lock = threading.Lock()  # splits call in concurrently

    def _ensure(self):
        with self._lock:
            if self.refs is None:
                from ray_tpu.data._execution import StreamingExecutor

                ex = StreamingExecutor(self.parallelism)
                self.refs = list(ex.execute(self.ops))

    def start_epoch(self, split_idx: int) -> None:
        self._ensure()
        self.positions[split_idx] = 0

    def next_block(self, split_idx: int):
        """Next block (as a table) for this split, or None when exhausted."""
        self._ensure()
        pos = self.positions.get(split_idx, 0)
        idx = pos * self.n + split_idx
        if idx >= len(self.refs):
            return None
        self.positions[split_idx] = pos + 1
        return ray_tpu.get(self.refs[idx])


class DataIterator:
    """Per-consumer view of a streaming split; picklable (ships the
    coordinator actor handle)."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx

    def _blocks(self) -> Iterator[pa.Table]:
        ray_tpu.get(self._coord.start_epoch.remote(self._idx))
        while True:
            blk = ray_tpu.get(self._coord.next_block.remote(self._idx))
            if blk is None:
                return
            yield blk

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
    ) -> Iterator[Any]:
        yield from batches_from_blocks(
            self._blocks(), batch_size, batch_format, drop_last
        )

    def iter_rows(self) -> Iterator[Any]:
        for blk in self._blocks():
            yield from B.block_to_rows(blk)

    def materialize(self):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data._execution import FromBlocks

        return Dataset([FromBlocks(list(self._blocks()))])

    def __reduce__(self):
        return (DataIterator, (self._coord, self._idx))
