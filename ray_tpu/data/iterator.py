"""DataIterator + streaming_split coordination (reference:
python/ray/data/iterator.py DataIterator and
_internal/execution/streaming_split coordination via
StreamSplitDataIterator — an actor serves blocks to N consumers).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B


def batches_from_blocks(
    blocks: Iterator[pa.Table],
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a stream of blocks into fixed-size batches."""
    if batch_size is None:
        for blk in blocks:
            if blk.num_rows:
                yield B.block_to_batch(blk, batch_format)
        return
    buf: List[pa.Table] = []
    buffered = 0
    for blk in blocks:
        if blk.num_rows == 0:
            continue
        buf.append(blk)
        buffered += blk.num_rows
        while buffered >= batch_size:
            merged = B.concat_blocks(buf)
            batch = B.slice_block(merged, 0, batch_size)
            rest = B.slice_block(merged, batch_size, merged.num_rows)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
            yield B.block_to_batch(batch, batch_format)
    if buffered and not drop_last:
        yield B.block_to_batch(B.concat_blocks(buf), batch_format)


def prefetch_iterator(it: Iterator[Any], n: int) -> Iterator[Any]:
    """Run `it` in a background thread, keeping up to `n` items ready.
    Overlaps batch assembly (block fetch + slice + format conversion) with
    the consumer's compute — the reference's prefetch_batches semantics
    (python/ray/data/iterator.py iter_batches)."""
    if n <= 0:
        yield from it
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=n)
    _END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # iterator — otherwise the fill thread would block on a full queue
        # forever, pinning the buffered batches and the upstream iterator.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def fill():
        try:
            for item in it:
                if not _put(item):
                    break
            else:
                _put(_END)
        except BaseException as e:  # surfaced on the consumer side
            _put(e)
        finally:
            if stop.is_set():
                # Run upstream generators' finally-blocks promptly.
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass

    t = threading.Thread(target=fill, daemon=True, name="batch-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class _SplitCoordinator:
    """Actor owning one dataset execution, serving blocks to N splits.

    Blocks are assigned round-robin at execution time; each epoch restarts
    iteration over the materialized block refs (first epoch materializes).
    """

    def __init__(self, plan_blob: bytes, n: int, parallelism: int):
        import threading

        import cloudpickle

        self.ops = cloudpickle.loads(plan_blob)
        self.n = n
        self.parallelism = parallelism
        self.refs: Optional[List[Any]] = None
        self.positions: Dict[int, int] = {}
        self._lock = threading.Lock()  # splits call in concurrently

    def _ensure(self):
        with self._lock:
            if self.refs is None:
                from ray_tpu.data._execution import StreamingExecutor

                ex = StreamingExecutor(self.parallelism)
                self.refs = list(ex.execute(self.ops))

    def start_epoch(self, split_idx: int) -> None:
        self._ensure()
        self.positions[split_idx] = 0

    def next_block(self, split_idx: int):
        """Next block (as a table) for this split, or None when exhausted.
        Kept for compatibility; split_refs is the fast path."""
        self._ensure()
        pos = self.positions.get(split_idx, 0)
        idx = pos * self.n + split_idx
        if idx >= len(self.refs):
            return None
        self.positions[split_idx] = pos + 1
        return ray_tpu.get(self.refs[idx])

    def split_refs(self, split_idx: int) -> List[Any]:
        """This split's block refs (round-robin assignment). The consumer
        fetches blocks straight from the object store — the data plane never
        flows through this actor (the old per-block next_block path
        re-serialized every block through the actor reply: two copies plus
        an actor round-trip per block)."""
        self._ensure()
        return self.refs[split_idx :: self.n]


class DataIterator:
    """Per-consumer view of a streaming split; picklable (ships the
    coordinator actor handle)."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx

    def _blocks(self) -> Iterator[pa.Table]:
        refs = ray_tpu.get(self._coord.split_refs.remote(self._idx))
        for ref in refs:
            # Direct object-store fetch: zero-copy shm view for local
            # blocks, chunked pull for remote ones.
            yield ray_tpu.get(ref)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        it = batches_from_blocks(
            self._blocks(), batch_size, batch_format, drop_last
        )
        yield from prefetch_iterator(it, prefetch_batches)

    def iter_rows(self) -> Iterator[Any]:
        for blk in self._blocks():
            yield from B.block_to_rows(blk)

    def materialize(self):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data._execution import FromBlocks

        return Dataset([FromBlocks(list(self._blocks()))])

    def __reduce__(self):
        return (DataIterator, (self._coord, self._idx))
