"""DataIterator + streaming_split coordination (reference:
python/ray/data/iterator.py DataIterator and
_internal/execution/streaming_split coordination via
StreamSplitDataIterator — an actor serves blocks to N consumers).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B


def batches_from_blocks(
    blocks: Iterator[pa.Table],
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a stream of blocks into fixed-size batches."""
    if batch_size is None:
        for blk in blocks:
            if blk.num_rows:
                yield B.block_to_batch(blk, batch_format)
        return
    buf: List[pa.Table] = []
    buffered = 0
    for blk in blocks:
        if blk.num_rows == 0:
            continue
        buf.append(blk)
        buffered += blk.num_rows
        while buffered >= batch_size:
            merged = B.concat_blocks(buf)
            batch = B.slice_block(merged, 0, batch_size)
            rest = B.slice_block(merged, batch_size, merged.num_rows)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
            yield B.block_to_batch(batch, batch_format)
    if buffered and not drop_last:
        yield B.block_to_batch(B.concat_blocks(buf), batch_format)


def prefetch_iterator(it: Iterator[Any], n: int) -> Iterator[Any]:
    """Run `it` in a background thread, keeping up to `n` items ready.
    Overlaps batch assembly (block fetch + slice + format conversion) with
    the consumer's compute — the reference's prefetch_batches semantics
    (python/ray/data/iterator.py iter_batches)."""
    if n <= 0:
        yield from it
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=n)
    _END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # iterator — otherwise the fill thread would block on a full queue
        # forever, pinning the buffered batches and the upstream iterator.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def fill():
        try:
            for item in it:
                if not _put(item):
                    break
            else:
                _put(_END)
        except BaseException as e:  # surfaced on the consumer side
            _put(e)
        finally:
            if stop.is_set():
                # Run upstream generators' finally-blocks promptly.
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass

    t = threading.Thread(target=fill, daemon=True, name="batch-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def _mapped_with_close(fn, it):
    """map() that forwards close() to the source generator — the prefetch
    thread relies on close() to run upstream finally-blocks promptly when
    the consumer abandons iteration (plain map objects have no close)."""
    try:
        for item in it:
            yield fn(item)
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()


class _SplitCoordinator:
    """Actor owning one dataset execution, streaming blocks to N splits.

    True streaming (reference: _internal/execution/operators/output_splitter.py
    + streaming_executor.py:48): a producer thread drives the pull-based
    StreamingExecutor and deposits block refs into per-split queues; splits
    drain their queue on demand. The producer blocks when every queue is at
    its cap, so backpressure reaches the executor's submit window and the
    dataset never has to fit in the object store. First-batch latency is one
    block, not one epoch.

    Dispatch (the reference OutputSplitter's equal=False load balancing):
    each block goes to the least-loaded non-full queue, so a stalled or
    slow split only ever pins cap-many blocks while healthy splits keep
    streaming. Once the producer finishes, an idle split steals the tail
    from lagging splits — immediately from splits that joined the epoch
    (they are racing it anyway), and only after a grace period from splits
    that never showed up (protects a late-starting trainer worker's share).

    Epochs: a split calls start_epoch before each pass. Joining a running
    epoch is immediate; asking for the NEXT epoch blocks until every split
    that joined the current epoch has drained it (barrier — prevents a fast
    split's relaunch from leaking next-epoch blocks into a slow split's
    current iteration), then relaunches the execution. A split that
    abandoned an epoch mid-way (consumer broke out of iter_batches) has its
    leftover share discarded when it asks for a fresh pass.
    """

    # Handed-out refs are pinned for this many subsequent next_refs calls of
    # the same split: the owner (this actor) must keep a ref alive until the
    # borrower has fetched the payload, and the consumer fetches group k
    # before requesting group k+1.
    _PIN_GROUPS = 2
    # Seconds after producer completion before an idle split may steal from
    # a split that never joined this epoch. Trade-off: shorter means a
    # sole sequential consumer finishes sooner; longer protects a
    # slow-starting trainer worker's share (reference equal=False makes no
    # reservation at all — any grace here is stricter fairness than the
    # reference's demand dispatch).
    _STEAL_GRACE = 10.0

    def __init__(self, plan_blob: bytes, n: int, parallelism: int):
        import collections
        import threading

        import cloudpickle

        self.ops = cloudpickle.loads(plan_blob)
        self.n = n
        self.parallelism = parallelism
        self._cond = threading.Condition()
        self._queues: List[Any] = [collections.deque() for _ in range(n)]
        self._queue_cap = max(2, -(-parallelism // n) + 1)
        self._buffered = 0
        self._rr = 0  # tie-break rotation for least-loaded dispatch
        self._epoch = -1
        self._producer: Optional[Any] = None
        self._producer_done = True
        self._done_at: float = 0.0  # monotonic time the producer finished
        self._producer_error: Optional[BaseException] = None
        # Epoch membership: splits that called start_epoch for the current
        # epoch, and splits that observed it exhausted.
        self._joined: set = set()
        self._finished: set = set()
        # split_idx -> deque of recently handed-out ref groups (pinning).
        self._handed: Dict[int, Any] = {
            i: collections.deque(maxlen=self._PIN_GROUPS) for i in range(n)
        }

    # -- producer ------------------------------------------------------------

    def _launch(self, joined_by: int) -> None:
        """Start execution for a new epoch. Caller holds self._cond."""
        import threading
        import time as _time

        self._epoch += 1
        for q in self._queues:
            q.clear()
        self._buffered = 0
        self._producer_done = False
        self._producer_error = None
        self._joined = {joined_by}
        self._finished = set()
        epoch = self._epoch

        def run():
            from ray_tpu.data._execution import StreamingExecutor

            try:
                ex = StreamingExecutor(self.parallelism)
                for ref in ex.execute(self.ops):
                    with self._cond:
                        while (
                            self._epoch == epoch
                            and min(len(q) for q in self._queues)
                            >= self._queue_cap
                        ):
                            self._cond.wait(1.0)
                        if self._epoch != epoch:
                            return  # superseded; drop the rest
                        # Least-loaded non-full queue; rotate ties so an
                        # all-empty start round-robins.
                        order = sorted(
                            range(self.n),
                            key=lambda i: (
                                len(self._queues[i]),
                                (i - self._rr) % self.n,
                            ),
                        )
                        dest = order[0]
                        self._rr = (dest + 1) % self.n
                        self._queues[dest].append(ref)
                        self._buffered += 1
                        self._cond.notify_all()
            except BaseException as e:  # surfaced to every consumer
                with self._cond:
                    if self._epoch == epoch:  # a superseded producer's late
                        self._producer_error = e  # failure must not poison
                        # the relaunched epoch.
            finally:
                with self._cond:
                    if self._epoch == epoch:
                        self._producer_done = True
                        self._done_at = _time.monotonic()
                        self._cond.notify_all()

        self._producer = threading.Thread(
            target=run, daemon=True, name=f"split-producer-{epoch}"
        )
        self._producer.start()

    # -- split-facing API ----------------------------------------------------

    def start_epoch(self, split_idx: int, timeout: float = 600.0) -> int:
        """Begin (or join) an epoch for this split; returns the epoch id.

        Blocks (barrier) when asking for a new epoch while peers are still
        draining the current one.
        """
        import time as _time

        with self._cond:
            if self._producer is None:
                self._launch(split_idx)
                return self._epoch
            if split_idx not in self._joined:
                # Fresh join of the running (or just-drained) epoch. Its
                # reserved share is still in its queue — un-joined splits
                # are protected from stealing by the grace period.
                self._joined.add(split_idx)
                return self._epoch
            if split_idx not in self._finished:
                # Abandoned mid-epoch (consumer broke out of iteration):
                # discard this split's leftover share so the epoch can
                # drain, then fall through to request a fresh pass.
                q = self._queues[split_idx]
                self._buffered -= len(q)
                q.clear()
                self._finished.add(split_idx)
                self._cond.notify_all()
            # Wants the NEXT epoch: wait until every joined split drained
            # the current one, then relaunch (one waiter wins; the rest see
            # the epoch advance and join it).
            target = self._epoch + 1
            deadline = _time.monotonic() + timeout
            while self._epoch < target:
                # Ready when every joined split drained the epoch. The
                # producer need not be done: if all consumers abandoned, the
                # relaunch supersedes it (the producer thread observes the
                # epoch bump and exits instead of producing to nobody).
                ready = self._buffered == 0 and self._joined <= self._finished
                if ready:
                    self._launch(split_idx)
                    return self._epoch
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"split {split_idx} waited {timeout}s for peers "
                        f"{sorted(self._joined - self._finished)} to finish "
                        f"epoch {self._epoch}"
                    )
                self._cond.wait(min(remaining, 1.0))
            self._joined.add(split_idx)
            return self._epoch

    def next_refs(self, split_idx: int, max_n: int = 4, timeout: float = 300.0):
        """Claim up to max_n block refs for this split.

        Returns (refs, done): done=True means the epoch is exhausted and no
        further refs will arrive. Blocks until at least one ref is available
        or the epoch ends; raises the producer's error if execution failed.
        """
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cond:
            while True:
                if self._producer_error is not None:
                    raise self._producer_error
                src = self._queues[split_idx]
                if not src and self._producer_done and self._buffered:
                    # Tail steal: production finished and this split is
                    # idle. Joined peers are fair game (they are actively
                    # racing); never-joined peers keep their share for a
                    # grace period in case they are still spawning.
                    grace_over = (
                        _time.monotonic() - self._done_at >= self._STEAL_GRACE
                    )
                    candidates = [
                        q
                        for j, q in enumerate(self._queues)
                        if q and (j in self._joined or grace_over)
                    ]
                    if candidates:
                        src = max(candidates, key=len)
                if src:
                    refs = []
                    while src and len(refs) < max_n:
                        refs.append(src.popleft())
                    self._buffered -= len(refs)
                    done = self._producer_done and self._buffered == 0
                    if done:
                        self._finished.add(split_idx)
                    # Pin: the bounded deque drops groups handed out
                    # _PIN_GROUPS calls ago — by then the consumer has
                    # fetched them (it requests group k+1 only after
                    # consuming group k).
                    self._handed[split_idx].append(refs)
                    self._cond.notify_all()  # wake the producer (queue space)
                    return refs, done
                if self._producer_done and self._buffered == 0:
                    self._finished.add(split_idx)
                    self._cond.notify_all()  # release the epoch barrier
                    return [], True
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"split {split_idx} waited {timeout}s for a block"
                    )
                self._cond.wait(min(remaining, 1.0))


class DataIterator:
    """Per-consumer view of a streaming split; picklable (ships the
    coordinator actor handle)."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx

    def _blocks(self) -> Iterator[pa.Table]:
        ray_tpu.get(self._coord.start_epoch.remote(self._idx))
        while True:
            refs, done = ray_tpu.get(
                self._coord.next_refs.remote(self._idx)
            )
            for ref in refs:
                # Direct object-store fetch: zero-copy shm view for local
                # blocks, chunked pull for remote ones — the data plane
                # never flows through the coordinator actor.
                yield ray_tpu.get(ref)
            if done:
                return

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
        _finalize_fn: Optional[Any] = None,
    ) -> Iterator[Any]:
        """Iterate fixed-size batches over this split's stream of blocks.

        _finalize_fn (reference: python/ray/data/iterator.py iter_batches
        _finalize_fn) runs on each batch INSIDE the prefetch thread — put
        `jax.device_put` there and the host->device copy of batch k+1
        overlaps the consumer's device compute on batch k (double
        buffering).
        """
        it = batches_from_blocks(
            self._blocks(), batch_size, batch_format, drop_last
        )
        if _finalize_fn is not None:
            it = _mapped_with_close(_finalize_fn, it)
        yield from prefetch_iterator(it, prefetch_batches)

    def iter_rows(self) -> Iterator[Any]:
        for blk in self._blocks():
            yield from B.block_to_rows(blk)

    def materialize(self):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data._execution import FromBlocks

        return Dataset([FromBlocks(list(self._blocks()))])

    def __reduce__(self):
        return (DataIterator, (self._coord, self._idx))
