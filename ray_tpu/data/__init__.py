"""ray_tpu.data — streaming distributed datasets (reference: python/ray/data).

    import ray_tpu.data as rd

    ds = rd.range(1000).map_batches(lambda b: {"x": b["id"] * 2})
    for batch in ds.iter_batches(batch_size=128):
        ...
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data._execution import FromBlocks, Read
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data import datasource as _src


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset([Read(read_tasks=_src.range_tasks(n, parallelism))], parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    return Dataset(
        [Read(read_tasks=_src.range_tensor_tasks(n, shape, parallelism))],
        parallelism,
    )


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(
        [Read(read_tasks=_src.items_tasks(list(items), parallelism))], parallelism
    )


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    return Dataset([FromBlocks(blocks=[pa.Table.from_pandas(df, preserve_index=False)])])


def from_arrow(table) -> Dataset:
    return Dataset([FromBlocks(blocks=[table])])


def from_numpy(arr, column: str = "data") -> Dataset:
    import pyarrow as pa

    return Dataset([FromBlocks(blocks=[pa.table({column: list(arr)})])])


def read_csv(paths, *, parallelism: int = 8, **kwargs) -> Dataset:
    return Dataset([Read(read_tasks=_src.csv_read_tasks(paths, **kwargs))], parallelism)


def read_parquet(
    paths, *, columns: Optional[List[str]] = None, parallelism: int = 8
) -> Dataset:
    return Dataset(
        [Read(read_tasks=_src.parquet_read_tasks(paths, columns))], parallelism
    )


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    return Dataset([Read(read_tasks=_src.json_read_tasks(paths))], parallelism)


def read_text(
    paths, *, encoding: str = "utf-8", drop_empty_lines: bool = True,
    parallelism: int = 8,
) -> Dataset:
    """One row per line across the files (reference: ray.data.read_text)."""
    return Dataset(
        [Read(read_tasks=_src.text_read_tasks(paths, encoding, drop_empty_lines))],
        parallelism,
    )


def read_binary_files(
    paths, *, include_paths: bool = False, parallelism: int = 8
) -> Dataset:
    """One row per file holding its raw bytes (reference:
    ray.data.read_binary_files)."""
    return Dataset(
        [Read(read_tasks=_src.binary_read_tasks(paths, include_paths))],
        parallelism,
    )


def read_numpy(paths, *, column: str = "data", parallelism: int = 8) -> Dataset:
    """.npy files, one block each (reference: ray.data.read_numpy)."""
    return Dataset(
        [Read(read_tasks=_src.numpy_read_tasks(paths, column))], parallelism
    )


def read_images(
    paths,
    *,
    size=None,
    mode=None,
    include_paths: bool = False,
    parallelism: int = 8,
) -> Dataset:
    """PIL-decoded images as an "image" tensor column (reference:
    ray.data.read_images). size=(H, W) resizes (required for a stacked
    fixed-shape column over mixed-size files); mode forces a PIL convert
    ("RGB", "L", ...)."""
    return Dataset(
        [
            Read(
                read_tasks=_src.image_read_tasks(
                    paths, size, mode, include_paths, parallelism
                )
            )
        ],
        parallelism,
    )


def read_webdataset(paths, *, parallelism: int = 8) -> Dataset:
    """WebDataset tar shards -> one row per sample keyed by "__key__", with
    a column per field; .txt/.cls/.json fields are decoded (reference:
    ray.data.read_webdataset)."""
    return Dataset(
        [Read(read_tasks=_src.webdataset_read_tasks(paths, parallelism))],
        parallelism,
    )


__all__ = [
    "Dataset",
    "GroupedData",
    "DataIterator",
    "range",
    "range_tensor",
    "from_items",
    "from_pandas",
    "from_arrow",
    "from_numpy",
    "read_csv",
    "read_parquet",
    "read_json",
    "read_text",
    "read_binary_files",
    "read_numpy",
    "read_images",
    "read_webdataset",
]
