"""Dataset: lazy logical plan + consumption APIs (reference:
python/ray/data/dataset.py:137 — same surface, executed by the streaming
executor in _execution.py)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data._execution import (
    FromBlocks,
    GroupByAgg,
    Limit,
    LogicalOp,
    MapBlocks,
    RandomShuffle,
    Repartition,
    Sort,
    StreamingExecutor,
    Union,
    Zip,
)
from ray_tpu.data.iterator import DataIterator, _SplitCoordinator, batches_from_blocks


class Dataset:
    def __init__(self, ops: List[LogicalOp], parallelism: int = 8):
        self._ops = ops
        self._parallelism = parallelism

    def _with(self, op: LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op], self._parallelism)

    # -- transforms (lazy) ---------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def _map(t: pa.Table, fn=fn):
            return B.rows_to_block([fn(r) for r in B.block_to_rows(t)])

        return self._with(MapBlocks(fn=_map, name="Map"))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def _fmap(t: pa.Table, fn=fn):
            out: List[Any] = []
            for r in B.block_to_rows(t):
                out.extend(fn(r))
            return B.rows_to_block(out)

        return self._with(MapBlocks(fn=_fmap, name="FlatMap"))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        def _filter(t: pa.Table, fn=fn):
            return B.rows_to_block([r for r in B.block_to_rows(t) if fn(r)])

        return self._with(MapBlocks(fn=_filter, name="Filter"))

    def map_batches(
        self,
        fn,
        *,
        batch_format: str = "numpy",
        batch_size: Optional[int] = None,
        concurrency: Optional[int] = None,
        fn_constructor_args: tuple = (),
    ) -> "Dataset":
        """fn: batch -> batch, or a class (stateful UDF -> actor pool,
        reference: ActorPoolMapOperator)."""
        def _per_batch(callable_fn, t: pa.Table):
            if batch_size is None or t.num_rows <= batch_size:
                return B.batch_to_block(
                    callable_fn(B.block_to_batch(t, batch_format))
                )
            outs = []
            for lo in range(0, t.num_rows, batch_size):
                chunk = B.slice_block(t, lo, min(lo + batch_size, t.num_rows))
                outs.append(
                    B.batch_to_block(
                        callable_fn(B.block_to_batch(chunk, batch_format))
                    )
                )
            return B.concat_blocks(outs)

        if isinstance(fn, type):
            import cloudpickle

            def _apply(udf, t: pa.Table):
                return _per_batch(udf, t)

            return self._with(
                MapBlocks(
                    fn=_apply,
                    name=f"MapBatches({fn.__name__})",
                    actor_cls=cloudpickle.dumps(fn),
                    actor_args=fn_constructor_args,
                    pool_size=concurrency or 2,
                )
            )

        def _mb(t: pa.Table, fn=fn):
            return _per_batch(fn, t)

        return self._with(MapBlocks(fn=_mb, name="MapBatches"))

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        def _add(t: pa.Table, name=name, fn=fn):
            col = fn(B.block_to_batch(t, "pandas"))
            return B.batch_to_block(
                t.append_column(name, pa.array(list(col)))
            )

        return self._with(MapBlocks(fn=_add, name=f"AddColumn({name})"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def _drop(t: pa.Table, cols=tuple(cols)):
            return t.drop_columns(list(cols))

        return self._with(MapBlocks(fn=_drop, name="DropColumns"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def _sel(t: pa.Table, cols=tuple(cols)):
            return t.select(list(cols))

        return self._with(MapBlocks(fn=_sel, name="SelectColumns"))

    def limit(self, n: int) -> "Dataset":
        return self._with(Limit(n=n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(Union(others=[o._ops for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(Zip(other=other._ops))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(Repartition(num_blocks=num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(Sort(key=key, descending=descending))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(RandomShuffle(seed=seed))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # -- execution -----------------------------------------------------------

    def _executor(self) -> StreamingExecutor:
        return StreamingExecutor(self._parallelism)

    def iter_bundles(self) -> Iterator[Any]:
        """Stream RefBundles (block ref + metadata) for the applied plan."""
        yield from self._executor().execute(self._ops)

    def iter_block_refs(self) -> Iterator[Any]:
        for b in self.iter_bundles():
            yield b.block

    def iter_blocks(self) -> Iterator[pa.Table]:
        from ray_tpu.data.iterator import iter_blocks_pipelined

        # Lookahead keeps K object-store fetches in flight so pull overlaps
        # whatever the consumer does with each block.
        yield from iter_blocks_pipelined(self.iter_block_refs())

    def materialize(self) -> "Dataset":
        """Execute now; the result holds concrete blocks
        (reference: Dataset.materialize)."""
        return Dataset(
            [FromBlocks(blocks=list(self.iter_blocks()))], self._parallelism
        )

    # -- consumption ---------------------------------------------------------

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for blk in self.limit(n).iter_blocks():
            out.extend(B.block_to_rows(blk))
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for blk in self.iter_blocks():
            out.extend(B.block_to_rows(blk))
        return out

    def count(self) -> int:
        """Row count without moving row data to the driver: sums the
        BlockMeta riding next to every block ref (one batched inline get —
        zero counter tasks, zero block fetches)."""
        from ray_tpu.data._execution import resolve_metas

        bundles = list(self.iter_bundles())
        if not bundles:
            return 0
        return sum(m.num_rows for m in resolve_metas(bundles))

    def schema(self) -> Optional[pa.Schema]:
        for blk in self.iter_blocks():
            if blk.num_rows or blk.num_columns:
                return blk.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        for blk in self.iter_blocks():
            yield from B.block_to_rows(blk)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        from ray_tpu.data.iterator import prefetch_iterator

        it = batches_from_blocks(
            self.iter_blocks(), batch_size, batch_format, drop_last
        )
        yield from prefetch_iterator(it, prefetch_batches)

    def to_pandas(self):
        return B.concat_blocks(list(self.iter_blocks())).to_pandas()

    def to_arrow(self) -> pa.Table:
        return B.concat_blocks(list(self.iter_blocks()))

    # -- splits --------------------------------------------------------------

    def split(self, n: int) -> List["Dataset"]:
        """Materializing split into n datasets (reference: Dataset.split)."""
        blocks = list(self.repartition(n).iter_blocks())
        return [
            Dataset([FromBlocks(blocks=blocks[i::n])], self._parallelism)
            for i in range(n)
        ]

    def streaming_split(self, n: int, *, equal: bool = False) -> List[DataIterator]:
        """N iterators backed by one shared execution (reference:
        Dataset.streaming_split, used for per-host train ingest)."""
        import cloudpickle

        ops = self._ops
        if equal:
            ops = ops + [Repartition(num_blocks=n * 4)]
        if n == 1:
            # Single consumer: no cross-consumer queueing to coordinate, so
            # skip the actor entirely — the iterator drives the executor
            # in-process (fast path; promoted to a coordinator only if the
            # iterator is pickled to a trainer worker).
            return [
                DataIterator(
                    None,
                    0,
                    _local_plan=cloudpickle.dumps(ops),
                    _parallelism=self._parallelism,
                )
            ]
        cls = ray_tpu.remote(_SplitCoordinator)
        # 2 slots per split: the DataIterator keeps one next_refs RPC in
        # flight ahead, and an abandoned consumer's stale call may still be
        # blocked server-side when the split starts its next epoch.
        coord = cls.options(
            max_concurrency=max(4, 2 * n + 2), num_cpus=0.5
        ).remote(cloudpickle.dumps(ops), n, self._parallelism)
        return [DataIterator(coord, i) for i in range(n)]

    # -- writes --------------------------------------------------------------

    def _write(self, writer, path: str) -> List[str]:
        w = ray_tpu.remote(writer)
        refs = [
            w.remote(ref, path, i)
            for i, ref in enumerate(self.iter_block_refs())
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import write_block_parquet

        return self._write(write_block_parquet, path)

    def write_csv(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import write_block_csv

        return self._write(write_block_csv, path)

    def write_json(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import write_block_json

        return self._write(write_block_json, path)

    def __repr__(self):
        names = [type(op).__name__ for op in self._ops]
        return f"Dataset({' -> '.join(names)})"


class GroupedData:
    """reference: python/ray/data/grouped_data.py."""

    _AGG_FNS = {"sum", "min", "max", "mean", "count"}

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, fn: str, col: Optional[str]) -> Dataset:
        if fn not in self._AGG_FNS:
            raise ValueError(f"unknown aggregate {fn}")
        aggs = [(col or self._key, fn)]
        return self._ds._with(GroupByAgg(key=self._key, aggs=aggs))

    def sum(self, col: str) -> Dataset:
        return self._agg("sum", col)

    def min(self, col: str) -> Dataset:
        return self._agg("min", col)

    def max(self, col: str) -> Dataset:
        return self._agg("max", col)

    def mean(self, col: str) -> Dataset:
        return self._agg("mean", col)

    def count(self) -> Dataset:
        return self._ds._with(GroupByAgg(key=self._key, aggs=[(self._key, "count")]))

    def aggregate(self, *aggs) -> Dataset:
        """aggs: (col, fn) tuples."""
        return self._ds._with(GroupByAgg(key=self._key, aggs=list(aggs)))
