"""Logical ops + streaming execution (reference: python/ray/data/_internal —
logical/interfaces/logical_operator.py, execution/streaming_executor.py:48,
execution/operators/*).

Execution model: each stage is a generator over block ObjectRefs with a
bounded in-flight window — downstream pulling makes upstream submit, so the
whole pipeline streams with backpressure, like the reference's pull-based
StreamingExecutor. Output order is preserved (head-of-line yield), which the
reference also guarantees by default.

Map-chains are fused into one task per block (reference: operator fusion in
plan optimization) so a read->map->filter pipeline costs one task per block.
"""

from __future__ import annotations

import collections
import itertools
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as B

# -- logical ops -------------------------------------------------------------


@dataclass
class LogicalOp:
    pass


@dataclass
class Read(LogicalOp):
    """Each read_task() -> pa.Table; one task per input partition."""

    read_tasks: List[Callable[[], pa.Table]] = field(default_factory=list)
    name: str = "Read"


@dataclass
class FromBlocks(LogicalOp):
    blocks: List[pa.Table] = field(default_factory=list)


@dataclass
class MapBlocks(LogicalOp):
    """fn(pa.Table) -> pa.Table. Covers map/filter/flat_map/map_batches."""

    fn: Callable[[pa.Table], pa.Table] = None
    name: str = "Map"
    # Class-based UDF → actor pool (reference: ActorPoolMapOperator).
    actor_cls: Optional[bytes] = None  # cloudpickled class
    actor_args: Tuple = ()
    pool_size: int = 2


@dataclass
class Limit(LogicalOp):
    n: int = 0


@dataclass
class Union(LogicalOp):
    others: List[List[LogicalOp]] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: List[LogicalOp] = field(default_factory=list)


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1


@dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclass
class GroupByAgg(LogicalOp):
    key: str = ""
    aggs: List[Tuple[str, str]] = field(default_factory=list)  # (col, fn)


# -- remote kernels ----------------------------------------------------------


def _remote(fn, **opts):
    return ray_tpu.remote(**{"num_cpus": 1, **opts})(fn)


def _exec_read(task_blob):
    import cloudpickle

    return cloudpickle.loads(task_blob)()


def _exec_map(fn_blob, table):
    import cloudpickle

    return cloudpickle.loads(fn_blob)(table)


def _num_rows(table):
    return table.num_rows


def _slice_concat(ranges, *tables):
    """ranges: list of (table_idx, start, end) over the varargs tables.

    Block refs ride as top-level varargs because only top-level ObjectRef
    args are resolved to values before execution (same contract as the
    reference's task arg resolution)."""
    from ray_tpu.data import block as B

    return B.concat_blocks([B.slice_block(tables[i], s, e) for i, s, e in ranges])


def _partition_block(table, key, n, seed, boundaries):
    from ray_tpu.data import block as B

    if boundaries is not None:
        return tuple(B.range_partition_block(table, key, boundaries))
    return tuple(B.hash_partition_block(table, key, n, seed))


def _merge_sort(key, descending, *parts):
    from ray_tpu.data import block as B

    return B.sort_block(B.concat_blocks(list(parts)), key, descending)


def _merge_shuffle(seed, *parts):
    from ray_tpu.data import block as B

    merged = B.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged
    rng = np.random.RandomState(seed)
    return merged.take(pa.array(rng.permutation(merged.num_rows)))


def _merge_groupby(key, aggs, *parts):
    from ray_tpu.data import block as B

    merged = B.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged
    agg_specs = [(col, fn) for col, fn in aggs]
    return merged.group_by(key).aggregate(agg_specs)


def _sample_block(table, key, k, seed):
    if table.num_rows == 0:
        return []
    rng = np.random.RandomState(seed)
    idx = rng.randint(0, table.num_rows, size=min(k, table.num_rows))
    return table.take(pa.array(idx)).column(key).to_pylist()


class _MapActor:
    """Actor-pool worker hosting a stateful UDF instance
    (reference: _MapWorker in actor_pool_map_operator.py)."""

    def __init__(self, cls_blob, ctor_args):
        import cloudpickle

        self.udf = cloudpickle.loads(cls_blob)(*ctor_args)

    def apply(self, wrapper_blob, table):
        import cloudpickle

        return cloudpickle.loads(wrapper_blob)(self.udf, table)


# -- the executor ------------------------------------------------------------


class StreamingExecutor:
    def __init__(self, parallelism: int = 8):
        self.parallelism = parallelism
        self._actor_pools: List[List[Any]] = []
        # Trailing window of actor-stage outputs: only tasks that may still be
        # in flight at teardown need sealing; a bounded deque avoids pinning
        # the whole stage output in the object store.
        self._actor_stage_refs: collections.deque = collections.deque(
            maxlen=2 * parallelism + 8
        )

    # Each stage: Iterator[ObjectRef[pa.Table]] -> Iterator[ObjectRef]

    def execute(self, ops: List[LogicalOp]) -> Iterator[Any]:
        """Yields block ObjectRefs for the fully-applied plan."""
        try:
            it = self._build(ops)
            yield from it
        finally:
            self._teardown_pools()

    def _teardown_pools(self):
        # Wait for every ref produced by an actor stage to materialize before
        # killing the pool: the consumer may not have fetched them yet, and a
        # killed actor can no longer seal its in-flight results.
        if self._actor_stage_refs:
            pending = list(self._actor_stage_refs)
            try:
                ray_tpu.wait(pending, num_returns=len(pending), timeout=60)
            except Exception:
                pass
            self._actor_stage_refs.clear()
        for pool in self._actor_pools:
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        self._actor_pools = []

    def _build(self, ops: List[LogicalOp]) -> Iterator[Any]:
        ops = _fuse_maps(list(ops))
        it: Optional[Iterator[Any]] = None
        for op in ops:
            if isinstance(op, Read):
                it = self._read_stage(op)
            elif isinstance(op, FromBlocks):
                it = iter([ray_tpu.put(b) for b in op.blocks])
            elif isinstance(op, MapBlocks):
                if op.actor_cls is not None:
                    it = self._actor_map_stage(op, it)
                else:
                    it = self._map_stage(op, it)
            elif isinstance(op, Limit):
                it = self._limit_stage(op, it)
            elif isinstance(op, Union):
                it = self._union_stage(op, it)
            elif isinstance(op, Zip):
                it = self._zip_stage(op, it)
            elif isinstance(op, (Repartition, Sort, RandomShuffle, GroupByAgg)):
                it = self._all_to_all_stage(op, it)
            else:
                raise TypeError(f"unknown logical op {op}")
        return it if it is not None else iter([])

    # -- stages --------------------------------------------------------------

    def _windowed(self, submit_iter) -> Iterator[Any]:
        """Ordered bounded-window pipeline: submit up to `parallelism`,
        yield head as it completes."""
        window: collections.deque = collections.deque()
        for ref in submit_iter:
            window.append(ref)
            while len(window) >= self.parallelism:
                yield window.popleft()
        while window:
            yield window.popleft()

    def _read_stage(self, op: Read) -> Iterator[Any]:
        import cloudpickle

        read = _remote(_exec_read, name=op.name)
        return self._windowed(
            read.remote(cloudpickle.dumps(t)) for t in op.read_tasks
        )

    def _map_stage(self, op: MapBlocks, upstream) -> Iterator[Any]:
        import cloudpickle

        blob = cloudpickle.dumps(op.fn)
        mapper = _remote(_exec_map, name=op.name)
        return self._windowed(mapper.remote(blob, ref) for ref in upstream)

    def _actor_map_stage(self, op: MapBlocks, upstream) -> Iterator[Any]:
        import cloudpickle

        cls = ray_tpu.remote(_MapActor)
        pool = [
            cls.options(max_concurrency=2, num_cpus=1).remote(
                op.actor_cls, op.actor_args
            )
            for _ in range(op.pool_size)
        ]
        self._actor_pools.append(pool)
        blob = cloudpickle.dumps(op.fn)

        def submit():
            for i, ref in enumerate(upstream):
                out = pool[i % len(pool)].apply.remote(blob, ref)
                self._actor_stage_refs.append(out)
                yield out

        return self._windowed(submit())

    def _limit_stage(self, op: Limit, upstream) -> Iterator[Any]:
        counter = _remote(_num_rows, num_cpus=0.5)
        slicer = _remote(_slice_concat, num_cpus=0.5)
        remaining = op.n
        upstream = iter(upstream)
        # Geometric window ramp: small limits stop after 1-2 blocks without
        # forcing a full parallelism window of upstream work; large limits
        # still amortize the count round-trips.
        window = 1
        while remaining > 0:
            chunk = list(itertools.islice(upstream, window))
            window = min(self.parallelism, window * 2)
            if not chunk:
                break
            counts = ray_tpu.get([counter.remote(r) for r in chunk])
            for ref, n in zip(chunk, counts):
                if remaining <= 0:
                    break
                if n <= remaining:
                    remaining -= n
                    yield ref
                else:
                    yield slicer.remote([(0, 0, remaining)], ref)
                    remaining = 0

    def _union_stage(self, op: Union, upstream) -> Iterator[Any]:
        yield from upstream
        for other_plan in op.others:
            sub = StreamingExecutor(self.parallelism)
            yield from sub.execute(other_plan)

    def _zip_stage(self, op: Zip, upstream) -> Iterator[Any]:
        """Blockwise zip: re-slice the right side to the left side's block
        boundaries, then one zip task per left block (no global concat —
        reference: ZipOperator aligns blocks the same way)."""
        left = list(upstream)
        sub = StreamingExecutor(self.parallelism)
        right = list(sub.execute(op.other))
        counter = _remote(_num_rows, num_cpus=0.5)
        l_counts = ray_tpu.get([counter.remote(r) for r in left])
        r_counts = ray_tpu.get([counter.remote(r) for r in right])
        if sum(l_counts) != sum(r_counts):
            raise ValueError(
                f"zip requires equal row counts: {sum(l_counts)} vs "
                f"{sum(r_counts)}"
            )
        slicer = _remote(_slice_concat, num_cpus=0.5)
        zipper = _remote(_zip_tables)
        r_offsets = np.cumsum([0] + r_counts)
        lo = 0
        for l_ref, n in zip(left, l_counts):
            hi = lo + n
            ranges, tables = [], []
            for i, r_ref in enumerate(right):
                s = max(lo, r_offsets[i])
                e = min(hi, r_offsets[i + 1])
                if s < e:
                    ranges.append(
                        (len(tables), int(s - r_offsets[i]), int(e - r_offsets[i]))
                    )
                    tables.append(r_ref)
            aligned = slicer.remote(ranges, *tables)
            yield zipper.remote(1, l_ref, aligned)
            lo = hi

    def _all_to_all_stage(self, op, upstream) -> Iterator[Any]:
        refs = list(upstream)
        if not refs:
            return
        if isinstance(op, Repartition):
            yield from self._repartition(refs, op.num_blocks)
            return
        n_parts = max(1, min(len(refs), self.parallelism))
        key = getattr(op, "key", None)
        seed = getattr(op, "seed", None)
        if seed is None:
            # Unseeded shuffle must differ across runs/epochs (reference
            # ray.data semantics).
            seed = random.randrange(2**31)
        boundaries = None
        if isinstance(op, Sort):
            sampler = _remote(_sample_block, num_cpus=0.5)
            samples = sorted(
                s
                for chunk in ray_tpu.get(
                    [sampler.remote(r, op.key, 16, i) for i, r in enumerate(refs)]
                )
                for s in chunk
            )
            if samples and n_parts > 1:
                step = max(1, len(samples) // n_parts)
                boundaries = sorted(set(samples[step::step]))[: n_parts - 1]
            else:
                boundaries = []
            n_parts = len(boundaries) + 1
        part = _remote(_partition_block, num_returns=n_parts)
        parts_per_block = [
            part.remote(r, key, n_parts, seed + i, boundaries)
            if n_parts > 1
            else [r]
            for i, r in enumerate(refs)
        ]
        if isinstance(op, Sort):
            merge = _remote(_merge_sort)
            order = range(n_parts - 1, -1, -1) if op.descending else range(n_parts)
            for p in order:
                yield merge.remote(
                    op.key, op.descending, *[pb[p] for pb in parts_per_block]
                )
        elif isinstance(op, RandomShuffle):
            merge = _remote(_merge_shuffle)
            for p in range(n_parts):
                yield merge.remote(seed + p, *[pb[p] for pb in parts_per_block])
        elif isinstance(op, GroupByAgg):
            merge = _remote(_merge_groupby)
            for p in range(n_parts):
                yield merge.remote(
                    op.key, op.aggs, *[pb[p] for pb in parts_per_block]
                )

    def _repartition(self, refs, num_blocks: int) -> Iterator[Any]:
        counter = _remote(_num_rows, num_cpus=0.5)
        counts = ray_tpu.get([counter.remote(r) for r in refs])
        total = sum(counts)
        slicer = _remote(_slice_concat)
        # Global row offsets -> num_blocks contiguous output ranges.
        starts = [round(total * j / num_blocks) for j in range(num_blocks)]
        ends = starts[1:] + [total]
        offsets = np.cumsum([0] + counts)
        for j in range(num_blocks):
            ranges, tables = [], []
            for i, r in enumerate(refs):
                lo = max(starts[j], offsets[i])
                hi = min(ends[j], offsets[i + 1])
                if lo < hi:
                    ranges.append(
                        (len(tables), int(lo - offsets[i]), int(hi - offsets[i]))
                    )
                    tables.append(r)
            yield slicer.remote(ranges, *tables)


def _zip_tables(n_left, *blocks):
    from ray_tpu.data import block as B

    lt = B.concat_blocks(list(blocks[:n_left]))
    rt = B.concat_blocks(list(blocks[n_left:]))
    if lt.num_rows != rt.num_rows:
        raise ValueError(
            f"zip requires equal row counts: {lt.num_rows} vs {rt.num_rows}"
        )
    cols = {}
    for name in lt.column_names:
        cols[name] = lt.column(name)
    for name in rt.column_names:
        out = name
        while out in cols:
            out = out + "_1"
        cols[out] = rt.column(name)
    return pa.table(cols)


def _fuse_maps(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Fuse consecutive task-pool MapBlocks into one task per block."""
    out: List[LogicalOp] = []
    for op in ops:
        if (
            isinstance(op, MapBlocks)
            and op.actor_cls is None
            and out
            and isinstance(out[-1], MapBlocks)
            and out[-1].actor_cls is None
        ):
            prev = out.pop()
            f, g = prev.fn, op.fn
            out.append(
                MapBlocks(
                    fn=lambda t, f=f, g=g: g(f(t)),
                    name=f"{prev.name}->{op.name}",
                )
            )
        else:
            out.append(op)
    return out
