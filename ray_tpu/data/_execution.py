"""Logical ops + streaming execution (reference: python/ray/data/_internal —
logical/interfaces/logical_operator.py, execution/streaming_executor.py:48,
execution/operators/*).

Execution model: each stage is a generator over RefBundles — a block
ObjectRef paired with its BlockMeta (num_rows/size_bytes), returned by the
stage task itself via ``num_returns=2`` — with a bounded in-flight window:
downstream pulling makes upstream submit, so the whole pipeline streams with
backpressure, like the reference's pull-based StreamingExecutor. Because
metadata rides alongside every ref, Limit/Zip/Repartition dispatch on a
batched inline-object get instead of submitting counter tasks per block.

Output order: stages hand refs downstream in submission order (a task's
output ref is a valid task arg before the task finishes, so interior stages
never wait). The FINAL output is resequenced by completion when
``preserve_order=False`` — a ``ray_tpu.wait``-driven bounded window yields
whichever block materializes first, so one slow read task no longer stalls
the consumer behind head-of-line blocking. ``preserve_order=True`` (the
default, and what Dataset-level iteration uses) keeps submission order.

Map-chains are fused into one task per block, and a task-pool MapBlocks
following a Read fuses INTO the read task (reference: operator fusion in
plan optimization) so a read->map->filter pipeline costs one task and one
object-store round trip per block.
"""

from __future__ import annotations

import collections
import itertools
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu import ObjectRef
from ray_tpu._private import telemetry
from ray_tpu.data import block as B

# -- telemetry (docs/observability.md: component "data") ---------------------

_BLOCKS_PRODUCED = telemetry.counter(
    "data", "blocks_produced", "blocks yielded per stage"
)
_BYTES_PRODUCED = telemetry.counter(
    "data", "bytes_produced", "block bytes yielded per stage (where metadata "
    "is resolved driver-side; fetch-path bytes are data.bytes_fetched)"
)
_META_RESOLVES = telemetry.counter(
    "data", "meta_resolves", "batched metadata gets (replaces counter tasks)"
)
_TEARDOWN_CANCELS = telemetry.counter(
    "data", "teardown_cancelled_refs", "undelivered actor-stage refs "
    "cancelled instead of awaited at teardown"
)

# -- logical ops -------------------------------------------------------------


@dataclass
class LogicalOp:
    pass


@dataclass
class Read(LogicalOp):
    """Each read_task() -> pa.Table; one task per input partition."""

    read_tasks: List[Callable[[], pa.Table]] = field(default_factory=list)
    name: str = "Read"


@dataclass
class FromBlocks(LogicalOp):
    blocks: List[pa.Table] = field(default_factory=list)


@dataclass
class MapBlocks(LogicalOp):
    """fn(pa.Table) -> pa.Table. Covers map/filter/flat_map/map_batches."""

    fn: Callable[[pa.Table], pa.Table] = None
    name: str = "Map"
    # Class-based UDF → actor pool (reference: ActorPoolMapOperator).
    actor_cls: Optional[bytes] = None  # cloudpickled class
    actor_args: Tuple = ()
    pool_size: int = 2


@dataclass
class Limit(LogicalOp):
    n: int = 0


@dataclass
class Union(LogicalOp):
    others: List[List[LogicalOp]] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: List[LogicalOp] = field(default_factory=list)


@dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1


@dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclass
class GroupByAgg(LogicalOp):
    key: str = ""
    aggs: List[Tuple[str, str]] = field(default_factory=list)  # (col, fn)


# -- ref bundles -------------------------------------------------------------


class RefBundle:
    """One block ObjectRef plus its metadata (reference: RefBundle in
    execution/interfaces/ref_bundle.py). ``meta`` is either a concrete
    BlockMeta (known driver-side, e.g. FromBlocks) or the ObjectRef of the
    task's second return value."""

    __slots__ = ("block", "meta")

    def __init__(self, block, meta):
        self.block = block
        self.meta = meta


def _from_returns(refs) -> RefBundle:
    """Bundle a ``num_returns=2`` task's [block_ref, meta_ref] pair."""
    return RefBundle(refs[0], refs[1])


def resolve_metas(bundles: List[RefBundle]) -> List[B.BlockMeta]:
    """Resolve every bundle's metadata with ONE batched get for the ref-typed
    ones (tiny inline objects — no task submissions, no block fetches)."""
    ref_idx = [i for i, b in enumerate(bundles) if isinstance(b.meta, ObjectRef)]
    out: List[Any] = [b.meta for b in bundles]
    if ref_idx:
        fetched = ray_tpu.get([bundles[i].meta for i in ref_idx])
        for i, m in zip(ref_idx, fetched):
            out[i] = m
            bundles[i].meta = m  # cache: later stages reuse without a get
        _META_RESOLVES.inc()
    return out


# -- remote kernels ----------------------------------------------------------


def _remote(fn, **opts):
    return ray_tpu.remote(**{"num_cpus": 1, **opts})(fn)


def _with_meta(table: pa.Table):
    return table, B.meta_for(table)


def _exec_read(task_blob):
    import cloudpickle

    return _with_meta(cloudpickle.loads(task_blob)())


def _exec_map(fn_blob, table):
    import cloudpickle

    return _with_meta(cloudpickle.loads(fn_blob)(table))


def _slice_concat(ranges, *tables):
    """ranges: list of (table_idx, start, end) over the varargs tables.

    Block refs ride as top-level varargs because only top-level ObjectRef
    args are resolved to values before execution (same contract as the
    reference's task arg resolution)."""
    from ray_tpu.data import block as B

    return _with_meta(
        B.concat_blocks([B.slice_block(tables[i], s, e) for i, s, e in ranges])
    )


def _partition_block(table, key, n, seed, boundaries):
    from ray_tpu.data import block as B

    if boundaries is not None:
        return tuple(B.range_partition_block(table, key, boundaries))
    return tuple(B.hash_partition_block(table, key, n, seed))


def _merge_sort(key, descending, *parts):
    from ray_tpu.data import block as B

    return _with_meta(B.sort_block(B.concat_blocks(list(parts)), key, descending))


def _merge_shuffle(seed, *parts):
    from ray_tpu.data import block as B

    merged = B.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return _with_meta(merged)
    rng = np.random.RandomState(seed)
    return _with_meta(merged.take(pa.array(rng.permutation(merged.num_rows))))


def _merge_groupby(key, aggs, *parts):
    from ray_tpu.data import block as B

    merged = B.concat_blocks(list(parts))
    if merged.num_rows == 0:
        return _with_meta(merged)
    agg_specs = [(col, fn) for col, fn in aggs]
    return _with_meta(merged.group_by(key).aggregate(agg_specs))


def _sample_block(table, key, k, seed):
    if table.num_rows == 0:
        return []
    rng = np.random.RandomState(seed)
    idx = rng.randint(0, table.num_rows, size=min(k, table.num_rows))
    return table.take(pa.array(idx)).column(key).to_pylist()


class _MapActor:
    """Actor-pool worker hosting a stateful UDF instance
    (reference: _MapWorker in actor_pool_map_operator.py)."""

    def __init__(self, cls_blob, ctor_args):
        import cloudpickle

        self.udf = cloudpickle.loads(cls_blob)(*ctor_args)

    def apply(self, wrapper_blob, table):
        import cloudpickle

        return _with_meta(cloudpickle.loads(wrapper_blob)(self.udf, table))


# -- the executor ------------------------------------------------------------


class StreamingExecutor:
    def __init__(self, parallelism: int = 8, preserve_order: bool = True):
        self.parallelism = parallelism
        self.preserve_order = preserve_order
        self._actor_pools: List[List[Any]] = []
        # Trailing window of actor-stage outputs that were handed DOWNSTREAM
        # (a later stage may have consumed them as task args, or the
        # consumer may be fetching them): only these need sealing before the
        # pool dies. Bounded so teardown never pins a whole stage output.
        self._actor_stage_refs: collections.deque = collections.deque(
            maxlen=2 * parallelism + 8
        )
        # Actor-stage outputs submitted but NOT yet handed downstream: no
        # other task depends on them and the consumer has never seen them,
        # so teardown cancels instead of awaiting (the abandoned-iteration
        # fast path — see _teardown_pools).
        self._actor_refs_pending: dict = {}

    # Each stage: Iterator[RefBundle] -> Iterator[RefBundle]

    def execute(self, ops: List[LogicalOp]) -> Iterator[RefBundle]:
        """Yields RefBundles for the fully-applied plan."""
        from ray_tpu.util import tracing

        try:
            it = self._build(ops)
            if not self.preserve_order:
                it = self._completion_order(it)
            # One span over the whole streamed execution, active while the
            # stage pumps run: every stage task submitted inside joins a
            # single trace (rooted here when none is ambient).
            yield from tracing.iter_scope(
                it, "data.execute", "data", stages=len(ops)
            )
        finally:
            self._teardown_pools()

    def _completion_order(self, it) -> Iterator[RefBundle]:
        """Bounded resequencer: keep up to ``parallelism`` final outputs
        buffered and yield whichever block ref completes first
        (``ray_tpu.wait``-driven), so a straggler task delays only itself."""
        buf: List[RefBundle] = []
        it = iter(it)
        exhausted = False
        while True:
            while not exhausted and len(buf) < self.parallelism:
                try:
                    buf.append(next(it))
                except StopIteration:
                    exhausted = True
            if not buf:
                return
            pick = 0
            if len(buf) > 1:
                try:
                    ready, _ = ray_tpu.wait(
                        [b.block for b in buf], num_returns=1, timeout=None
                    )
                except Exception:
                    ready = []
                if ready:
                    first = ready[0]
                    for i, b in enumerate(buf):
                        if b.block is first or b.block == first:
                            pick = i
                            break
            yield buf.pop(pick)

    def _teardown_pools(self):
        # Refs handed downstream may be task args or in-flight consumer
        # fetches — those must seal before the pool dies (a killed actor can
        # no longer seal its results and the waiter would hang). Refs the
        # consumer NEVER received (still queued in the stage window when
        # iteration was abandoned) have no waiters: cancel them instead of
        # riding out the whole trailing window's execution.
        for ref in self._actor_refs_pending.values():
            try:
                ray_tpu.cancel(ref)
                _TEARDOWN_CANCELS.inc()
            except Exception:
                pass
        self._actor_refs_pending.clear()
        if self._actor_stage_refs:
            pending = list(self._actor_stage_refs)
            try:
                ray_tpu.wait(pending, num_returns=len(pending), timeout=60)
            except Exception:
                pass
            self._actor_stage_refs.clear()
        for pool in self._actor_pools:
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        self._actor_pools = []

    def _build(self, ops: List[LogicalOp]) -> Iterator[RefBundle]:
        ops = _fuse_maps(list(ops))
        it: Optional[Iterator[RefBundle]] = None
        for op in ops:
            if isinstance(op, Read):
                it = self._read_stage(op)
            elif isinstance(op, FromBlocks):
                it = iter(
                    [
                        RefBundle(ray_tpu.put(b), B.meta_for(b))
                        for b in op.blocks
                    ]
                )
            elif isinstance(op, MapBlocks):
                if op.actor_cls is not None:
                    it = self._actor_map_stage(op, it)
                else:
                    it = self._map_stage(op, it)
            elif isinstance(op, Limit):
                it = self._limit_stage(op, it)
            elif isinstance(op, Union):
                it = self._union_stage(op, it)
            elif isinstance(op, Zip):
                it = self._zip_stage(op, it)
            elif isinstance(op, (Repartition, Sort, RandomShuffle, GroupByAgg)):
                it = self._all_to_all_stage(op, it)
            else:
                raise TypeError(f"unknown logical op {op}")
        return it if it is not None else iter([])

    # -- stages --------------------------------------------------------------

    def _windowed(self, submit_iter, stage: str = "") -> Iterator[RefBundle]:
        """Bounded-window pipeline: submit up to `parallelism`, hand the head
        downstream as the window fills. Yields follow submission order — a
        ref is a valid downstream task arg before its task completes, so
        interior stages never block here (final-output reordering is
        _completion_order's job)."""
        cell = _BLOCKS_PRODUCED.cell(stage=stage) if stage else None
        window: collections.deque = collections.deque()
        for bundle in submit_iter:
            window.append(bundle)
            while len(window) >= self.parallelism:
                if cell is not None:
                    cell.inc()
                yield window.popleft()
        while window:
            if cell is not None:
                cell.inc()
            yield window.popleft()

    def _read_stage(self, op: Read) -> Iterator[RefBundle]:
        import cloudpickle

        read = _remote(_exec_read, name=op.name, num_returns=2)
        return self._windowed(
            (
                _from_returns(read.remote(cloudpickle.dumps(t)))
                for t in op.read_tasks
            ),
            stage=op.name,
        )

    def _map_stage(self, op: MapBlocks, upstream) -> Iterator[RefBundle]:
        import cloudpickle

        blob = cloudpickle.dumps(op.fn)
        mapper = _remote(_exec_map, name=op.name, num_returns=2)
        return self._windowed(
            (
                _from_returns(mapper.remote(blob, b.block))
                for b in upstream
            ),
            stage=op.name,
        )

    def _actor_map_stage(self, op: MapBlocks, upstream) -> Iterator[RefBundle]:
        import cloudpickle

        cls = ray_tpu.remote(_MapActor)
        pool = [
            cls.options(max_concurrency=2, num_cpus=1).remote(
                op.actor_cls, op.actor_args
            )
            for _ in range(op.pool_size)
        ]
        self._actor_pools.append(pool)
        blob = cloudpickle.dumps(op.fn)

        def submit():
            for i, bundle in enumerate(upstream):
                refs = (
                    pool[i % len(pool)]
                    .apply.options(num_returns=2)
                    .remote(blob, bundle.block)
                )
                self._actor_refs_pending[refs[0].hex()] = refs[0]
                yield _from_returns(refs)

        def delivered():
            for bundle in self._windowed(submit(), stage=op.name):
                # Leaving the stage window: downstream may now depend on it,
                # so it graduates from cancel-on-teardown to seal-before-kill.
                self._actor_refs_pending.pop(bundle.block.hex(), None)
                self._actor_stage_refs.append(bundle.block)
                yield bundle

        return delivered()

    def _limit_stage(self, op: Limit, upstream) -> Iterator[RefBundle]:
        slicer = _remote(_slice_concat, num_cpus=0.5, num_returns=2)
        remaining = op.n
        upstream = iter(upstream)
        # Geometric window ramp: small limits stop after 1-2 blocks without
        # forcing a full parallelism window of upstream work; large limits
        # amortize the (batched, inline) metadata gets.
        window = 1
        bytes_cell = _BYTES_PRODUCED.cell(stage="Limit")
        try:
            while remaining > 0:
                chunk = list(itertools.islice(upstream, window))
                window = min(self.parallelism, window * 2)
                if not chunk:
                    break
                metas = resolve_metas(chunk)
                for bundle, meta in zip(chunk, metas):
                    if remaining <= 0:
                        break
                    if meta.num_rows <= remaining:
                        remaining -= meta.num_rows
                        bytes_cell.inc(meta.size_bytes)
                        yield bundle
                    else:
                        yield _from_returns(
                            slicer.remote([(0, 0, remaining)], bundle.block)
                        )
                        remaining = 0
        finally:
            close = getattr(upstream, "close", None)
            if close is not None:  # stop upstream submission promptly
                close()

    def _union_stage(self, op: Union, upstream) -> Iterator[RefBundle]:
        yield from upstream
        for other_plan in op.others:
            sub = StreamingExecutor(self.parallelism)
            yield from sub.execute(other_plan)

    def _zip_stage(self, op: Zip, upstream) -> Iterator[RefBundle]:
        """Blockwise zip: re-slice the right side to the left side's block
        boundaries, then one zip task per left block (no global concat —
        reference: ZipOperator aligns blocks the same way). Row counts come
        from the bundled metadata — zero counter tasks."""
        left = list(upstream)
        sub = StreamingExecutor(self.parallelism)
        right = list(sub.execute(op.other))
        l_counts = [m.num_rows for m in resolve_metas(left)]
        r_counts = [m.num_rows for m in resolve_metas(right)]
        if sum(l_counts) != sum(r_counts):
            raise ValueError(
                f"zip requires equal row counts: {sum(l_counts)} vs "
                f"{sum(r_counts)}"
            )
        slicer = _remote(_slice_concat, num_cpus=0.5, num_returns=2)
        zipper = _remote(_zip_tables, num_returns=2)
        r_offsets = np.cumsum([0] + r_counts)
        lo = 0
        for l_bundle, n in zip(left, l_counts):
            hi = lo + n
            ranges, tables = [], []
            for i, r_bundle in enumerate(right):
                s = max(lo, r_offsets[i])
                e = min(hi, r_offsets[i + 1])
                if s < e:
                    ranges.append(
                        (len(tables), int(s - r_offsets[i]), int(e - r_offsets[i]))
                    )
                    tables.append(r_bundle.block)
            aligned = slicer.remote(ranges, *tables)
            yield _from_returns(zipper.remote(1, l_bundle.block, aligned[0]))
            lo = hi

    def _all_to_all_stage(self, op, upstream) -> Iterator[RefBundle]:
        bundles = list(upstream)
        if not bundles:
            return
        if isinstance(op, Repartition):
            yield from self._repartition(bundles, op.num_blocks)
            return
        refs = [b.block for b in bundles]
        n_parts = max(1, min(len(refs), self.parallelism))
        key = getattr(op, "key", None)
        seed = getattr(op, "seed", None)
        if seed is None:
            # Unseeded shuffle must differ across runs/epochs (reference
            # ray.data semantics).
            seed = random.randrange(2**31)
        boundaries = None
        if isinstance(op, Sort):
            sampler = _remote(_sample_block, num_cpus=0.5)
            samples = sorted(
                s
                for chunk in ray_tpu.get(
                    [sampler.remote(r, op.key, 16, i) for i, r in enumerate(refs)]
                )
                for s in chunk
            )
            if samples and n_parts > 1:
                step = max(1, len(samples) // n_parts)
                boundaries = sorted(set(samples[step::step]))[: n_parts - 1]
            else:
                boundaries = []
            n_parts = len(boundaries) + 1
        part = _remote(_partition_block, num_returns=n_parts)
        parts_per_block = [
            part.remote(r, key, n_parts, seed + i, boundaries)
            if n_parts > 1
            else [r]
            for i, r in enumerate(refs)
        ]
        if isinstance(op, Sort):
            merge = _remote(_merge_sort, num_returns=2)
            order = range(n_parts - 1, -1, -1) if op.descending else range(n_parts)
            for p in order:
                yield _from_returns(
                    merge.remote(
                        op.key, op.descending, *[pb[p] for pb in parts_per_block]
                    )
                )
        elif isinstance(op, RandomShuffle):
            merge = _remote(_merge_shuffle, num_returns=2)
            for p in range(n_parts):
                yield _from_returns(
                    merge.remote(seed + p, *[pb[p] for pb in parts_per_block])
                )
        elif isinstance(op, GroupByAgg):
            merge = _remote(_merge_groupby, num_returns=2)
            for p in range(n_parts):
                yield _from_returns(
                    merge.remote(
                        op.key, op.aggs, *[pb[p] for pb in parts_per_block]
                    )
                )

    def _repartition(self, bundles, num_blocks: int) -> Iterator[RefBundle]:
        counts = [m.num_rows for m in resolve_metas(bundles)]
        total = sum(counts)
        slicer = _remote(_slice_concat, num_returns=2)
        # Global row offsets -> num_blocks contiguous output ranges.
        starts = [round(total * j / num_blocks) for j in range(num_blocks)]
        ends = starts[1:] + [total]
        offsets = np.cumsum([0] + counts)
        for j in range(num_blocks):
            ranges, tables = [], []
            for i, b in enumerate(bundles):
                lo = max(starts[j], offsets[i])
                hi = min(ends[j], offsets[i + 1])
                if lo < hi:
                    ranges.append(
                        (len(tables), int(lo - offsets[i]), int(hi - offsets[i]))
                    )
                    tables.append(b.block)
            yield _from_returns(slicer.remote(ranges, *tables))


def _zip_tables(n_left, *blocks):
    from ray_tpu.data import block as B

    lt = B.concat_blocks(list(blocks[:n_left]))
    rt = B.concat_blocks(list(blocks[n_left:]))
    if lt.num_rows != rt.num_rows:
        raise ValueError(
            f"zip requires equal row counts: {lt.num_rows} vs {rt.num_rows}"
        )
    cols = {}
    for name in lt.column_names:
        cols[name] = lt.column(name)
    for name in rt.column_names:
        out = name
        while out in cols:
            out = out + "_1"
        cols[out] = rt.column(name)
    return _with_meta(pa.table(cols))


def _fuse_maps(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Fuse consecutive task-pool MapBlocks into one task per block, then
    fuse a leading Read with the task-pool MapBlocks that follows it so
    read->map costs ONE task and one object-store round trip per block
    (reference: read->map operator fusion in plan optimization)."""
    out: List[LogicalOp] = []
    for op in ops:
        if (
            isinstance(op, MapBlocks)
            and op.actor_cls is None
            and out
            and isinstance(out[-1], MapBlocks)
            and out[-1].actor_cls is None
        ):
            prev = out.pop()
            f, g = prev.fn, op.fn
            out.append(
                MapBlocks(
                    fn=lambda t, f=f, g=g: g(f(t)),
                    name=f"{prev.name}->{op.name}",
                )
            )
        elif (
            isinstance(op, MapBlocks)
            and op.actor_cls is None
            and out
            and isinstance(out[-1], Read)
        ):
            prev = out.pop()
            g = op.fn
            out.append(
                Read(
                    read_tasks=[
                        (lambda t=t, g=g: g(t())) for t in prev.read_tasks
                    ],
                    name=f"{prev.name}->{op.name}",
                )
            )
        else:
            out.append(op)
    return out
