"""Build script for native extensions.

Usage: python setup.py build_ext --inplace
Builds ray_tpu/_native/_shm*.so (POSIX shm buffer extension). The framework
falls back to multiprocessing.shared_memory when the extension is absent, so
pure-Python installs still work; the native path avoids the resource-tracker
overhead and gives page-aligned zero-copy buffers.
"""

from setuptools import Extension, setup

setup(
    name="ray-tpu",
    ext_modules=[
        Extension(
            "ray_tpu._native._shm",
            sources=["src/shm_buffer.cc"],
            extra_compile_args=["-O2", "-std=c++17"],
            libraries=["rt"],
        ),
        Extension(
            "ray_tpu._native._store",
            sources=["src/store_core.cc"],
            extra_compile_args=["-O2", "-std=c++17"],
        ),
        Extension(
            "ray_tpu._native._fastpath",
            sources=["src/fastpath.cc"],
            extra_compile_args=["-O2", "-std=c++17"],
        ),
    ],
)
