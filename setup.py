"""Build script for native extensions.

Usage: python setup.py build_ext --inplace
Builds ray_tpu/_native/{_shm,_store,_fastpath}*.so. The framework falls
back to pure Python where an extension is absent (shm via
multiprocessing.shared_memory, task dispatch via the RPC path), so
pure-Python installs still work.

Sanitizer builds (reference: the C++ tree's TSAN/ASAN CI configs): set
RAY_TPU_SANITIZE=address|thread|undefined to compile the extensions with
the matching -fsanitize instrumentation, then run the native tests under
it, e.g.

    RAY_TPU_SANITIZE=address python setup.py build_ext --inplace
    LD_PRELOAD=$(gcc -print-file-name=libasan.so) \\
        python -m pytest tests/test_store_core.py tests/test_fastpath_native.py
"""

import os

from setuptools import Extension, setup

_SAN = os.environ.get("RAY_TPU_SANITIZE")
_san_flags = [f"-fsanitize={_SAN}", "-fno-omit-frame-pointer", "-g"] if _SAN else []

setup(
    name="ray-tpu",
    ext_modules=[
        Extension(
            "ray_tpu._native._shm",
            sources=["src/shm_buffer.cc"],
            extra_compile_args=["-O2", "-std=c++17"] + _san_flags,
            extra_link_args=list(_san_flags),
            libraries=["rt"],
        ),
        Extension(
            "ray_tpu._native._store",
            sources=["src/store_core.cc"],
            extra_compile_args=["-O2", "-std=c++17"] + _san_flags,
            extra_link_args=list(_san_flags),
        ),
        Extension(
            "ray_tpu._native._fastpath",
            sources=["src/fastpath.cc"],
            extra_compile_args=["-O2", "-std=c++17"] + _san_flags,
            extra_link_args=list(_san_flags),
        ),
    ],
)
