"""Flagship benchmark: ResNet-50 train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference's headline Train-ResNet e2e number, 40.7 images/s on
one GPU worker (BASELINE.md / doc/source/train/benchmarks.rst:36). Same
model family + train-step workload (synthetic ImageNet-shape data, bf16),
so vs_baseline = images_per_sec / 40.7.
"""

from __future__ import annotations

import json
import time

BASELINE_IMAGES_PER_SEC = 40.7  # reference: 1-GPU Train ResNet e2e


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import ResNetConfig, resnet_apply, resnet_init

    platform = jax.devices()[0].platform
    batch = 256 if platform == "tpu" else 8
    size = 224 if platform == "tpu" else 64
    steps = 20 if platform == "tpu" else 3

    cfg = ResNetConfig(depth=50, num_classes=1000, dtype=jnp.bfloat16)
    params = resnet_init(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.1, momentum=0.9)
    opt = tx.init(params)

    def loss_fn(params, images, labels):
        logits, new_params = resnet_apply(params, images, cfg, train=True)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        return loss, new_params

    @jax.jit
    def step(params, opt, images, labels):
        (loss, new_params), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, images, labels)
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(new_params, updates)
        return params, opt, loss

    images = jax.random.normal(
        jax.random.PRNGKey(1), (batch, size, size, 3), jnp.bfloat16
    )
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    # Warmup (compile) then timed steps.
    params, opt, loss = step(params, opt, images, labels)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step(params, opt, images, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_1chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
