"""Flagship benchmark: ResNet-50 + GPT-2 transformer training on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
   "mfu": ..., "e2e_images_per_sec": ..., "transformer_tokens_per_sec": ...}

Three phases, each in its own subprocess (the axon TPU tunnel admits one
process at a time, and the e2e phase needs the chip free for its train
worker):

1. **step** — raw jitted ResNet train-step throughput (synthetic resident
   data). MFU uses XLA's compiled cost analysis (multiply-add = 2 flops,
   the same convention as the chip's quoted peak). Measured: ~30% MFU at
   batch 128. NOTE on why: XLA's "bytes accessed" (51 GB/step) is an
   upper bound that counts every buffer touch, not post-fusion HBM
   traffic, so it cannot be used for a roofline bound (it would imply
   <=2048 img/s, below what we measure). The honest statement is the
   measurement itself: ~30% MFU, consistent with public ResNet-on-TPU
   results where small convolution shapes underfill the MXU.

   Committed NEGATIVE RESULTS (v5e, measured 2026-07, round 5):
   - batch sweep 64/128/256/512 -> 2197/2476/2314/2150 img/s (MFU
     0.266/0.302/0.281/0.267): batch 128 is the knee; larger batches LOWER
     utilization on this chip, so the requested batch-256 experiment does
     not move MFU toward 0.40.
   - MLPerf-style space-to-depth stem (ResNetConfig.space_to_depth=True:
     2x2 s2d + 4x4/s1 conv replacing the 7x7/s2, cin 3 -> 12) -> 2478
     img/s at batch 128, parity within noise: XLA's conv lowering already
     handles the stem about as well, i.e. the remaining gap is spread
     across the many small-spatial 1x1/3x3 convs + BN, not one fixable op.
2. **transformer** — the flagship decoder transformer (models/transformer.py)
   at GPT-2-small scale (124M params, vocab 50304, seq 1024, batch 32,
   remat): one jitted train step. The pallas flash backward + chunked
   LM-head CE are what make batch 32 fit and the step MXU-bound.

   MFU derivation (v5e peak 197e12 bf16 FLOP/s): useful flops/token =
   3 * (L*(matmul_fwd + causal_attn_fwd) + lm_head_fwd) = 7.98e8 for this
   config (flops_per_token; causal attention averages (S+1)/2 attended
   keys — crediting full S^2 overcounts ~2x and is what made round 4's
   0.81 "MFU" exceed peak once recompute was added). Hardware flops/token
   adds the flash-backward recompute and the per-block remat recompute:
   1.006e9 (hardware_flops_per_token). Measured ~183k tok/s => useful-MFU
   ~0.74, hardware-MFU ~0.93 < 1.0 (the arithmetic sanity bound round 4's
   number failed). Cross-checks, committed here because they cannot run in
   CI: (a) remat=False OOMs at B=32 (21.8G > 15.75G HBM) — remat is
   load-bearing, not optional; (b) at B=8, remat=True 62.7k tok/s vs
   remat=False 65.7k tok/s — recompute costs ~5% wall despite +26%
   analytic flops, so hardware-MFU is an UPPER bound on executed work
   (XLA elides part of the recompute); (c) XLA cost analysis reports
   7.3e7 flops/token for the compiled step — it counts the lax.scan body
   ONCE (trip count not folded) and cannot see pallas custom calls, so it
   cross-checks the per-layer term, not the total.
3. **e2e** — ingest -> train through the framework, mirroring the measured
   reference workload (doc/source/train/benchmarks.rst:36: Train ResNet e2e
   with Ray Data ingest, 40.7 images/s on one GPU worker): a
   ray_tpu.data pipeline (parallel synth-decode tasks -> columnar tensor
   blocks in the shm object store -> true streaming_split) feeds a 1-worker
   JaxTrainer that runs the same train step per batch, with the h2d copy
   double-buffered via iter_batches(_finalize_fn=device_put). Timed window
   covers the whole warm pipeline (execution + iteration + h2d + step),
   excluding only process bring-up and jit compilation. The phase also
   COMMITS the breakdown — ingest_only_images_per_sec (full pipeline, no
   device) and iter_only_images_per_sec (materialized blocks -> batches) —
   so the location of any e2e-vs-step gap is a measurement in
   BENCH_r{N}.json, not a docstring claim.

   Measured composition on this CI host (1 CPU core, chip behind the axon
   tunnel; 2026-07, round 5): ingest-only 1429 img/s, iter-only 2.28M
   img/s, raw step 2476 img/s, e2e 439 img/s. With one core there is no
   parallelism to overlap INTO: decode tasks, the split coordinator, the
   train worker's batch assembly, and the tunnel h2d all time-share the
   same core, so e2e ~= 1 / (1/ingest + 1/worker-side) rather than
   min(ingest, step). The worker-side term (~630 img/s) is dominated by
   the ~95 MB/s uint8 h2d through the tunnel. On a real TPU VM (dozens of
   cores, PCIe-attached chips) the same code overlaps: ingest and the
   step pipeline run on different cores and h2d is not tunneled.

Baseline: the reference's headline Train-ResNet e2e number, 40.7 images/s
(BASELINE.md). vs_baseline compares the matching e2e phase.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

BASELINE_IMAGES_PER_SEC = 40.7  # reference: 1-GPU Train ResNet e2e

# Peak bf16 FLOP/s per chip by device kind (public spec sheet numbers).
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}
_DEFAULT_PEAK = 197e12


def _peak_for(kind: str) -> float:
    for prefix, peak in _PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return _DEFAULT_PEAK


def phase_step() -> dict:
    import time

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import ResNetConfig, resnet_apply, resnet_init

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64
    steps = 30 if on_tpu else 3

    cfg = ResNetConfig(depth=50, num_classes=1000, dtype=jnp.bfloat16)
    params = resnet_init(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.1, momentum=0.9)
    opt = tx.init(params)

    def loss_fn(params, images, labels):
        logits, new_params = resnet_apply(params, images, cfg, train=True)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        return loss, new_params

    def step(params, opt, images, labels):
        (loss, new_params), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, images, labels)
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(new_params, updates)
        return params, opt, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    images = jax.random.normal(
        jax.random.PRNGKey(1), (batch, size, size, 3), jnp.bfloat16
    )
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    # AOT-lower once for cost analysis. The timed loop runs the jitted
    # dispatch path, NOT the lowered executable: invoking the AOT object
    # directly through the axon TPU tunnel intermittently loses
    # step-to-step sequencing and reports impossible rates (observed 79k
    # img/s / "9.7 MFU" on a chip whose peak supports ~8k).
    ca = jstep.lower(params, opt, images, labels).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    flops_per_step = float(ca.get("flops", 0.0) or 0.0)
    bytes_per_step = float(ca.get("bytes accessed", 0.0) or 0.0)

    # Warmup (compiles the dispatch-path executable) then timed steps.
    params, opt, loss = jstep(params, opt, images, labels)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = jstep(params, opt, images, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    peak = _peak_for(dev.device_kind)
    mfu = (flops_per_step / batch) * images_per_sec / peak if flops_per_step else 0.0
    return {
        "step_images_per_sec": round(images_per_sec, 2),
        "mfu": round(mfu, 4),
        "flops_per_image": round(flops_per_step / max(batch, 1), 0),
        "hbm_gb_per_step": round(bytes_per_step / 1e9, 2),
        "device_kind": dev.device_kind,
        "peak_flops": peak,
        "batch": batch,
    }


def phase_transformer() -> dict:
    """Flagship decoder-transformer train step at GPT-2-small scale.

    MFU accounting (two numbers, deliberately separate):

    - transformer_mfu (useful-MFU): analytic USEFUL flops/token — 6ND plus
      the CAUSAL attention term (the flash kernel really skips masked
      tiles, so non-causal accounting would overcount ~2x) — times
      measured tokens/s, over the chip's bf16 peak. No recomputation is
      credited: recompute is overhead, not useful work.
    - transformer_hw_mfu (hardware-MFU): the flops the chip actually
      executes — useful + flash-backward recompute (+ block-remat
      recompute when remat=True) — over peak. This number MUST be < 1.0;
      it is the arithmetic sanity bound on the measurement.

    Cross-check: transformer_xla_flops_per_token reports XLA's compiled
    cost analysis for the same executable. XLA cannot see inside pallas
    custom calls, so it misses the attention flops; analytic non-attention
    hardware flops should bracket it.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import TransformerConfig, make_train_step
    from ray_tpu.models.transformer import (
        flops_per_token,
        hardware_flops_per_token,
    )
    from ray_tpu.parallel import make_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=50304, d_model=768, n_layers=12, n_heads=12,
            max_seq_len=1024, dtype=jnp.bfloat16, remat=True,
        )
        B, S, steps = 32, 1024, 40
    else:  # probe/CI shapes
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            max_seq_len=128, dtype=jnp.float32,
        )
        B, S, steps = 4, 64, 3

    mesh = make_mesh({"data": 1}, devices=[dev])
    init_state, step, shardings = make_train_step(cfg, mesh, optax.adamw(1e-3))
    state = init_state(jax.random.PRNGKey(0))
    raw = jax.random.randint(
        jax.random.PRNGKey(1), (B, S + 1), 0, cfg.vocab_size
    )
    batch = {
        "tokens": jax.device_put(raw[:, :-1], shardings["tokens"]),
        "targets": jax.device_put(raw[:, 1:], shardings["tokens"]),
    }
    # XLA's flops view of the step, cross-check only (pallas custom calls
    # are opaque to it, and it counts the lax.scan body once). MEASUREMENT
    # NOTE: the timed loop below deliberately runs the jitted dispatch
    # path, NOT this AOT executable — calling the lowered executable
    # directly through the axon TPU tunnel returns without proper
    # step-to-step sequencing and yields impossible (>1 MFU) rates.
    xla_flops_per_token = 0.0
    try:
        ca = step.lower(state, batch).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        xla_flops_per_token = float(ca.get("flops", 0.0) or 0.0) / (B * S)
    except Exception:
        pass
    state, m = step(state, batch)  # compile + warmup
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = B * S * steps / dt
    n_params = sum(
        int(np.prod(x.shape))
        for x in jax.tree_util.tree_leaves(state["params"])
    )
    useful = flops_per_token(cfg, S)
    hardware = hardware_flops_per_token(cfg, S)
    peak = _peak_for(dev.device_kind)
    return {
        "transformer_tokens_per_sec": round(tokens_per_sec, 0),
        "transformer_mfu": round(useful * tokens_per_sec / peak, 4),
        "transformer_hw_mfu": round(hardware * tokens_per_sec / peak, 4),
        "transformer_useful_flops_per_token": round(useful, 0),
        "transformer_hw_flops_per_token": round(hardware, 0),
        "transformer_xla_flops_per_token": round(xla_flops_per_token, 0),
        "transformer_remat": bool(cfg.remat),
        "transformer_params_m": round(n_params / 1e6, 1),
        "transformer_batch": B,
        "transformer_seq": S,
    }


def phase_e2e() -> dict:
    """Ingest -> train e2e: ray_tpu.data pipeline feeding a JaxTrainer.

    Streaming: decode tasks, block transport, batch assembly, and the h2d
    copy all overlap the device step (true streaming_split + _finalize_fn
    device_put in the prefetch thread), so steady-state e2e approaches
    min(ingest rate, step rate) instead of their serial sum. Alongside the
    e2e number this phase measures the breakdown:
      - ingest_only_images_per_sec: the full data pipeline (execute ->
        split -> fetch -> batch) consumed with no device work at all;
      - iter_only_images_per_sec: batch iteration over already-materialized
        blocks (no execution, no device) — the pure consumer-side path.
    """
    import time

    import numpy as np

    import ray_tpu
    from ray_tpu import data as rd
    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    probe = os.environ.get("RAY_TPU_BENCH_PROBE") == "1"
    n_blocks = 4 if probe else 16
    rows_per_block = 16 if probe else 256
    size = 64 if probe else 224
    batch = 8 if probe else 256

    def synth_batch(batch) -> dict:
        # Stands in for read+decode: produces raw uint8 image rows as ONE
        # columnar block — the (N, H*W*C) image array becomes a contiguous
        # Arrow tensor column that moves through the object store as a
        # single zero-copy buffer (no per-row bytes objects anywhere).
        seed = int(np.asarray(batch["id"]).reshape(-1)[0])
        rng = np.random.default_rng(seed)
        # rng.bytes is the cheapest generator that still writes every byte
        # (the decode stand-in must produce real per-image data, not a view
        # of one shared buffer).
        images = np.frombuffer(
            rng.bytes(rows_per_block * size * size * 3), dtype=np.uint8
        ).reshape(rows_per_block, size * size * 3)
        labels = rng.integers(0, 1000, rows_per_block).astype(np.int64)
        return {"image": images, "label": labels}

    def train_fn(config):
        import time

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import ResNetConfig, resnet_apply, resnet_init

        size, batch = config["size"], config["batch"]
        cfg = ResNetConfig(depth=50, num_classes=1000, dtype=jnp.bfloat16)
        params = resnet_init(jax.random.PRNGKey(0), cfg)
        tx = optax.sgd(0.1, momentum=0.9)
        opt = tx.init(params)

        def loss_fn(params, images, labels):
            logits, new_params = resnet_apply(params, images, cfg, train=True)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
            return loss, new_params

        @jax.jit
        def step(params, opt, raw_u8, labels):
            # Normalize on device: only uint8 crosses host->device.
            images = raw_u8.astype(jnp.bfloat16) / 127.5 - 1.0
            (loss, new_params), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, images, labels)
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(new_params, updates)
            return params, opt, loss

        # Compile outside the timed window with a synthetic batch, so the
        # measurement covers the FULL pipeline — execution (decode tasks ->
        # shm blocks), iteration, h2d transfer, and the train step — but not
        # one-time jit compilation.
        warm = np.zeros((batch, size, size, 3), dtype=np.uint8)
        warm_labels = np.zeros((batch,), dtype=np.int32)
        params, opt, loss = step(params, opt, jnp.asarray(warm), jnp.asarray(warm_labels))
        jax.block_until_ready(loss)

        def to_device(raw):
            # Runs in the prefetch thread (_finalize_fn): the reshape is a
            # free view and device_put is async, so the h2d copy of batch
            # k+1 overlaps the device compute of batch k.
            imgs = np.asarray(raw["image"]).reshape(-1, size, size, 3)
            labels = np.asarray(raw["label"], dtype=np.int32)
            return jax.device_put(imgs), jax.device_put(labels), len(imgs)

        shard = train.get_dataset_shard("train")
        n = 0
        t0 = time.perf_counter()
        for imgs, labels, k in shard.iter_batches(
            batch_size=batch, batch_format="numpy", prefetch_batches=2,
            _finalize_fn=to_device,
        ):
            params, opt, loss = step(params, opt, imgs, labels)
            n += k
        if n == 0:
            raise RuntimeError("dataset shard yielded no batches")
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        train.report({"e2e_images_per_sec": n / dt if dt > 0 else 0.0, "n": n})

    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        # Warm the worker pool (spawn + import cost) with a throwaway
        # pipeline so the measured window is steady-state ingest, not
        # process bring-up — the reference's e2e methodology also measures
        # warm epochs (doc/source/train/benchmarks.rst: multi-epoch runs).
        warm = rd.range(4, parallelism=4).map_batches(
            lambda b: {"x": np.zeros((2, 8), dtype=np.uint8)}, batch_size=1
        )
        for _ in warm.iter_batches(batch_size=None):
            pass

        def make_ds():
            return rd.range(n_blocks, parallelism=n_blocks).map_batches(
                synth_batch, batch_size=1
            )

        result = JaxTrainer(
            train_fn,
            train_loop_config={"size": size, "batch": batch},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="bench_e2e", storage_path="/tmp/rt_bench_e2e"),
            datasets={"train": make_ds()},
        ).fit()

        # -- breakdown: ingest-only (full warm pipeline, no device work) ----
        shard = make_ds().streaming_split(1)[0]
        n = 0
        t0 = time.perf_counter()
        for b in shard.iter_batches(batch_size=batch, prefetch_batches=2):
            n += len(b["label"])
        ingest_dt = time.perf_counter() - t0
        ingest_only = n / ingest_dt if ingest_dt > 0 else 0.0

        # -- breakdown: iter-only (materialized blocks -> batches) ----------
        from ray_tpu.data.iterator import batches_from_blocks

        blocks = list(make_ds().iter_blocks())
        n = 0
        t0 = time.perf_counter()
        for b in batches_from_blocks(iter(blocks), batch, "numpy"):
            n += len(b["label"])
        iter_dt = time.perf_counter() - t0
        iter_only = n / iter_dt if iter_dt > 0 else 0.0

        return {
            "e2e_images_per_sec": round(result.metrics["e2e_images_per_sec"], 2),
            "e2e_images": result.metrics["n"],
            "ingest_only_images_per_sec": round(ingest_only, 2),
            "iter_only_images_per_sec": round(iter_only, 2),
        }
    finally:
        ray_tpu.shutdown()


def _run_phase(name: str) -> dict:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", name],
        capture_output=True,
        text=True,
        timeout=1800,
    )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"phase {name} produced no JSON: {out.stdout[-2000:]}\n{out.stderr[-2000:]}")


def main():
    if "--phase" in sys.argv:
        idx = sys.argv.index("--phase")
        phase = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        phases = {"step": phase_step, "e2e": phase_e2e,
                  "transformer": phase_transformer}
        if phase not in phases:
            raise SystemExit(
                f"unknown --phase {phase!r}; expected one of {sorted(phases)}"
            )
        print(json.dumps(phases[phase]()))
        return
    step = _run_phase("step")
    try:
        tf = _run_phase("transformer")
    except Exception as e:
        tf = {"transformer_tokens_per_sec": 0.0, "transformer_error": str(e)[:500]}
    try:
        e2e = _run_phase("e2e")
    except Exception as e:  # e2e must not mask the headline number
        e2e = {"e2e_images_per_sec": 0.0, "e2e_error": str(e)[:500]}
    out = {
        "metric": "resnet50_train_images_per_sec_1chip",
        "value": step["step_images_per_sec"],
        "unit": "images/sec",
        # Baseline is the reference's e2e-with-ingest number; compare like
        # with like.
        "vs_baseline": round(
            (e2e.get("e2e_images_per_sec") or 0.0) / BASELINE_IMAGES_PER_SEC, 2
        ),
        **{k: v for k, v in step.items() if k != "step_images_per_sec"},
        **tf,
        **e2e,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
