# Developer entry points. CI (.github/workflows/ci.yml) runs the same
# commands; keep the two in sync.

PYTHON ?= python
SANITIZER ?= address

.PHONY: lint test sanitize wire-docs flow-docs protocols build chaos loadgen \
	perf explore

# The unified gate (all passes + stale-suppression audit + wall-time
# budget), then the rpc_flow mutation gate: a seeded synchronous back-call
# cycle must be detected, or the pass has lost its teeth.
lint:
	$(PYTHON) -m ray_tpu.devtools.lint
	$(PYTHON) -m ray_tpu.devtools.rpc_flow --mutate back_call \
		--expect-violation
	$(PYTHON) -m ray_tpu.devtools.exc_flow --mutate swallow_cancel \
		--expect-violation

test:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider -p no:xdist \
		-p no:randomly

build:
	$(PYTHON) setup.py build_ext --inplace

# Rebuild the C++ extensions with -fsanitize=$(SANITIZER) and run the
# native-path tests under the instrumented .so files. ASan needs its
# runtime loaded before python, hence the LD_PRELOAD (gcc resolves the
# right libasan for the toolchain); UBSan links its runtime statically.
sanitize:
	RAY_TPU_SANITIZE=$(SANITIZER) $(PYTHON) setup.py build_ext --inplace
	@if [ "$(SANITIZER)" = "address" ]; then \
		env LD_PRELOAD=$$(gcc -print-file-name=libasan.so) \
			ASAN_OPTIONS=detect_leaks=0 JAX_PLATFORMS=cpu \
			$(PYTHON) -m pytest tests/test_store_core.py \
			tests/test_fastpath_native.py -q -p no:cacheprovider; \
	else \
		env JAX_PLATFORMS=cpu $(PYTHON) -m pytest \
			tests/test_store_core.py tests/test_fastpath_native.py \
			-q -p no:cacheprovider; \
	fi
	$(PYTHON) setup.py build_ext --inplace  # restore uninstrumented .so

wire-docs:
	$(PYTHON) -m ray_tpu.devtools.rpc_check --markdown > docs/wire_protocol.md

# Regenerate the cross-process blocking-graph inventory; CI fails if the
# checked-in copy is stale.
flow-docs:
	$(PYTHON) -m ray_tpu.devtools.rpc_flow --markdown > docs/rpc_flow.md

# Regenerate the FSM reference from the machine-readable spec; CI fails if
# the checked-in copy is stale.
protocols:
	$(PYTHON) -m ray_tpu.devtools.protocols --markdown > docs/protocols.md

# Deterministic fault injection (docs/chaos.md). SEEDS seeds per scenario;
# failing seeds land in chaos_corpus.jsonl for replay. The latency suite
# exercises the RPC resilience layer (docs/resilience.md) over fewer seeds.
# Serve load harness (docs/serving.md): closed-loop calibration plus a 5x
# open-loop overload phase against a local deployment; exits nonzero if an
# admitted request overruns its deadline.
loadgen:
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.loadgen --smoke

# Perf floors (CI perf-smoke job runs the same commands): the ray_perf
# microbenchmark suite — tasks/actors/put/get plus the streaming-ingest
# leg (ingest_rows_per_s) — and the serve loadgen smoke, gated together
# against benchmarks/perf_floors.json. Then the native-wire A/B: the
# lease bench runs with and without RAY_TPU_NATIVE_WIRE=0 and the gate
# asserts the _fastpath codec strictly wins (pack >= 1.2x) and the
# end-to-end lease rate doesn't regress with native enabled.
perf:
	timeout -k 10 900 env JAX_PLATFORMS=cpu \
		$(PYTHON) -m ray_tpu._private.ray_perf --json /tmp/perf.json
	timeout -k 10 300 env JAX_PLATFORMS=cpu \
		$(PYTHON) -m ray_tpu.loadgen --smoke --json /tmp/serve_load.json
	$(PYTHON) benchmarks/perf_gate.py /tmp/perf.json /tmp/serve_load.json
	timeout -k 10 600 env JAX_PLATFORMS=cpu \
		$(PYTHON) benchmarks/native_ab.py

# Exhaustive interleaving explorer (docs/static_analysis.md): enumerate
# the control-plane scenarios' schedule spaces under the virtual loop
# (lease + ha exhaust; resubscribe runs bounded-clean), prove the
# double-grant mutation is still caught and its committed trace still
# replays to the violation, then scan the WAL/replicated-store
# group-commit crash points. CI's explore-smoke job runs the same
# commands.
HA_EXPLORE_BUDGET ?= 40000
explore:
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--scenario lease_exactly_once --budget 5000 --check-determinism
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--scenario ha_promotion --budget $(HA_EXPLORE_BUDGET)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--scenario quorum_election --budget 4000 --check-determinism
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--scenario resubscribe_gap --budget 3000 --allow-bounded
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--scenario lease_exactly_once --mutate double_grant \
		--expect-violation
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--replay tests/schedules/lease_double_grant.json --expect-violation
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.devtools.explore \
		--crash-points

SEEDS ?= 20
LATENCY_SEEDS ?= 10
SCHED_SEEDS ?= 10
RECOVERY_SEEDS ?= 10
COLLECTIVE_SEEDS ?= 5
HA_SEEDS ?= 10
SPILL_SEEDS ?= 10
chaos:
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos --check-determinism \
		--suite full --seeds $(SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos --suite smoke \
		--seeds $(SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos --suite latency \
		--seeds $(LATENCY_SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos --suite sched \
		--seeds $(SCHED_SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos \
		--suite recovery_durable --seeds $(RECOVERY_SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos \
		--suite ha --seeds $(HA_SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos \
		--suite collective --seeds $(COLLECTIVE_SEEDS)
	env JAX_PLATFORMS=cpu $(PYTHON) -m ray_tpu.chaos \
		--suite spill --seeds $(SPILL_SEEDS)
