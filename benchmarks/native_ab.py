"""Native-wire A/B gate: prove the `_fastpath` codec wins, and that the
end-to-end lease path does not regress when it is enabled.

Two legs (``make perf`` runs both; the CI fastpath-parity job runs
``--codec-only``):

**Codec leg** — packs/decodes representative lease-path frames
(RequestWorkerLease, an 8-entry LeaseBatch, grant replies) through
``_fastpath.pack_frame``/``_fastpath.Decoder`` and through msgpack's C
extension, and asserts the native codec strictly wins (pack >= 1.2x,
decode >= 1.02x; measured ~1.7x / ~1.25x on the reference box). This is
the honest form of "native wins": both codecs are C, and the native one
is faster because it is specialized (no Packer object churn, no ext-type
dispatch, frame-shaped fast paths).

**End-to-end leg** — runs the scheduler bench (`ray_perf._bench_sched`)
in two fresh subprocesses, native enabled vs ``RAY_TPU_NATIVE_WIRE=0``,
and asserts native is not a regression beyond run-to-run noise
(native >= 0.85x fallback). End-to-end the two are within noise on the
1-core box: the lease cycle spends ~65us in asyncio callback machinery
and ~4us in codec work, so a 1.7x codec win moves the total by ~2% —
docs/perf.md "where the 1.15 ms goes" has the full budget.

Exit 0 = both assertions hold; exit 1 with a diff report otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import msgpack

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # invoked as `python benchmarks/native_ab.py`
    sys.path.insert(0, REPO)

PACK_MIN_RATIO = 1.2
DECODE_MIN_RATIO = 1.02
E2E_MIN_RATIO = 0.85

_LEASE_PAYLOAD = {
    "bundle_index": -1,
    "job_id": "job-000001",
    "lease_id": "6f1d9c2ab34e5f60718293a4b5c6d7e8",
    "locality": {},
    "pg_id": "",
    "resources": {"CPU": 10000},
    "spilled_from": "",
    "strategy": "DEFAULT",
}


def _frames():
    req = [17, 0, "RequestWorkerLease", _LEASE_PAYLOAD, 5.0]
    batch = [
        0,
        3,
        "LeaseBatch",
        {
            "entries": [
                [100 + i, "RequestWorkerLease", dict(_LEASE_PAYLOAD), 5.0, None]
                for i in range(8)
            ]
        },
    ]
    grant = [
        17,
        1,
        "RequestWorkerLease",
        {
            "granted": True,
            "lease_id": _LEASE_PAYLOAD["lease_id"],
            "worker": {"addr": ["127.0.0.1", 43210], "worker_id": "w" * 32},
            "retry_at_raylet": None,
        },
    ]
    return [req, batch, grant]


def bench_codec(rounds: int = 30000):
    from ray_tpu._native import _fastpath

    frames = _frames()
    packer = msgpack.Packer(use_bin_type=True, autoreset=True)

    # -- pack --
    t0 = time.perf_counter()
    for _ in range(rounds):
        for f in frames:
            packer.pack(f)
    t_msgpack_pack = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(rounds):
        for f in frames:
            _fastpath.pack_frame(f)
    t_native_pack = time.perf_counter() - t0

    # -- decode (streaming, like Connection.data_received) --
    blob = b"".join(packer.pack(f) for f in frames) * 64
    n_frames = 3 * 64
    dec_rounds = max(1, rounds // 64)

    t0 = time.perf_counter()
    for _ in range(dec_rounds):
        u = msgpack.Unpacker(use_list=True, raw=False, strict_map_key=False)
        u.feed(blob)
        n = sum(1 for _ in u)
        assert n == n_frames
    t_msgpack_dec = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(dec_rounds):
        d = _fastpath.Decoder()
        d.feed(blob)
        n = sum(1 for _ in d)
        assert n == n_frames
    t_native_dec = time.perf_counter() - t0

    return {
        "pack_ratio": t_msgpack_pack / t_native_pack,
        "decode_ratio": t_msgpack_dec / t_native_dec,
        "native_pack_us": t_native_pack / (rounds * 3) * 1e6,
        "msgpack_pack_us": t_msgpack_pack / (rounds * 3) * 1e6,
        "native_decode_us": t_native_dec / (dec_rounds * n_frames) * 1e6,
        "msgpack_decode_us": t_msgpack_dec / (dec_rounds * n_frames) * 1e6,
    }


_E2E_CHILD = """\
import json, sys
from ray_tpu._private import ray_perf
print(json.dumps(ray_perf._bench_sched()))
"""


def bench_e2e(tasks: int):
    rates = {}
    for label, native in (("native", "1"), ("fallback", "0")):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            RAY_TPU_NATIVE_WIRE=native,
            RAY_TPU_SCHED_BENCH_TASKS=str(tasks),
        )
        out = subprocess.run(
            [sys.executable, "-c", _E2E_CHILD],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=600,
        )
        if out.returncode != 0:
            print(out.stdout, file=sys.stderr)
            print(out.stderr, file=sys.stderr)
            raise RuntimeError(f"{label} bench child failed")
        rates[label] = json.loads(out.stdout.strip().splitlines()[-1])
    return rates


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--codec-only",
        action="store_true",
        help="skip the end-to-end subprocess A/B (CI runners are too noisy "
        "for a throughput comparison; the codec leg is load-independent)",
    )
    parser.add_argument("--tasks", type=int, default=4000)
    args = parser.parse_args(argv)

    failures = []

    codec = bench_codec()
    print(
        f"codec pack:   native {codec['native_pack_us']:.2f}us/frame vs "
        f"msgpack {codec['msgpack_pack_us']:.2f}us/frame "
        f"-> {codec['pack_ratio']:.2f}x (min {PACK_MIN_RATIO}x)"
    )
    print(
        f"codec decode: native {codec['native_decode_us']:.2f}us/frame vs "
        f"msgpack {codec['msgpack_decode_us']:.2f}us/frame "
        f"-> {codec['decode_ratio']:.2f}x (min {DECODE_MIN_RATIO}x)"
    )
    if codec["pack_ratio"] < PACK_MIN_RATIO:
        failures.append(
            f"native pack ratio {codec['pack_ratio']:.2f}x "
            f"below {PACK_MIN_RATIO}x"
        )
    if codec["decode_ratio"] < DECODE_MIN_RATIO:
        failures.append(
            f"native decode ratio {codec['decode_ratio']:.2f}x "
            f"below {DECODE_MIN_RATIO}x"
        )

    if not args.codec_only:
        rates = bench_e2e(args.tasks)
        nat = rates["native"]["leases_per_s"]
        fb = rates["fallback"]["leases_per_s"]
        ratio = nat / fb
        print(
            f"e2e sched:    native {nat:.0f} leases/s vs "
            f"fallback (RAY_TPU_NATIVE_WIRE=0) {fb:.0f} leases/s "
            f"-> {ratio:.2f}x (min {E2E_MIN_RATIO}x; within-noise expected, "
            f"see docs/perf.md)"
        )
        if ratio < E2E_MIN_RATIO:
            failures.append(
                f"end-to-end lease rate with native wire ({nat:.0f}/s) "
                f"regressed below {E2E_MIN_RATIO:.0%} of the msgpack "
                f"fallback ({fb:.0f}/s)"
            )

    if failures:
        print("\nnative A/B FAILED:", file=sys.stderr)
        for line in failures:
            print(f"  {line}", file=sys.stderr)
        return 1
    print("\nnative A/B passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
