"""Perf regression gate: compare results JSONs against the committed floors
and fail (exit 1) on any metric outside its bounds.

Usage::

    python -m ray_tpu._private.ray_perf --json /tmp/perf.json
    python -m ray_tpu.loadgen --smoke --json /tmp/serve.json
    python benchmarks/perf_gate.py /tmp/perf.json /tmp/serve.json

Multiple results files are shallow-merged (later files win on key
collisions) so the core-runtime and serving harnesses gate together.

Floors live in benchmarks/perf_floors.json next to this script; each gated
metric records the reference rate it was set from and a ``floor`` at 70% of
it, so the gate trips on a >30% regression. Latency-style metrics where
lower is better carry a ``ceiling`` instead (measured must stay at or
below it). A metric present in the floors file but missing from the
results is a failure too (a silently-dropped benchmark must not pass the
gate).

Runtime telemetry stays ENABLED for every gated run: the put/get/transfer
floors therefore bound the instrumented hot paths, and the dedicated
``telemetry_overhead_ns`` ceiling bounds the per-record cost itself.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_FLOORS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "perf_floors.json")


def gate(results_paths: List[str], floors_path: str = _FLOORS) -> int:
    if isinstance(results_paths, str):  # back-compat: single-path callers
        results_paths = [results_paths]
    results = {}
    for path in results_paths:
        with open(path) as f:
            results.update(json.load(f))
    with open(floors_path) as f:
        floors = json.load(f)

    failures = []
    print(f"{'metric':<28} {'measured':>12} {'bound':>12} {'reference':>12}")
    for name, spec in floors["metrics"].items():
        ref = spec["reference"]
        ceiling = spec.get("ceiling")
        floor = spec.get("floor")
        bound = ceiling if ceiling is not None else floor
        measured = results.get(name)
        if measured is None:
            failures.append(f"{name}: missing from results")
            print(f"{name:<28} {'MISSING':>12} {bound:>12.1f} {ref:>12.1f}")
            continue
        if ceiling is not None:
            ok = measured <= ceiling
            if not ok:
                failures.append(
                    f"{name}: {measured:.1f} is above ceiling {ceiling:.1f} "
                    f"({measured / ref:.0%} of reference {ref:.1f})"
                )
        else:
            ok = measured >= floor
            if not ok:
                failures.append(
                    f"{name}: {measured:.1f} is below floor {floor:.1f} "
                    f"({measured / ref:.0%} of reference {ref:.1f})"
                )
        verdict = "" if ok else "  << REGRESSION"
        print(f"{name:<28} {measured:>12.1f} {bound:>12.1f} {ref:>12.1f}{verdict}")
    if failures:
        print("\nperf gate FAILED:", file=sys.stderr)
        for line in failures:
            print(f"  {line}", file=sys.stderr)
        return 1
    print("\nperf gate passed")
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "results",
        nargs="+",
        help="results JSON path(s): ray_perf --json and/or loadgen --smoke "
        "--json output; merged before gating",
    )
    parser.add_argument("--floors", default=_FLOORS)
    args = parser.parse_args()
    sys.exit(gate(args.results, args.floors))
