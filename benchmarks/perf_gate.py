"""Perf regression gate: compare a ray_perf results JSON against the
committed floors and fail (exit 1) on any metric below its floor.

Usage::

    python -m ray_tpu._private.ray_perf --json /tmp/perf.json
    python benchmarks/perf_gate.py /tmp/perf.json

Floors live in benchmarks/perf_floors.json next to this script; each gated
metric records the reference rate it was set from and a ``floor`` at 70% of
it, so the gate trips on a >30% regression. A metric present in the floors
file but missing from the results is a failure too (a silently-dropped
benchmark must not pass the gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_FLOORS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "perf_floors.json")


def gate(results_path: str, floors_path: str = _FLOORS) -> int:
    with open(results_path) as f:
        results = json.load(f)
    with open(floors_path) as f:
        floors = json.load(f)

    failures = []
    print(f"{'metric':<28} {'measured':>12} {'floor':>12} {'reference':>12}")
    for name, spec in floors["metrics"].items():
        floor, ref = spec["floor"], spec["reference"]
        measured = results.get(name)
        if measured is None:
            failures.append(f"{name}: missing from results")
            print(f"{name:<28} {'MISSING':>12} {floor:>12.1f} {ref:>12.1f}")
            continue
        verdict = "" if measured >= floor else "  << REGRESSION"
        print(f"{name:<28} {measured:>12.1f} {floor:>12.1f} {ref:>12.1f}{verdict}")
        if measured < floor:
            failures.append(
                f"{name}: {measured:.1f}/s is below floor {floor:.1f}/s "
                f"({measured / ref:.0%} of reference {ref:.1f}/s)"
            )
    if failures:
        print("\nperf gate FAILED:", file=sys.stderr)
        for line in failures:
            print(f"  {line}", file=sys.stderr)
        return 1
    print("\nperf gate passed")
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("results", help="ray_perf --json output path")
    parser.add_argument("--floors", default=_FLOORS)
    args = parser.parse_args()
    sys.exit(gate(args.results, args.floors))
