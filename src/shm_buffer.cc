// ray_tpu._native._shm — POSIX shared-memory segments with zero-copy buffer
// protocol access.
//
// TPU-native analog of the reference's plasma store mmap layer
// (src/ray/object_manager/plasma/{dlmalloc.cc,plasma_allocator.cc}): the
// reference subdivides one big mmap with dlmalloc because plasma clients
// attach a single fd; here each object gets its own shm segment (named by
// object id) and the kernel does the sharing — the object directory, ref
// counting and eviction live in the raylet daemon. Buffers are page-aligned
// by construction, so numpy/jax views over them are aligned for dlpack.
//
// Exposed API:
//   create(name, size)  -> ShmBuffer (read-write, O_CREAT|O_EXCL)
//   open_ro(name)       -> ShmBuffer (read-only)
//   open_rw(name)       -> ShmBuffer (read-write, existing)
//   unlink(name)        -> None
//   ShmBuffer: buffer protocol, .size, .name, .close()

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <fcntl.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

#include <string>
#include <thread>
#include <vector>

namespace {

typedef struct {
  PyObject_HEAD
  void* addr;
  Py_ssize_t size;
  int writable;
  int exports;
  char name[256];
} ShmBufferObject;

static PyObject* ShmError;

static void ShmBuffer_dealloc(ShmBufferObject* self) {
  if (self->addr != nullptr && self->addr != MAP_FAILED) {
    munmap(self->addr, static_cast<size_t>(self->size));
    self->addr = nullptr;
  }
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

static int ShmBuffer_getbuffer(ShmBufferObject* self, Py_buffer* view, int flags) {
  if (self->addr == nullptr) {
    PyErr_SetString(ShmError, "buffer is closed");
    return -1;
  }
  if ((flags & PyBUF_WRITABLE) && !self->writable) {
    PyErr_SetString(PyExc_BufferError, "shm buffer is read-only");
    return -1;
  }
  int rc = PyBuffer_FillInfo(view, reinterpret_cast<PyObject*>(self), self->addr,
                             self->size, self->writable ? 0 : 1, flags);
  if (rc == 0) self->exports++;
  return rc;
}

static void ShmBuffer_releasebuffer(ShmBufferObject* self, Py_buffer* view) {
  (void)view;
  self->exports--;
}

static PyBufferProcs ShmBuffer_as_buffer = {
    reinterpret_cast<getbufferproc>(ShmBuffer_getbuffer),
    reinterpret_cast<releasebufferproc>(ShmBuffer_releasebuffer),
};

static PyObject* ShmBuffer_close(ShmBufferObject* self, PyObject* Py_UNUSED(args)) {
  if (self->exports > 0) {
    PyErr_SetString(ShmError, "cannot close shm buffer with exported views");
    return nullptr;
  }
  if (self->addr != nullptr && self->addr != MAP_FAILED) {
    munmap(self->addr, static_cast<size_t>(self->size));
    self->addr = nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* ShmBuffer_get_size(ShmBufferObject* self, void*) {
  return PyLong_FromSsize_t(self->size);
}

static PyObject* ShmBuffer_get_name(ShmBufferObject* self, void*) {
  return PyUnicode_FromString(self->name);
}

static PyObject* ShmBuffer_get_closed(ShmBufferObject* self, void*) {
  return PyBool_FromLong(self->addr == nullptr);
}

static PyMethodDef ShmBuffer_methods[] = {
    {"close", reinterpret_cast<PyCFunction>(ShmBuffer_close), METH_NOARGS,
     "Unmap the segment. Fails if memoryviews are outstanding."},
    {nullptr, nullptr, 0, nullptr},
};

static PyGetSetDef ShmBuffer_getset[] = {
    {"size", reinterpret_cast<getter>(ShmBuffer_get_size), nullptr, nullptr, nullptr},
    {"name", reinterpret_cast<getter>(ShmBuffer_get_name), nullptr, nullptr, nullptr},
    {"closed", reinterpret_cast<getter>(ShmBuffer_get_closed), nullptr, nullptr, nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

static PyTypeObject ShmBufferType = {
    PyVarObject_HEAD_INIT(nullptr, 0) "ray_tpu._native._shm.ShmBuffer", /* tp_name */
    sizeof(ShmBufferObject),
};

static ShmBufferObject* make_buffer(const char* name, void* addr, Py_ssize_t size,
                                    int writable) {
  ShmBufferObject* self =
      PyObject_New(ShmBufferObject, &ShmBufferType);
  if (self == nullptr) return nullptr;
  self->addr = addr;
  self->size = size;
  self->writable = writable;
  self->exports = 0;
  strncpy(self->name, name, sizeof(self->name) - 1);
  self->name[sizeof(self->name) - 1] = '\0';
  return self;
}

static PyObject* shm_create(PyObject*, PyObject* args) {
  const char* name;
  Py_ssize_t size;
  if (!PyArg_ParseTuple(args, "sn", &name, &size)) return nullptr;
  if (size <= 0) {
    PyErr_SetString(ShmError, "size must be positive");
    return nullptr;
  }
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    PyErr_Format(ShmError, "shm_open(create %s) failed: %s", name, strerror(errno));
    return nullptr;
  }
  if (ftruncate(fd, size) != 0) {
    PyErr_Format(ShmError, "ftruncate(%s, %zd) failed: %s", name, size, strerror(errno));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* addr = mmap(nullptr, static_cast<size_t>(size), PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (addr == MAP_FAILED) {
    PyErr_Format(ShmError, "mmap(%s) failed: %s", name, strerror(errno));
    shm_unlink(name);
    return nullptr;
  }
  return reinterpret_cast<PyObject*>(make_buffer(name, addr, size, 1));
}

static PyObject* shm_open_impl(PyObject* args, int writable) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  int fd = shm_open(name, writable ? O_RDWR : O_RDONLY, 0600);
  if (fd < 0) {
    PyErr_Format(ShmError, "shm_open(%s) failed: %s", name, strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    PyErr_Format(ShmError, "fstat(%s) failed: %s", name, strerror(errno));
    close(fd);
    return nullptr;
  }
  void* addr = mmap(nullptr, static_cast<size_t>(st.st_size),
                    writable ? (PROT_READ | PROT_WRITE) : PROT_READ, MAP_SHARED, fd, 0);
  close(fd);
  if (addr == MAP_FAILED) {
    PyErr_Format(ShmError, "mmap(%s) failed: %s", name, strerror(errno));
    return nullptr;
  }
  return reinterpret_cast<PyObject*>(
      make_buffer(name, addr, static_cast<Py_ssize_t>(st.st_size), writable));
}

static PyObject* shm_open_ro(PyObject*, PyObject* args) { return shm_open_impl(args, 0); }
static PyObject* shm_open_rw(PyObject*, PyObject* args) { return shm_open_impl(args, 1); }

static PyObject* shm_unlink_py(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  if (shm_unlink(name) != 0 && errno != ENOENT) {
    PyErr_Format(ShmError, "shm_unlink(%s) failed: %s", name, strerror(errno));
    return nullptr;
  }
  Py_RETURN_NONE;
}

// prefault(buffer[, nthreads]): touch every page so later writes into the
// arena don't pay first-touch page-allocation faults (the dominant cost of a
// large object put — measured ~17 ms per 16 MiB on tmpfs vs ~1.5 ms
// pre-faulted). GIL released; reference analog: plasma pre-allocates its
// whole /dev/shm arena at startup (plasma_allocator.cc).
static PyObject* shm_prefault(PyObject*, PyObject* args) {
  Py_buffer view;
  int nthreads = 4;
  if (!PyArg_ParseTuple(args, "w*|i", &view, &nthreads)) return nullptr;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  char* base = static_cast<char*>(view.buf);
  Py_ssize_t total = view.len;
  Py_BEGIN_ALLOW_THREADS;
  const Py_ssize_t kPage = 4096;
  Py_ssize_t chunk = (total / nthreads + kPage - 1) & ~(kPage - 1);
  std::vector<std::thread> threads;
  for (int t = 0; t < nthreads; t++) {
    Py_ssize_t lo = t * chunk;
    if (lo >= total) break;
    Py_ssize_t hi = lo + chunk < total ? lo + chunk : total;
    threads.emplace_back([base, lo, hi, kPage]() {
      for (Py_ssize_t off = lo; off < hi; off += kPage) {
        // Atomic CAS(0 -> 0): forces a write fault (page allocation) on
        // untouched pages and is a no-op on pages holding data — safe to
        // run concurrently with client writes into the arena.
        char* p = base + off;
        char expected = 0;
        __atomic_compare_exchange_n(p, &expected, 0, false,
                                    __ATOMIC_RELAXED, __ATOMIC_RELAXED);
      }
    });
  }
  for (auto& th : threads) th.join();
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&view);
  Py_RETURN_NONE;
}

// Non-temporal (streaming) copy: bypasses the cache hierarchy on the store
// side, so writes into a cold arena region skip the read-for-ownership
// traffic a cached store pays. Measured on the dev box (1-core Xeon,
// tmpfs destination outside LLC): 16 MiB memcpy ~2.0 ms vs NT copy
// ~1.2 ms. Falls back to memcpy when AVX2 is unavailable.
#if defined(__x86_64__)
__attribute__((target("avx2"))) static void nt_copy_avx2(char* d, const char* s,
                                                         size_t n) {
  size_t head = (64 - (reinterpret_cast<uintptr_t>(d) & 63)) & 63;
  if (head > n) head = n;
  if (head) {
    memcpy(d, s, head);
    d += head;
    s += head;
    n -= head;
  }
  size_t blocks = n / 64;
  for (size_t i = 0; i < blocks; i++) {
    __m256i a = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s));
    __m256i b = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s + 32));
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d), a);
    _mm256_stream_si256(reinterpret_cast<__m256i*>(d + 32), b);
    d += 64;
    s += 64;
  }
  _mm_sfence();
  memcpy(d, s, n - blocks * 64);
}
#endif

static void fast_copy(char* d, const char* s, size_t n) {
#if defined(__x86_64__)
  // NT stores only win when the destination is unlikely to be re-read from
  // cache immediately — true for arena writes of multi-MiB objects.
  if (n >= (1 << 20) && __builtin_cpu_supports("avx2")) {
    nt_copy_avx2(d, s, n);
    return;
  }
#endif
  memcpy(d, s, n);
}

// copy_nt(dst, src): single-threaded streaming copy with the GIL released.
// The arena-write primitive for few-core hosts where parallel_copy's
// fan-out overhead loses (serialization.py picks between them).
static PyObject* shm_copy_nt(PyObject*, PyObject* args) {
  Py_buffer dst, src;
  if (!PyArg_ParseTuple(args, "w*y*", &dst, &src)) return nullptr;
  if (src.len > dst.len) {
    PyBuffer_Release(&dst);
    PyBuffer_Release(&src);
    PyErr_SetString(ShmError, "copy_nt: source larger than destination");
    return nullptr;
  }
  char* d = static_cast<char*>(dst.buf);
  const char* s = static_cast<const char*>(src.buf);
  Py_ssize_t total = src.len;
  Py_BEGIN_ALLOW_THREADS;
  fast_copy(d, s, static_cast<size_t>(total));
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&dst);
  PyBuffer_Release(&src);
  Py_RETURN_NONE;
}

// parallel_copy(dst, src[, nthreads]): multithreaded memcpy with the GIL
// released. Large-object puts hit memory bandwidth instead of a single
// core's memcpy throughput.
static PyObject* shm_parallel_copy(PyObject*, PyObject* args) {
  Py_buffer dst, src;
  int nthreads = 4;
  if (!PyArg_ParseTuple(args, "w*y*|i", &dst, &src, &nthreads)) return nullptr;
  if (src.len > dst.len) {
    PyBuffer_Release(&dst);
    PyBuffer_Release(&src);
    PyErr_SetString(ShmError, "parallel_copy: source larger than destination");
    return nullptr;
  }
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  char* d = static_cast<char*>(dst.buf);
  const char* s = static_cast<const char*>(src.buf);
  Py_ssize_t total = src.len;
  Py_BEGIN_ALLOW_THREADS;
  if (total < (4 << 20) || nthreads == 1) {
    fast_copy(d, s, static_cast<size_t>(total));
  } else {
    Py_ssize_t chunk = (total / nthreads + 63) & ~static_cast<Py_ssize_t>(63);
    std::vector<std::thread> threads;
    for (int t = 0; t < nthreads; t++) {
      Py_ssize_t lo = t * chunk;
      if (lo >= total) break;
      Py_ssize_t hi = lo + chunk < total ? lo + chunk : total;
      threads.emplace_back([d, s, lo, hi]() {
        fast_copy(d + lo, s + lo, static_cast<size_t>(hi - lo));
      });
    }
    for (auto& th : threads) th.join();
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&dst);
  PyBuffer_Release(&src);
  Py_RETURN_NONE;
}

static PyMethodDef module_methods[] = {
    {"create", shm_create, METH_VARARGS, "create(name, size) -> ShmBuffer (rw)"},
    {"open_ro", shm_open_ro, METH_VARARGS, "open_ro(name) -> ShmBuffer"},
    {"open_rw", shm_open_rw, METH_VARARGS, "open_rw(name) -> ShmBuffer"},
    {"unlink", shm_unlink_py, METH_VARARGS, "unlink(name)"},
    {"prefault", shm_prefault, METH_VARARGS,
     "prefault(buffer[, nthreads]) — touch every page (multithreaded, no GIL)"},
    {"parallel_copy", shm_parallel_copy, METH_VARARGS,
     "parallel_copy(dst, src[, nthreads]) — multithreaded memcpy (no GIL)"},
    {"copy_nt", shm_copy_nt, METH_VARARGS,
     "copy_nt(dst, src) — single-threaded non-temporal copy (no GIL)"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef shm_module = {
    PyModuleDef_HEAD_INIT, "_shm",
    "POSIX shared memory segments with buffer protocol (plasma-lite).",
    -1, module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__shm(void) {
  ShmBufferType.tp_dealloc = reinterpret_cast<destructor>(ShmBuffer_dealloc);
  ShmBufferType.tp_flags = Py_TPFLAGS_DEFAULT;
  ShmBufferType.tp_doc = "A mapped POSIX shared-memory segment.";
  ShmBufferType.tp_as_buffer = &ShmBuffer_as_buffer;
  ShmBufferType.tp_methods = ShmBuffer_methods;
  ShmBufferType.tp_getset = ShmBuffer_getset;
  ShmBufferType.tp_new = nullptr;  // not constructible from Python
  if (PyType_Ready(&ShmBufferType) < 0) return nullptr;

  PyObject* m = PyModule_Create(&shm_module);
  if (m == nullptr) return nullptr;
  ShmError = PyErr_NewException("ray_tpu._native._shm.ShmError", nullptr, nullptr);
  Py_INCREF(ShmError);
  PyModule_AddObject(m, "ShmError", ShmError);
  Py_INCREF(&ShmBufferType);
  PyModule_AddObject(m, "ShmBuffer", reinterpret_cast<PyObject*>(&ShmBufferType));
  return m;
}
