// ray_tpu._native._store — plasma-style object-store core: best-fit arena
// allocator with coalescing free lists, object table, and LRU eviction.
//
// TPU-native analog of the reference's plasma store internals
// (src/ray/object_manager/plasma/{plasma_allocator.cc,object_lifecycle_manager.cc,
// eviction_policy.cc}): the reference subdivides one big mmap with dlmalloc and
// tracks object lifecycle + LRU eviction in the store process. Here the same
// three concerns live in this extension, owned by the raylet: the arena
// itself is a single POSIX shm segment (mapped via ray_tpu._native._shm);
// this module only does the bookkeeping — allocation offsets, seal/pin
// state, LRU ordering — so the Python fallback can implement the identical
// interface.
//
// Exposed API (class StoreCore):
//   StoreCore(capacity)
//   alloc(oid, size, pin) -> offset            (-1 if it doesn't fit)
//   seal(oid) / is_sealed(oid)
//   touch(oid)                                  (LRU bump, on every access)
//   pin(oid) / unpin(oid)
//   free(oid) -> size                           (0 if absent)
//   evict(nbytes, grace_ticks) -> [oid, ...]    (frees sealed+unpinned LRU
//                                                victims not touched within
//                                                the last grace_ticks touches)
//   lookup(oid) -> (offset, size, sealed, pinned) | None
//   used / capacity / num_objects / fragmentation()

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct ObjEntry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool pinned = false;
  uint64_t lru_tick = 0;
};

// Best-fit allocator over [0, capacity) with O(log n) alloc/free and
// neighbor coalescing. Two indexes over the same free spans:
//   by_offset: offset -> size      (coalescing)
//   by_size:   (size, offset)      (best-fit lookup)
class Allocator {
 public:
  explicit Allocator(uint64_t capacity) : capacity_(capacity) {
    by_offset_[0] = capacity;
    by_size_.insert({capacity, 0});
  }

  static uint64_t Round(uint64_t size) {
    // Round to 64B so neighboring objects never share a cache line.
    if (size == 0) size = 1;
    return (size + 63) & ~uint64_t(63);
  }

  int64_t Alloc(uint64_t size) {
    size = Round(size);
    auto it = by_size_.lower_bound({size, 0});
    if (it == by_size_.end()) return -1;
    uint64_t span_size = it->first, span_off = it->second;
    by_size_.erase(it);
    by_offset_.erase(span_off);
    if (span_size > size) {
      uint64_t rest_off = span_off + size;
      uint64_t rest_size = span_size - size;
      by_offset_[rest_off] = rest_size;
      by_size_.insert({rest_size, rest_off});
    }
    return static_cast<int64_t>(span_off);
  }

  void Free(uint64_t offset, uint64_t size) {
    size = Round(size);
    // Coalesce with successor.
    auto next = by_offset_.lower_bound(offset);
    if (next != by_offset_.end() && next->first == offset + size) {
      size += next->second;
      by_size_.erase({next->second, next->first});
      by_offset_.erase(next);
    }
    // Coalesce with predecessor.
    auto prev = by_offset_.lower_bound(offset);
    if (prev != by_offset_.begin()) {
      --prev;
      if (prev->first + prev->second == offset) {
        by_size_.erase({prev->second, prev->first});
        offset = prev->first;
        size += prev->second;
        by_offset_.erase(prev);
      }
    }
    by_offset_[offset] = size;
    by_size_.insert({size, offset});
  }

  uint64_t LargestFree() const {
    return by_size_.empty() ? 0 : by_size_.rbegin()->first;
  }

  size_t NumSpans() const { return by_offset_.size(); }

 private:
  uint64_t capacity_;
  std::map<uint64_t, uint64_t> by_offset_;      // offset -> size (free spans)
  std::set<std::pair<uint64_t, uint64_t>> by_size_;  // (size, offset)
};

struct StoreCoreObject {
  PyObject_HEAD
  Allocator* alloc;
  std::unordered_map<std::string, ObjEntry>* objects;
  std::map<uint64_t, std::string>* lru;  // tick -> oid
  uint64_t capacity;
  uint64_t used;
  uint64_t tick;
};

static void StoreCore_dealloc(StoreCoreObject* self) {
  delete self->alloc;
  delete self->objects;
  delete self->lru;
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

static PyObject* StoreCore_new(PyTypeObject* type, PyObject* args, PyObject* kwds) {
  StoreCoreObject* self =
      reinterpret_cast<StoreCoreObject*>(type->tp_alloc(type, 0));
  if (self != nullptr) {
    self->alloc = nullptr;
    self->objects = nullptr;
    self->lru = nullptr;
    self->capacity = 0;
    self->used = 0;
    self->tick = 0;
  }
  return reinterpret_cast<PyObject*>(self);
}

static int StoreCore_init(StoreCoreObject* self, PyObject* args, PyObject* kwds) {
  static const char* kwlist[] = {"capacity", nullptr};
  unsigned long long capacity = 0;
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "K",
                                   const_cast<char**>(kwlist), &capacity)) {
    return -1;
  }
  self->capacity = capacity;
  self->alloc = new Allocator(capacity);
  self->objects = new std::unordered_map<std::string, ObjEntry>();
  self->lru = new std::map<uint64_t, std::string>();
  return 0;
}

static ObjEntry* FindEntry(StoreCoreObject* self, const char* oid) {
  auto it = self->objects->find(oid);
  return it == self->objects->end() ? nullptr : &it->second;
}

// lru maps tick -> oid, so touching needs the oid string.
static void TouchEntryNamed(StoreCoreObject* self, const std::string& oid,
                            ObjEntry* e) {
  self->lru->erase(e->lru_tick);
  e->lru_tick = ++self->tick;
  (*self->lru)[e->lru_tick] = oid;
}

static PyObject* StoreCore_alloc(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  unsigned long long size;
  int pin = 1;
  if (!PyArg_ParseTuple(args, "sK|p", &oid, &size, &pin)) return nullptr;
  if (FindEntry(self, oid) != nullptr) {
    PyErr_Format(PyExc_KeyError, "object %s already allocated", oid);
    return nullptr;
  }
  int64_t off = self->alloc->Alloc(size);
  if (off < 0) return PyLong_FromLong(-1);
  ObjEntry e;
  e.offset = static_cast<uint64_t>(off);
  e.size = size;
  e.pinned = pin != 0;
  (*self->objects)[oid] = e;
  TouchEntryNamed(self, oid, &(*self->objects)[oid]);
  self->used += size;
  return PyLong_FromLongLong(off);
}

static PyObject* StoreCore_seal(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  ObjEntry* e = FindEntry(self, oid);
  if (e == nullptr) {
    PyErr_Format(PyExc_KeyError, "unknown object %s", oid);
    return nullptr;
  }
  e->sealed = true;
  TouchEntryNamed(self, oid, e);
  Py_RETURN_NONE;
}

static PyObject* StoreCore_touch(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  ObjEntry* e = FindEntry(self, oid);
  if (e != nullptr) TouchEntryNamed(self, oid, e);
  Py_RETURN_NONE;
}

static PyObject* SetPin(StoreCoreObject* self, PyObject* args, bool pinned) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  ObjEntry* e = FindEntry(self, oid);
  if (e != nullptr) e->pinned = pinned;
  Py_RETURN_NONE;
}

static PyObject* StoreCore_pin(StoreCoreObject* self, PyObject* args) {
  return SetPin(self, args, true);
}

static PyObject* StoreCore_unpin(StoreCoreObject* self, PyObject* args) {
  return SetPin(self, args, false);
}

static PyObject* StoreCore_free(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  auto it = self->objects->find(oid);
  if (it == self->objects->end()) return PyLong_FromLong(0);
  ObjEntry& e = it->second;
  self->alloc->Free(e.offset, e.size);
  self->used -= e.size;
  self->lru->erase(e.lru_tick);
  uint64_t size = e.size;
  self->objects->erase(it);
  return PyLong_FromUnsignedLongLong(size);
}

static PyObject* StoreCore_evict(StoreCoreObject* self, PyObject* args) {
  unsigned long long nbytes;
  unsigned long long grace_ticks = 0;
  if (!PyArg_ParseTuple(args, "K|K", &nbytes, &grace_ticks)) return nullptr;
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  uint64_t freed = 0;
  uint64_t min_tick_protected =
      grace_ticks >= self->tick ? 0 : self->tick - grace_ticks;
  auto it = self->lru->begin();
  while (it != self->lru->end() && freed < nbytes) {
    if (grace_ticks > 0 && it->first > min_tick_protected) break;
    const std::string oid = it->second;
    auto oit = self->objects->find(oid);
    if (oit == self->objects->end()) {
      it = self->lru->erase(it);
      continue;
    }
    ObjEntry& e = oit->second;
    if (!e.sealed || e.pinned) {
      ++it;
      continue;
    }
    self->alloc->Free(e.offset, e.size);
    self->used -= e.size;
    freed += e.size;
    it = self->lru->erase(it);
    self->objects->erase(oit);
    PyObject* name = PyUnicode_FromString(oid.c_str());
    PyList_Append(out, name);
    Py_DECREF(name);
  }
  return out;
}

static PyObject* StoreCore_lookup(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  ObjEntry* e = FindEntry(self, oid);
  if (e == nullptr) Py_RETURN_NONE;
  return Py_BuildValue("(KKOO)", e->offset, e->size,
                       e->sealed ? Py_True : Py_False,
                       e->pinned ? Py_True : Py_False);
}

static PyObject* StoreCore_contains(StoreCoreObject* self, PyObject* args) {
  const char* oid;
  if (!PyArg_ParseTuple(args, "s", &oid)) return nullptr;
  ObjEntry* e = FindEntry(self, oid);
  if (e != nullptr && e->sealed) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

static PyObject* StoreCore_fragmentation(StoreCoreObject* self, PyObject*) {
  uint64_t free_total = self->capacity - self->used;
  uint64_t largest = self->alloc->LargestFree();
  double frag = free_total == 0
                    ? 0.0
                    : 1.0 - static_cast<double>(largest) /
                                static_cast<double>(free_total);
  return Py_BuildValue("(dKn)", frag, largest,
                       static_cast<Py_ssize_t>(self->alloc->NumSpans()));
}

static PyObject* StoreCore_get_used(StoreCoreObject* self, void*) {
  return PyLong_FromUnsignedLongLong(self->used);
}

static PyObject* StoreCore_get_capacity(StoreCoreObject* self, void*) {
  return PyLong_FromUnsignedLongLong(self->capacity);
}

static PyObject* StoreCore_get_num_objects(StoreCoreObject* self, void*) {
  return PyLong_FromSize_t(self->objects->size());
}

static PyMethodDef StoreCore_methods[] = {
    {"alloc", reinterpret_cast<PyCFunction>(StoreCore_alloc), METH_VARARGS,
     "alloc(oid, size, pin=True) -> offset or -1"},
    {"seal", reinterpret_cast<PyCFunction>(StoreCore_seal), METH_VARARGS, ""},
    {"touch", reinterpret_cast<PyCFunction>(StoreCore_touch), METH_VARARGS, ""},
    {"pin", reinterpret_cast<PyCFunction>(StoreCore_pin), METH_VARARGS, ""},
    {"unpin", reinterpret_cast<PyCFunction>(StoreCore_unpin), METH_VARARGS, ""},
    {"free", reinterpret_cast<PyCFunction>(StoreCore_free), METH_VARARGS,
     "free(oid) -> size"},
    {"evict", reinterpret_cast<PyCFunction>(StoreCore_evict), METH_VARARGS,
     "evict(nbytes, grace_ticks=0) -> [oid]"},
    {"lookup", reinterpret_cast<PyCFunction>(StoreCore_lookup), METH_VARARGS,
     "lookup(oid) -> (offset, size, sealed, pinned) | None"},
    {"contains", reinterpret_cast<PyCFunction>(StoreCore_contains), METH_VARARGS,
     "contains(oid) -> sealed?"},
    {"fragmentation", reinterpret_cast<PyCFunction>(StoreCore_fragmentation),
     METH_NOARGS, "() -> (frag_ratio, largest_free, num_spans)"},
    {nullptr, nullptr, 0, nullptr}};

static PyGetSetDef StoreCore_getset[] = {
    {"used", reinterpret_cast<getter>(StoreCore_get_used), nullptr, "", nullptr},
    {"capacity", reinterpret_cast<getter>(StoreCore_get_capacity), nullptr, "",
     nullptr},
    {"num_objects", reinterpret_cast<getter>(StoreCore_get_num_objects), nullptr,
     "", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr}};

static PyTypeObject StoreCoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "ray_tpu._native._store.StoreCore",     /* tp_name */
    sizeof(StoreCoreObject),                /* tp_basicsize */
};

static PyModuleDef store_module = {
    PyModuleDef_HEAD_INIT, "ray_tpu._native._store",
    "plasma-style object store core (allocator + lifecycle + LRU)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__store(void) {
  StoreCoreType.tp_dealloc = reinterpret_cast<destructor>(StoreCore_dealloc);
  StoreCoreType.tp_flags = Py_TPFLAGS_DEFAULT;
  StoreCoreType.tp_doc = "object store bookkeeping core";
  StoreCoreType.tp_methods = StoreCore_methods;
  StoreCoreType.tp_getset = StoreCore_getset;
  StoreCoreType.tp_init = reinterpret_cast<initproc>(StoreCore_init);
  StoreCoreType.tp_new = StoreCore_new;
  if (PyType_Ready(&StoreCoreType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&store_module);
  if (m == nullptr) return nullptr;
  Py_INCREF(&StoreCoreType);
  PyModule_AddObject(m, "StoreCore",
                     reinterpret_cast<PyObject*>(&StoreCoreType));
  return m;
}
