// ray_tpu._native._fastpath — native direct-call task channel.
//
// TPU-native analog of the reference's C++ direct task transport
// (src/ray/core_worker/transport/direct_task_transport.h:75 submit side,
// src/ray/core_worker/core_worker.cc:2146 SubmitTask, and the worker-side
// PushTask handling in core_worker.proto:446): once a worker lease is held,
// eligible tasks bypass the Python asyncio/msgpack RPC stack entirely and
// ride a dedicated socket owned by this extension.
//
//   driver role: client_connect() opens a channel to a worker's fastpath
//     port. submit() frames the task and hands it to one global IO thread
//     (corked writev batching). Replies are parsed off-thread into a
//     completion list; a self-pipe byte wakes the driver's event loop,
//     which drains completions in one batch (drain()).
//   worker role: serve() runs an accept loop; each connection gets a
//     thread that reads a task frame, takes the GIL, invokes the Python
//     exec callback (function lookup + arg deserialization + user code +
//     result serialization stay in Python), and writes the reply frame.
//     Execution is serialized per connection — the same semantics as the
//     worker's sync exec thread.
//
// Frame format (little-endian):
//   [u32 frame_len] [u8 type] [u8 tid_len] [tid]
//     type 1 (task):  [u16 fid_len][fid][u16 name_len][name][args_blob]
//     type 10+status (reply): [payload]
// Completion statuses surfaced by drain(): 0 ok, 1 application error
// (payload = serialized error), 2 lost (channel died; caller resubmits
// through the normal path); the Python layers define further statuses
// (4 function-not-cached, 6 large-result-in-plasma) that ride the same
// 10+status reply encoding.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------- utils

int SetNoDelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void AppendU32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}
void AppendU16(std::string* out, uint16_t v) {
  out->append(reinterpret_cast<const char*>(&v), 2);
}

bool WriteAll(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::send(fd, data, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Buffered frame reader: large recv()s, frames parsed from the buffer —
// one syscall amortizes across many pipelined frames instead of two
// syscalls (header + body) per frame.
struct FrameReader {
  explicit FrameReader(int fd) : fd(fd) {}

  bool FillTo(size_t need) {
    while (buf.size() - pos < need) {
      char tmp[65536];
      ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        return false;
      }
      if (pos > (1u << 20)) {
        buf.erase(0, pos);
        pos = 0;
      }
      buf.append(tmp, static_cast<size_t>(n));
    }
    return true;
  }

  bool ReadFrame(std::string* body) {
    if (!FillTo(4)) return false;
    uint32_t len;
    std::memcpy(&len, buf.data() + pos, 4);
    if (len < 2 || len > (64u << 20)) return false;
    if (!FillTo(4 + static_cast<size_t>(len))) return false;
    body->assign(buf, pos + 4, len);
    pos += 4 + static_cast<size_t>(len);
    if (pos == buf.size()) {
      buf.clear();
      pos = 0;
    }
    return true;
  }

  // A complete frame already sits in the buffer (no syscall needed).
  bool HasBufferedFrame() const {
    if (buf.size() - pos < 4) return false;
    uint32_t len;
    std::memcpy(&len, buf.data() + pos, 4);
    return buf.size() - pos >= 4 + static_cast<size_t>(len);
  }

  int fd;
  std::string buf;
  size_t pos = 0;
};

std::string BuildTaskFrame(const std::string& tid, const std::string& fid,
                           const std::string& name, const char* args,
                           size_t args_len) {
  std::string body;
  body.reserve(1 + 1 + tid.size() + 2 + fid.size() + 2 + name.size() + args_len);
  body.push_back(static_cast<char>(1));
  body.push_back(static_cast<char>(tid.size()));
  body.append(tid);
  AppendU16(&body, static_cast<uint16_t>(fid.size()));
  body.append(fid);
  AppendU16(&body, static_cast<uint16_t>(name.size()));
  body.append(name);
  body.append(args, args_len);
  std::string frame;
  frame.reserve(4 + body.size());
  AppendU32(&frame, static_cast<uint32_t>(body.size()));
  frame.append(body);
  return frame;
}

// ---------------------------------------------------------------- driver

struct Completion {
  std::string tid;
  int status;  // 0 ok, 1 error, 2 lost
  std::string payload;
};

struct Channel {
  int id;
  int fd;
  std::thread reader;
  std::mutex mu;  // guards pending + closed
  std::unordered_set<std::string> pending;  // tids in flight
  bool closed = false;
};

class Driver {
 public:
  Driver() {
    int p[2];
    (void)!pipe(p);
    notify_rd_ = p[0];
    notify_wr_ = p[1];
    // Drain() is callable at any time (not just after a readable event):
    // an empty pipe must return 0, not block the caller.
    int flags = fcntl(notify_rd_, F_GETFL, 0);
    fcntl(notify_rd_, F_SETFL, flags | O_NONBLOCK);
  }

  int Connect(const char* host, int port) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      return -1;
    }
    SetNoDelay(fd);
    auto ch = std::make_shared<Channel>();
    int id;
    {
      std::lock_guard<std::mutex> lk(mu_);
      id = next_id_++;
      ch->id = id;
      ch->fd = fd;
      channels_[id] = ch;
    }
    ch->reader = std::thread([this, ch] { ReadLoop(ch); });
    return id;
  }

  // Direct synchronous write from the submitting (GIL-holding Python)
  // thread. On the single-core hosts this framework targets for its
  // control plane, a dedicated IO thread only adds context switches: the
  // send() of a ~250B frame into the kernel buffer costs ~1-2us and never
  // meaningfully blocks at the pipeline depths the lease pool allows. The
  // GIL itself serializes submitters, so writes need no ordering lock.
  bool Submit(int channel_id, std::string tid, const std::string& frame) {
    std::shared_ptr<Channel> ch = Find(channel_id);
    if (!ch) return false;
    {
      std::lock_guard<std::mutex> lk(ch->mu);
      if (ch->closed) return false;
      ch->pending.insert(std::move(tid));
    }
    if (!WriteAll(ch->fd, frame.data(), frame.size())) {
      FailChannel(ch);
      return false;
    }
    return true;
  }

  void Close(int channel_id) {
    std::shared_ptr<Channel> ch;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = channels_.find(channel_id);
      if (it == channels_.end()) return;
      ch = it->second;
      channels_.erase(it);
    }
    ShutdownChannel(ch);
    if (ch->reader.joinable()) ch->reader.join();
  }

  std::vector<Completion> Drain() {
    // Clear the notify pipe first, then swap the list: a notifier racing in
    // after the swap re-signals, so no completion waits indefinitely.
    char buf[256];
    while (::read(notify_rd_, buf, sizeof(buf)) == sizeof(buf)) {
    }
    std::vector<Completion> out;
    {
      std::lock_guard<std::mutex> lk(done_mu_);
      out.swap(done_);
    }
    return out;
  }

  int notify_fd() const { return notify_rd_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    std::vector<std::shared_ptr<Channel>> chans;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& kv : channels_) chans.push_back(kv.second);
      channels_.clear();
    }
    for (auto& ch : chans) {
      ShutdownChannel(ch);
      if (ch->reader.joinable()) ch->reader.join();
    }
  }

 private:
  std::shared_ptr<Channel> Find(int id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = channels_.find(id);
    return it == channels_.end() ? nullptr : it->second;
  }

  void ShutdownChannel(const std::shared_ptr<Channel>& ch) {
    {
      std::lock_guard<std::mutex> lk(ch->mu);
      if (ch->closed) return;
      ch->closed = true;
    }
    ::shutdown(ch->fd, SHUT_RDWR);
  }

  void Notify() {
    char b = 1;
    (void)!::write(notify_wr_, &b, 1);
  }

  void Complete(Completion c) {
    bool was_empty;
    {
      std::lock_guard<std::mutex> lk(done_mu_);
      was_empty = done_.empty();
      done_.push_back(std::move(c));
    }
    if (was_empty) Notify();
  }

  // Per-channel reply reader.
  void ReadLoop(std::shared_ptr<Channel> ch) {
    FrameReader reader(ch->fd);
    std::string body;
    for (;;) {
      if (!reader.ReadFrame(&body)) break;
      uint8_t type = static_cast<uint8_t>(body[0]);
      uint8_t tid_len = static_cast<uint8_t>(body[1]);
      if (static_cast<size_t>(2 + tid_len) > body.size()) break;
      std::string tid = body.substr(2, tid_len);
      {
        std::lock_guard<std::mutex> lk(ch->mu);
        ch->pending.erase(tid);
      }
      // Reply type is 10 + status (status 2 is reserved for channel loss,
      // reported locally by FailChannel, never by the peer).
      int status = type >= 10 ? type - 10 : 1;
      Complete({std::move(tid), status, body.substr(2 + tid_len)});
    }
    FailChannel(ch);
  }

  void FailChannel(const std::shared_ptr<Channel>& ch) {
    std::unordered_set<std::string> orphans;
    {
      std::lock_guard<std::mutex> lk(ch->mu);
      if (ch->closed && ch->pending.empty()) return;
      ch->closed = true;
      orphans.swap(ch->pending);
    }
    ::shutdown(ch->fd, SHUT_RDWR);
    for (auto& tid : orphans) Complete({tid, 2, std::string()});
  }

  std::mutex mu_;
  std::unordered_map<int, std::shared_ptr<Channel>> channels_;
  int next_id_ = 1;
  std::mutex done_mu_;
  std::vector<Completion> done_;
  int notify_rd_ = -1, notify_wr_ = -1;
  std::atomic<bool> stopping_{false};
};

Driver* g_driver = nullptr;
std::mutex g_driver_mu;

Driver* GetDriver() {
  std::lock_guard<std::mutex> lk(g_driver_mu);
  if (g_driver == nullptr) g_driver = new Driver();
  return g_driver;
}

// ---------------------------------------------------------------- server

struct Server {
  int id;
  int listen_fd;
  PyObject* callback;  // owned
  std::thread accept_thread;
  std::mutex mu;
  std::vector<std::thread> conn_threads;
  std::atomic<bool> stopping{false};
};

std::mutex g_servers_mu;
std::unordered_map<int, std::shared_ptr<Server>> g_servers;
int g_next_server_id = 1;

// Execute one parsed task frame under an already-held GIL; appends the
// reply frame to `replies`. Returns false on a malformed frame.
bool ExecOneTask(const std::shared_ptr<Server>& srv, const std::string& body,
                 std::string* replies) {
  // Every length is validated against the remaining body before it is
  // read: a truncated/corrupt frame must drop the connection, not read
  // out of bounds or throw through the thread entry.
  uint8_t type = static_cast<uint8_t>(body[0]);
  uint8_t tid_len = static_cast<uint8_t>(body[1]);
  size_t off = 2;
  if (type != 1 || off + tid_len + 2 > body.size()) return false;
  std::string tid = body.substr(off, tid_len);
  off += tid_len;
  uint16_t fid_len;
  std::memcpy(&fid_len, body.data() + off, 2);
  off += 2;
  if (off + fid_len + 2 > body.size()) return false;
  std::string fid = body.substr(off, fid_len);
  off += fid_len;
  uint16_t name_len;
  std::memcpy(&name_len, body.data() + off, 2);
  off += 2;
  if (off + name_len > body.size()) return false;
  std::string name = body.substr(off, name_len);
  off += name_len;

  int status = 1;
  std::string payload;
  PyObject* res = PyObject_CallFunction(
      srv->callback, "y#y#y#y#", tid.data(), (Py_ssize_t)tid.size(),
      fid.data(), (Py_ssize_t)fid.size(), name.data(),
      (Py_ssize_t)name.size(), body.data() + off,
      (Py_ssize_t)(body.size() - off));
  if (res != nullptr && PyTuple_Check(res) && PyTuple_GET_SIZE(res) == 2) {
    PyObject* st = PyTuple_GET_ITEM(res, 0);
    PyObject* pl = PyTuple_GET_ITEM(res, 1);
    char* data = nullptr;
    Py_ssize_t dlen = 0;
    if (PyLong_Check(st) && PyBytes_AsStringAndSize(pl, &data, &dlen) == 0) {
      status = static_cast<int>(PyLong_AsLong(st));
      payload.assign(data, static_cast<size_t>(dlen));
    }
  }
  if (res == nullptr) PyErr_Clear();
  Py_XDECREF(res);

  if (status < 0 || status > 200) status = 1;
  std::string reply_body;
  reply_body.reserve(2 + tid.size() + payload.size());
  reply_body.push_back(static_cast<char>(10 + status));
  reply_body.push_back(static_cast<char>(tid.size()));
  reply_body.append(tid);
  reply_body.append(payload);
  AppendU32(replies, static_cast<uint32_t>(reply_body.size()));
  replies->append(reply_body);
  return true;
}

void ServeConn(std::shared_ptr<Server> srv, int fd) {
  SetNoDelay(fd);
  FrameReader reader(fd);
  std::string body;
  std::string replies;
  // Adaptive corking, mirroring what the asyncio RPC path gets from its
  // transport: while more task frames are already buffered, keep executing
  // under ONE GIL hold and accumulate replies; flush with ONE send when the
  // input drains (or a batch cap hits, to bound reply latency). Per-task
  // context switches collapse to ~2 per batch.
  constexpr int kMaxBatch = 64;
  for (;;) {
    if (!reader.ReadFrame(&body)) break;
    if (srv->stopping.load() || !Py_IsInitialized()) break;
    replies.clear();
    bool ok = true;
    PyGILState_STATE gil = PyGILState_Ensure();
    int batch = 0;
    for (;;) {
      if (!ExecOneTask(srv, body, &replies)) {
        ok = false;
        break;
      }
      if (++batch >= kMaxBatch || !reader.HasBufferedFrame()) break;
      if (!reader.ReadFrame(&body)) {
        ok = false;
        break;
      }
    }
    PyGILState_Release(gil);
    if (!replies.empty() && !WriteAll(fd, replies.data(), replies.size()))
      break;
    if (!ok) break;
  }
  ::close(fd);
}

void AcceptLoop(std::shared_ptr<Server> srv) {
  for (;;) {
    int fd = ::accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listen socket closed (stop)
    }
    if (srv->stopping.load()) {
      ::close(fd);
      break;
    }
    std::lock_guard<std::mutex> lk(srv->mu);
    srv->conn_threads.emplace_back(
        [srv, fd] { ServeConn(srv, fd); });
  }
}

// ------------------------------------------------------------- wire codec
//
// C-level pack/unpack for the asyncio RPC stack's msgpack frames
// (ray_tpu/_private/rpc.py). Byte-identical to
// msgpack.Packer(use_bin_type=True) / msgpack.Unpacker(raw=False,
// strict_map_key=False): the Python side fuzzes parity in both directions
// (tests/test_fastpath_native.py), so any divergence is a test failure,
// not a silent wire fork. Registered per-schema: rpc.py consults
// schema_versions() against wire.NATIVE_WIRE_SCHEMAS and only routes a
// method here while the versions match.
//
// The schema markers below are parsed by devtools/rpc_check.py
// (wire-native-drift): editing a natively-packed schema's field list in
// wire.py without bumping BOTH the version there and the marker (and
// table) here fails lint.
//
// NATIVE_WIRE_SCHEMA: RequestWorkerLease v1 fields=bundle_index,job_id,lease_id,locality,pg_id,resources,spilled_from,strategy
// NATIVE_WIRE_SCHEMA: ReturnWorker v1 fields=dirty,lease_id
// NATIVE_WIRE_SCHEMA: CancelWorkerLease v1 fields=lease_id
// NATIVE_WIRE_SCHEMA: LeaseBatch v1 fields=entries
// NATIVE_WIRE_SCHEMA: PubBatch v1 fields=items

struct WireSchema {
  const char* method;
  int version;
};
constexpr WireSchema kWireSchemas[] = {
    {"RequestWorkerLease", 1}, {"ReturnWorker", 1}, {"CancelWorkerLease", 1},
    {"LeaseBatch", 1},         {"PubBatch", 1},
};

constexpr size_t kMaxWireFrame = 64u << 20;  // mirrors rpc._MAX_FRAME

// -- encoder --

void PutBE16(std::string* out, uint16_t v) {
  char b[2] = {static_cast<char>(v >> 8), static_cast<char>(v)};
  out->append(b, 2);
}
void PutBE32(std::string* out, uint32_t v) {
  char b[4] = {static_cast<char>(v >> 24), static_cast<char>(v >> 16),
               static_cast<char>(v >> 8), static_cast<char>(v)};
  out->append(b, 4);
}
void PutBE64(std::string* out, uint64_t v) {
  char b[8];
  for (int i = 0; i < 8; ++i) b[i] = static_cast<char>(v >> (56 - 8 * i));
  out->append(b, 8);
}

// Packs one Python object; byte-for-byte what msgpack-python's C packer
// emits for the same value. Returns false with a Python error set on
// unsupported types (caller falls back to the Python packer).
bool PackObj(std::string* out, PyObject* o, int depth) {
  if (depth > 128) {
    PyErr_SetString(PyExc_ValueError, "pack_frame: nesting too deep");
    return false;
  }
  if (o == Py_None) {
    out->push_back(static_cast<char>(0xc0));
    return true;
  }
  // bool before int: Python bool subclasses int.
  if (PyBool_Check(o)) {
    out->push_back(static_cast<char>(o == Py_True ? 0xc3 : 0xc2));
    return true;
  }
  if (PyLong_Check(o)) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(o, &overflow);
    if (overflow > 0) {
      unsigned long long u = PyLong_AsUnsignedLongLong(o);
      if (u == static_cast<unsigned long long>(-1) && PyErr_Occurred())
        return false;  // > 2**64-1: OverflowError, like msgpack
      out->push_back(static_cast<char>(0xcf));
      PutBE64(out, u);
      return true;
    }
    if (overflow < 0) {
      PyErr_SetString(PyExc_OverflowError, "int too small for msgpack");
      return false;
    }
    if (v == -1 && PyErr_Occurred()) return false;
    if (v >= 0) {
      if (v < 0x80) {
        out->push_back(static_cast<char>(v));
      } else if (v < 0x100) {
        out->push_back(static_cast<char>(0xcc));
        out->push_back(static_cast<char>(v));
      } else if (v < 0x10000) {
        out->push_back(static_cast<char>(0xcd));
        PutBE16(out, static_cast<uint16_t>(v));
      } else if (v < 0x100000000LL) {
        out->push_back(static_cast<char>(0xce));
        PutBE32(out, static_cast<uint32_t>(v));
      } else {
        out->push_back(static_cast<char>(0xcf));
        PutBE64(out, static_cast<uint64_t>(v));
      }
    } else {
      if (v >= -32) {
        out->push_back(static_cast<char>(0xe0 | (v & 0x1f)));
      } else if (v >= -128) {
        out->push_back(static_cast<char>(0xd0));
        out->push_back(static_cast<char>(v));
      } else if (v >= -32768) {
        out->push_back(static_cast<char>(0xd1));
        PutBE16(out, static_cast<uint16_t>(v));
      } else if (v >= -2147483648LL) {
        out->push_back(static_cast<char>(0xd2));
        PutBE32(out, static_cast<uint32_t>(v));
      } else {
        out->push_back(static_cast<char>(0xd3));
        PutBE64(out, static_cast<uint64_t>(v));
      }
    }
    return true;
  }
  if (PyFloat_Check(o)) {
    double d = PyFloat_AS_DOUBLE(o);
    uint64_t bits;
    std::memcpy(&bits, &d, 8);
    out->push_back(static_cast<char>(0xcb));
    PutBE64(out, bits);
    return true;
  }
  if (PyUnicode_Check(o)) {
    Py_ssize_t len;
    const char* s = PyUnicode_AsUTF8AndSize(o, &len);
    if (s == nullptr) return false;
    size_t n = static_cast<size_t>(len);
    if (n < 32) {
      out->push_back(static_cast<char>(0xa0 | n));
    } else if (n < 0x100) {
      out->push_back(static_cast<char>(0xd9));
      out->push_back(static_cast<char>(n));
    } else if (n < 0x10000) {
      out->push_back(static_cast<char>(0xda));
      PutBE16(out, static_cast<uint16_t>(n));
    } else {
      out->push_back(static_cast<char>(0xdb));
      PutBE32(out, static_cast<uint32_t>(n));
    }
    out->append(s, n);
    return true;
  }
  if (PyBytes_Check(o) || PyByteArray_Check(o) || PyMemoryView_Check(o)) {
    Py_buffer view;
    if (PyObject_GetBuffer(o, &view, PyBUF_CONTIG_RO) != 0) return false;
    size_t n = static_cast<size_t>(view.len);
    if (n < 0x100) {
      out->push_back(static_cast<char>(0xc4));
      out->push_back(static_cast<char>(n));
    } else if (n < 0x10000) {
      out->push_back(static_cast<char>(0xc5));
      PutBE16(out, static_cast<uint16_t>(n));
    } else {
      out->push_back(static_cast<char>(0xc6));
      PutBE32(out, static_cast<uint32_t>(n));
    }
    out->append(static_cast<const char*>(view.buf), n);
    PyBuffer_Release(&view);
    return true;
  }
  if (PyList_Check(o) || PyTuple_Check(o)) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(o);
    if (n < 16) {
      out->push_back(static_cast<char>(0x90 | n));
    } else if (n < 0x10000) {
      out->push_back(static_cast<char>(0xdc));
      PutBE16(out, static_cast<uint16_t>(n));
    } else {
      out->push_back(static_cast<char>(0xdd));
      PutBE32(out, static_cast<uint32_t>(n));
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* item = PyList_Check(o) ? PyList_GET_ITEM(o, i)
                                       : PyTuple_GET_ITEM(o, i);
      if (!PackObj(out, item, depth + 1)) return false;
    }
    return true;
  }
  if (PyDict_Check(o)) {
    Py_ssize_t n = PyDict_Size(o);
    if (n < 16) {
      out->push_back(static_cast<char>(0x80 | n));
    } else if (n < 0x10000) {
      out->push_back(static_cast<char>(0xde));
      PutBE16(out, static_cast<uint16_t>(n));
    } else {
      out->push_back(static_cast<char>(0xdf));
      PutBE32(out, static_cast<uint32_t>(n));
    }
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(o, &pos, &key, &value)) {
      if (!PackObj(out, key, depth + 1)) return false;
      if (!PackObj(out, value, depth + 1)) return false;
    }
    return true;
  }
  PyErr_Format(PyExc_TypeError, "pack_frame: cannot pack %s",
               Py_TYPE(o)->tp_name);
  return false;
}

// -- decoder --

enum ParseStatus { kParseOk = 0, kParseMore = 1, kParseErr = 2 };

uint32_t GetBE32(const unsigned char* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

// Parses one msgpack object at *off. On kParseOk advances *off and sets
// *out (new reference). kParseMore = need more bytes (*off untouched,
// no error set). kParseErr = malformed stream (Python error set).
ParseStatus ParseObj(const unsigned char* p, size_t n, size_t* off,
                     PyObject** out, int depth) {
  if (depth > 128) {
    PyErr_SetString(PyExc_ValueError, "msgpack nesting too deep");
    return kParseErr;
  }
  size_t o = *off;
  if (o >= n) return kParseMore;
  uint8_t b = p[o++];
  // Fast scalar forms first.
  if (b < 0x80) {  // positive fixint
    *out = PyLong_FromLong(b);
    *off = o;
    return *out ? kParseOk : kParseErr;
  }
  if (b >= 0xe0) {  // negative fixint
    *out = PyLong_FromLong(static_cast<int8_t>(b));
    *off = o;
    return *out ? kParseOk : kParseErr;
  }
  size_t len = 0;
  switch (b) {
    case 0xc0:
      Py_INCREF(Py_None);
      *out = Py_None;
      *off = o;
      return kParseOk;
    case 0xc2:
      Py_INCREF(Py_False);
      *out = Py_False;
      *off = o;
      return kParseOk;
    case 0xc3:
      Py_INCREF(Py_True);
      *out = Py_True;
      *off = o;
      return kParseOk;
    case 0xcc:
      if (o + 1 > n) return kParseMore;
      *out = PyLong_FromLong(p[o]);
      *off = o + 1;
      return *out ? kParseOk : kParseErr;
    case 0xcd:
      if (o + 2 > n) return kParseMore;
      *out = PyLong_FromLong((p[o] << 8) | p[o + 1]);
      *off = o + 2;
      return *out ? kParseOk : kParseErr;
    case 0xce:
      if (o + 4 > n) return kParseMore;
      *out = PyLong_FromUnsignedLong(GetBE32(p + o));
      *off = o + 4;
      return *out ? kParseOk : kParseErr;
    case 0xcf: {
      if (o + 8 > n) return kParseMore;
      uint64_t v = (static_cast<uint64_t>(GetBE32(p + o)) << 32) |
                   GetBE32(p + o + 4);
      *out = PyLong_FromUnsignedLongLong(v);
      *off = o + 8;
      return *out ? kParseOk : kParseErr;
    }
    case 0xd0:
      if (o + 1 > n) return kParseMore;
      *out = PyLong_FromLong(static_cast<int8_t>(p[o]));
      *off = o + 1;
      return *out ? kParseOk : kParseErr;
    case 0xd1:
      if (o + 2 > n) return kParseMore;
      *out = PyLong_FromLong(
          static_cast<int16_t>((p[o] << 8) | p[o + 1]));
      *off = o + 2;
      return *out ? kParseOk : kParseErr;
    case 0xd2:
      if (o + 4 > n) return kParseMore;
      *out = PyLong_FromLong(static_cast<int32_t>(GetBE32(p + o)));
      *off = o + 4;
      return *out ? kParseOk : kParseErr;
    case 0xd3: {
      if (o + 8 > n) return kParseMore;
      uint64_t v = (static_cast<uint64_t>(GetBE32(p + o)) << 32) |
                   GetBE32(p + o + 4);
      *out = PyLong_FromLongLong(static_cast<int64_t>(v));
      *off = o + 8;
      return *out ? kParseOk : kParseErr;
    }
    case 0xca: {  // float32 (never emitted by us; accepted for parity)
      if (o + 4 > n) return kParseMore;
      uint32_t bits = GetBE32(p + o);
      float f;
      std::memcpy(&f, &bits, 4);
      *out = PyFloat_FromDouble(f);
      *off = o + 4;
      return *out ? kParseOk : kParseErr;
    }
    case 0xcb: {
      if (o + 8 > n) return kParseMore;
      uint64_t bits = (static_cast<uint64_t>(GetBE32(p + o)) << 32) |
                      GetBE32(p + o + 4);
      double d;
      std::memcpy(&d, &bits, 8);
      *out = PyFloat_FromDouble(d);
      *off = o + 8;
      return *out ? kParseOk : kParseErr;
    }
    case 0xd9:  // str8
      if (o + 1 > n) return kParseMore;
      len = p[o];
      o += 1;
      goto parse_str;
    case 0xda:
      if (o + 2 > n) return kParseMore;
      len = (p[o] << 8) | p[o + 1];
      o += 2;
      goto parse_str;
    case 0xdb:
      if (o + 4 > n) return kParseMore;
      len = GetBE32(p + o);
      o += 4;
      goto parse_str;
    case 0xc4:
      if (o + 1 > n) return kParseMore;
      len = p[o];
      o += 1;
      goto parse_bin;
    case 0xc5:
      if (o + 2 > n) return kParseMore;
      len = (p[o] << 8) | p[o + 1];
      o += 2;
      goto parse_bin;
    case 0xc6:
      if (o + 4 > n) return kParseMore;
      len = GetBE32(p + o);
      o += 4;
      goto parse_bin;
    case 0xdc:
      if (o + 2 > n) return kParseMore;
      len = (p[o] << 8) | p[o + 1];
      o += 2;
      goto parse_array;
    case 0xdd:
      if (o + 4 > n) return kParseMore;
      len = GetBE32(p + o);
      o += 4;
      goto parse_array;
    case 0xde:
      if (o + 2 > n) return kParseMore;
      len = (p[o] << 8) | p[o + 1];
      o += 2;
      goto parse_map;
    case 0xdf:
      if (o + 4 > n) return kParseMore;
      len = GetBE32(p + o);
      o += 4;
      goto parse_map;
    default:
      if ((b & 0xe0) == 0xa0) {  // fixstr
        len = b & 0x1f;
        goto parse_str;
      }
      if ((b & 0xf0) == 0x90) {  // fixarray
        len = b & 0x0f;
        goto parse_array;
      }
      if ((b & 0xf0) == 0x80) {  // fixmap
        len = b & 0x0f;
        goto parse_map;
      }
      // 0xc1 (reserved) and ext families: the wire never carries them.
      PyErr_Format(PyExc_ValueError, "unsupported msgpack byte 0x%02x", b);
      return kParseErr;
  }

parse_str:
  if (len > kMaxWireFrame) {
    PyErr_SetString(PyExc_ValueError, "msgpack str too large");
    return kParseErr;
  }
  if (o + len > n) return kParseMore;
  *out = PyUnicode_DecodeUTF8(reinterpret_cast<const char*>(p + o),
                              static_cast<Py_ssize_t>(len), nullptr);
  if (*out == nullptr) return kParseErr;
  *off = o + len;
  return kParseOk;

parse_bin:
  if (len > kMaxWireFrame) {
    PyErr_SetString(PyExc_ValueError, "msgpack bin too large");
    return kParseErr;
  }
  if (o + len > n) return kParseMore;
  *out = PyBytes_FromStringAndSize(reinterpret_cast<const char*>(p + o),
                                   static_cast<Py_ssize_t>(len));
  if (*out == nullptr) return kParseErr;
  *off = o + len;
  return kParseOk;

parse_array: {
  if (len > (16u << 20)) {
    PyErr_SetString(PyExc_ValueError, "msgpack array too large");
    return kParseErr;
  }
  PyObject* list = PyList_New(static_cast<Py_ssize_t>(len));
  if (list == nullptr) return kParseErr;
  for (size_t i = 0; i < len; ++i) {
    PyObject* item = nullptr;
    ParseStatus st = ParseObj(p, n, &o, &item, depth + 1);
    if (st != kParseOk) {
      Py_DECREF(list);
      return st;
    }
    PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i), item);  // steals
  }
  *out = list;
  *off = o;
  return kParseOk;
}

parse_map: {
  if (len > (16u << 20)) {
    PyErr_SetString(PyExc_ValueError, "msgpack map too large");
    return kParseErr;
  }
  PyObject* dict = PyDict_New();
  if (dict == nullptr) return kParseErr;
  for (size_t i = 0; i < len; ++i) {
    PyObject* key = nullptr;
    PyObject* value = nullptr;
    ParseStatus st = ParseObj(p, n, &o, &key, depth + 1);
    if (st != kParseOk) {
      Py_DECREF(dict);
      return st;
    }
    st = ParseObj(p, n, &o, &value, depth + 1);
    if (st != kParseOk) {
      Py_DECREF(key);
      Py_DECREF(dict);
      return st;
    }
    int rc = PyDict_SetItem(dict, key, value);
    Py_DECREF(key);
    Py_DECREF(value);
    if (rc != 0) {  // e.g. unhashable key
      Py_DECREF(dict);
      return kParseErr;
    }
  }
  *out = dict;
  *off = o;
  return kParseOk;
}
}

// Streaming decoder object: the same feed()/iterate/tell() surface as
// msgpack.Unpacker, so rpc._new_unpacker can swap it in transparently
// (including the blob-mode switch, which relies on tell() counting total
// consumed bytes since construction).
struct DecoderObject {
  PyObject_HEAD
  std::string* buf;
  size_t pos;                      // parse cursor into *buf
  unsigned long long consumed;     // total bytes consumed since creation
};

PyObject* DecoderNew(PyTypeObject* type, PyObject*, PyObject*) {
  DecoderObject* self =
      reinterpret_cast<DecoderObject*>(type->tp_alloc(type, 0));
  if (self == nullptr) return nullptr;
  self->buf = new std::string();
  self->pos = 0;
  self->consumed = 0;
  return reinterpret_cast<PyObject*>(self);
}

void DecoderDealloc(DecoderObject* self) {
  delete self->buf;
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* DecoderFeed(DecoderObject* self, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_CONTIG_RO) != 0) return nullptr;
  if (self->buf->size() - self->pos + static_cast<size_t>(view.len) >
      kMaxWireFrame) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "decoder buffer limit exceeded");
    return nullptr;
  }
  // Compact consumed prefix before it grows unbounded.
  if (self->pos > (1u << 20)) {
    self->buf->erase(0, self->pos);
    self->pos = 0;
  }
  self->buf->append(static_cast<const char*>(view.buf),
                    static_cast<size_t>(view.len));
  PyBuffer_Release(&view);
  Py_RETURN_NONE;
}

PyObject* DecoderTell(DecoderObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(self->consumed);
}

PyObject* DecoderIter(PyObject* self) {
  Py_INCREF(self);
  return self;
}

PyObject* DecoderNext(DecoderObject* self) {
  const unsigned char* p =
      reinterpret_cast<const unsigned char*>(self->buf->data());
  size_t n = self->buf->size();
  size_t off = self->pos;
  PyObject* out = nullptr;
  ParseStatus st = ParseObj(p, n, &off, &out, 0);
  if (st == kParseOk) {
    self->consumed += off - self->pos;
    self->pos = off;
    return out;
  }
  if (st == kParseMore) return nullptr;  // StopIteration (no error set)
  return nullptr;                        // kParseErr: Python error already set
}

PyMethodDef kDecoderMethods[] = {
    {"feed", reinterpret_cast<PyCFunction>(DecoderFeed), METH_O,
     "feed(bytes-like): append raw stream bytes"},
    {"tell", reinterpret_cast<PyCFunction>(DecoderTell), METH_NOARGS,
     "tell() -> total bytes consumed since creation"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject DecoderType = {
    PyVarObject_HEAD_INIT(nullptr, 0) "_fastpath.Decoder", /* tp_name */
    sizeof(DecoderObject),                                 /* tp_basicsize */
    0,                                                     /* tp_itemsize */
    reinterpret_cast<destructor>(DecoderDealloc),          /* tp_dealloc */
};

PyObject* py_pack_frame(PyObject*, PyObject* arg) {
  std::string out;
  out.reserve(256);
  if (!PackObj(&out, arg, 0)) return nullptr;
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

PyObject* py_schema_versions(PyObject*, PyObject*) {
  PyObject* d = PyDict_New();
  if (d == nullptr) return nullptr;
  for (const auto& s : kWireSchemas) {
    PyObject* v = PyLong_FromLong(s.version);
    if (v == nullptr || PyDict_SetItemString(d, s.method, v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(v);
  }
  return d;
}

// ---------------------------------------------------------------- python

PyObject* py_client_connect(PyObject*, PyObject* args) {
  const char* host;
  int port;
  if (!PyArg_ParseTuple(args, "si", &host, &port)) return nullptr;
  int id;
  Py_BEGIN_ALLOW_THREADS;
  id = GetDriver()->Connect(host, port);
  Py_END_ALLOW_THREADS;
  return PyLong_FromLong(id);
}

PyObject* py_client_close(PyObject*, PyObject* args) {
  int id;
  if (!PyArg_ParseTuple(args, "i", &id)) return nullptr;
  Py_BEGIN_ALLOW_THREADS;
  GetDriver()->Close(id);
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

PyObject* py_submit(PyObject*, PyObject* args) {
  int id;
  const char *tid, *fid, *name, *blob;
  Py_ssize_t tid_len, fid_len, name_len, blob_len;
  if (!PyArg_ParseTuple(args, "iy#y#y#y#", &id, &tid, &tid_len, &fid,
                        &fid_len, &name, &name_len, &blob, &blob_len))
    return nullptr;
  if (tid_len > 255 || fid_len > 65535 || name_len > 65535) {
    PyErr_SetString(PyExc_ValueError, "fastpath field too long");
    return nullptr;
  }
  std::string t(tid, tid_len);
  std::string frame = BuildTaskFrame(
      t, std::string(fid, fid_len), std::string(name, name_len), blob,
      static_cast<size_t>(blob_len));
  // No ALLOW_THREADS: the critical sections inside Submit are O(1) swaps
  // and a condvar notify — releasing the GIL for that costs more (a
  // contended re-acquire) than it saves.
  bool ok = GetDriver()->Submit(id, std::move(t), std::move(frame));
  if (ok) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

PyObject* py_notify_fd(PyObject*, PyObject*) {
  return PyLong_FromLong(GetDriver()->notify_fd());
}

PyObject* py_drain(PyObject*, PyObject*) {
  std::vector<Completion> done;
  Py_BEGIN_ALLOW_THREADS;
  done = GetDriver()->Drain();
  Py_END_ALLOW_THREADS;
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(done.size()));
  if (out == nullptr) return nullptr;
  for (size_t i = 0; i < done.size(); ++i) {
    PyObject* item = Py_BuildValue(
        "(y#iy#)", done[i].tid.data(), (Py_ssize_t)done[i].tid.size(),
        done[i].status, done[i].payload.data(),
        (Py_ssize_t)done[i].payload.size());
    if (item == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), item);
  }
  return out;
}

PyObject* py_serve(PyObject*, PyObject* args) {
  const char* host;
  int port;
  PyObject* callback;
  if (!PyArg_ParseTuple(args, "siO", &host, &port, &callback)) return nullptr;
  if (!PyCallable_Check(callback)) {
    PyErr_SetString(PyExc_TypeError, "callback must be callable");
    return nullptr;
  }
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 64) != 0) {
    ::close(fd);
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  int bound_port = ntohs(addr.sin_port);

  auto srv = std::make_shared<Server>();
  srv->listen_fd = fd;
  Py_INCREF(callback);
  srv->callback = callback;
  int id;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    id = g_next_server_id++;
    srv->id = id;
    g_servers[id] = srv;
  }
  srv->accept_thread = std::thread([srv] { AcceptLoop(srv); });
  return Py_BuildValue("(ii)", id, bound_port);
}

PyObject* py_stop_server(PyObject*, PyObject* args) {
  int id;
  if (!PyArg_ParseTuple(args, "i", &id)) return nullptr;
  std::shared_ptr<Server> srv;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    auto it = g_servers.find(id);
    if (it != g_servers.end()) {
      srv = it->second;
      g_servers.erase(it);
    }
  }
  if (srv) {
    srv->stopping.store(true);
    ::shutdown(srv->listen_fd, SHUT_RDWR);
    ::close(srv->listen_fd);
    Py_BEGIN_ALLOW_THREADS;
    if (srv->accept_thread.joinable()) srv->accept_thread.join();
    {
      std::lock_guard<std::mutex> lk(srv->mu);
      for (auto& t : srv->conn_threads)
        if (t.joinable()) t.detach();  // blocked in recv; sockets closed by
                                       // peers at teardown
    }
    Py_END_ALLOW_THREADS;
  }
  Py_RETURN_NONE;
}

PyObject* py_stop_all(PyObject*, PyObject*) {
  Driver* d = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_driver_mu);
    d = g_driver;
  }
  if (d != nullptr) {
    Py_BEGIN_ALLOW_THREADS;
    d->Stop();
    Py_END_ALLOW_THREADS;
  }
  Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"client_connect", py_client_connect, METH_VARARGS,
     "client_connect(host, port) -> channel_id (-1 on failure)"},
    {"client_close", py_client_close, METH_VARARGS, "close a channel"},
    {"submit", py_submit, METH_VARARGS,
     "submit(channel_id, task_id, func_id, name, args_blob) -> bool"},
    {"notify_fd", py_notify_fd, METH_NOARGS,
     "fd readable when completions are pending"},
    {"drain", py_drain, METH_NOARGS,
     "drain() -> [(task_id, status, payload)]"},
    {"serve", py_serve, METH_VARARGS,
     "serve(host, port, callback) -> (server_id, bound_port)"},
    {"stop_server", py_stop_server, METH_VARARGS, "stop a server"},
    {"stop_all", py_stop_all, METH_NOARGS, "stop the driver IO threads"},
    {"pack_frame", py_pack_frame, METH_O,
     "pack_frame(obj) -> bytes (msgpack, byte-identical to the Python "
     "packer)"},
    {"schema_versions", py_schema_versions, METH_NOARGS,
     "schema_versions() -> {method: version} for natively packed schemas"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_fastpath",
    "native direct-call task channel (driver + worker roles)", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__fastpath() {
  DecoderType.tp_flags = Py_TPFLAGS_DEFAULT;
  DecoderType.tp_doc = "streaming msgpack decoder (msgpack.Unpacker surface)";
  DecoderType.tp_iter = DecoderIter;
  DecoderType.tp_iternext = reinterpret_cast<iternextfunc>(DecoderNext);
  DecoderType.tp_methods = kDecoderMethods;
  DecoderType.tp_new = DecoderNew;
  if (PyType_Ready(&DecoderType) < 0) return nullptr;
  PyObject* mod = PyModule_Create(&kModule);
  if (mod == nullptr) return nullptr;
  Py_INCREF(&DecoderType);
  if (PyModule_AddObject(mod, "Decoder",
                         reinterpret_cast<PyObject*>(&DecoderType)) < 0) {
    Py_DECREF(&DecoderType);
    Py_DECREF(mod);
    return nullptr;
  }
  return mod;
}
