"""Remote-storage URIs for object spilling and train checkpoints, against an
in-process mock S3 server (reference pattern:
python/ray/tests/mock_s3_server.py + test_object_spilling remote-storage
cases + train/_internal/storage.py pyarrow.fs persistence)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from mock_s3_server import MockS3Server  # noqa: E402

import ray_tpu  # noqa: E402


@pytest.fixture
def mock_s3(monkeypatch):
    with MockS3Server() as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", srv.endpoint)
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "mock")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "mock")
        monkeypatch.setenv("AWS_DEFAULT_REGION", "us-east-1")
        srv.create_bucket("bucket")
        yield srv


def test_spill_to_s3_roundtrip(mock_s3, monkeypatch, shutdown_only):
    """Objects spilled under memory pressure land in the S3 bucket and
    restore with contents intact."""
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG",
        '{"type": "uri", "params": {"uri": "s3://bucket/spill"}}',
    )
    arena = 64 * 1024 * 1024
    obj = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=arena)
    n = 2 * arena // obj  # 2x the arena forces spilling
    refs = []
    for i in range(n):
        refs.append(ray_tpu.put(np.full(obj // 8, i, dtype=np.float64)))
    # Something actually went to the bucket.
    with mock_s3.state.lock:
        spilled_keys = [
            k for k in mock_s3.state.buckets["bucket"] if k.startswith("spill/")
        ]
    assert spilled_keys, "no objects were spilled to s3://bucket/spill"
    # Everything restores intact (cold objects pull back from S3).
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=120)
        assert out[0] == i and out[-1] == i and out.shape == (obj // 8,)


def test_checkpoint_to_s3_and_resume(mock_s3, shutdown_only, tmp_path):
    """JaxTrainer persists checkpoints to an s3:// storage path; a second
    run resumes from the S3 checkpoint."""
    import json

    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.jax import JaxTrainer

    ray_tpu.init(num_cpus=4, num_tpus=0)

    def train_fn(config):
        import json as _json
        import os as _os
        import tempfile

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt:
            with ckpt.as_directory() as d:
                start = _json.load(
                    open(_os.path.join(d, "state.json"))
                )["step"] + 1
        for i in range(start, start + 2):
            with tempfile.TemporaryDirectory() as d:
                _json.dump(
                    {"step": i}, open(_os.path.join(d, "state.json"), "w")
                )
                train.report(
                    {"step": i}, checkpoint=Checkpoint.from_directory(d)
                )

    r1 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="s3run", storage_path="s3://bucket/results"),
    ).fit()
    assert r1.error is None
    assert r1.checkpoint is not None
    assert r1.checkpoint.path.startswith("s3://bucket/results/s3run")
    # The files are really in the bucket.
    with mock_s3.state.lock:
        keys = [
            k for k in mock_s3.state.buckets["bucket"]
            if k.startswith("results/s3run") and k.endswith("state.json")
        ]
    assert keys, "checkpoint files not found in the mock bucket"
    # Materialize from S3 and read back.
    with r1.checkpoint.as_directory() as d:
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 1

    # Resume: steps continue from the persisted S3 checkpoint.
    r2 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="s3run2", storage_path="s3://bucket/results"),
        resume_from_checkpoint=r1.checkpoint,
    ).fit()
    assert r2.error is None
    assert [m["step"] for m in r2.metrics_history] == [2, 3]


def test_uri_storage_s3_spill_restore_delete_roundtrip(mock_s3):
    """Direct UriStorage coverage against the mock S3 server: spill writes
    one namespaced key, restore returns identical bytes, delete removes the
    key, destroy clears the namespace."""
    from ray_tpu._private.external_storage import UriStorage

    store = UriStorage("s3://bucket/direct", namespace="nodeB")
    payload = np.arange(4096, dtype=np.int64).tobytes()
    uri = store.spill("oidX", memoryview(payload))
    assert uri.startswith("uri://bucket/direct/nodeB/oidX-")
    with mock_s3.state.lock:
        keys = [
            k
            for k in mock_s3.state.buckets["bucket"]
            # skip the create_dir placeholder, present only on mock/NFS-like
            # stores where prefixes are materialized
            if k.startswith("direct/nodeB/") and not k.endswith("/")
        ]
    assert len(keys) == 1
    dest = bytearray(len(payload))
    assert store.restore(uri, memoryview(dest)) == len(payload)
    assert bytes(dest) == payload
    store.delete(uri)
    with mock_s3.state.lock:
        assert not [
            k
            for k in mock_s3.state.buckets["bucket"]
            if k.startswith("direct/nodeB/") and not k.endswith("/")
        ]
    store.destroy()


def test_uri_storage_torn_spill_raises_typed_error(mock_s3):
    """Partial-write crash injection: truncate the stored object behind the
    backend's back (a torn upload a crash made visible). Restore must raise
    SpillIntegrityError — never hand back a short/garbage buffer."""
    from ray_tpu._private.external_storage import SpillIntegrityError, UriStorage

    store = UriStorage("s3://bucket/torn")
    payload = np.arange(8192, dtype=np.int64).tobytes()
    uri = store.spill("oidT", memoryview(payload))
    key = uri[len("uri://bucket/") :]
    with mock_s3.state.lock:
        data = mock_s3.state.buckets["bucket"][key]
        mock_s3.state.buckets["bucket"][key] = data[: len(data) // 2]
    dest = bytearray(len(payload))
    with pytest.raises(SpillIntegrityError) as ei:
        store.restore(uri, memoryview(dest))
    assert ei.value.expected == len(payload)
    assert ei.value.actual < len(payload)
    # The typed error is what the raylet keys its copy-lost handling on; a
    # generic short read would instead seal trailing garbage into the arena.
    assert "torn" in str(ei.value)


def test_uri_storage_local_file_scheme(tmp_path):
    """The same uri backend covers plain filesystem URIs (NFS-style)."""
    from ray_tpu._private.external_storage import UriStorage

    store = UriStorage(f"file://{tmp_path}/spill", namespace="nodeA")
    payload = np.arange(1000, dtype=np.int64).tobytes()
    uri = store.spill("oid1", memoryview(payload))
    dest = bytearray(len(payload))
    n = store.restore(uri, memoryview(dest))
    assert n == len(payload) and bytes(dest) == payload
    store.delete(uri)
    dest2 = bytearray(len(payload))
    with pytest.raises(Exception):
        store.restore(uri, memoryview(dest2))
    store.destroy()
