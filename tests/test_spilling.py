"""Object spilling + create backpressure + memory monitor.

Reference analogs: python/ray/tests/test_object_spilling.py (spill/restore),
plasma create_request_queue.cc (backpressure), memory_monitor.h +
worker_killing_policy.h (OOM killing)."""

import os

import numpy as np
import pytest

import ray_tpu

ARENA = 64 * 1024 * 1024  # store minimum
OBJ = 8 * 1024 * 1024  # 8 MB payloads


@pytest.fixture
def small_store():
    info = ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=ARENA)
    yield info
    ray_tpu.shutdown()


def test_put_2x_capacity_and_get_all_back(small_store):
    """Fill the arena twice over; cold objects spill to disk and restore on
    get with their contents intact."""
    n = 2 * ARENA // OBJ  # 16 objects of 8 MB = 128 MB through a 64 MB arena
    refs = []
    for i in range(n):
        arr = np.full(OBJ // 8, i, dtype=np.float64)
        refs.append(ray_tpu.put(arr))
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=60)
        assert out[0] == i and out[-1] == i and out.shape == (OBJ // 8,)


def test_task_outputs_spill(small_store):
    """Task returns exceeding capacity spill; all remain gettable."""

    @ray_tpu.remote
    def produce(i):
        return np.full(OBJ // 8, i, dtype=np.float64)

    refs = [produce.remote(i) for i in range(12)]  # 96 MB of returns
    for i, ref in enumerate(refs):
        assert ray_tpu.get(ref, timeout=120)[0] == i


def test_spill_stats_visible(small_store):
    # Hold the refs: unreferenced puts are freed promptly and would never
    # pressure the store into spilling.
    refs = [ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)) for i in range(12)]
    stats = [
        s
        for s in ray_tpu._private.worker.global_worker.run_async(
            _node_stats(), timeout=30
        )
    ]
    assert any(s.get("spilled_objects", 0) > 0 for s in stats)


async def _node_stats():
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker.core
    reply = await core.raylet_conn.call("GetNodeStats", {})
    return [reply]


def test_spill_io_off_event_loop(shutdown_only, monkeypatch):
    """A slow spill backend must not stall the raylet event loop: control
    RPCs (Ping) stay fast while multi-object spills are in flight
    (reference: async IO workers, local_object_manager.cc)."""
    import json
    import threading
    import time

    from ray_tpu._private import external_storage as es
    from ray_tpu._private import worker as worker_mod

    class SlowFS(es.FileSystemStorage):
        def spill(self, oid, data):
            time.sleep(0.5)  # simulate slow storage media
            return super().spill(oid, data)

    es.register_storage_backend(
        "slowfs",
        lambda params: SlowFS(
            params.get("directory_path", "/tmp/ray_tpu_slowfs_test")
        ),
    )
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG", json.dumps({"type": "slowfs"})
    )
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=ARENA)

    refs = []
    done = threading.Event()

    def putter():
        # 2x arena capacity: forces spills of the cold half, each write
        # paying the 0.5s media penalty on the IO pool.
        for i in range(16):
            refs.append(ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)))
        done.set()

    t = threading.Thread(target=putter, daemon=True)
    t.start()

    async def _ping():
        core = worker_mod.global_worker.core
        return await core.raylet_conn.call("Ping", {})

    worst = 0.0
    while not done.is_set():
        t0 = time.monotonic()
        worker_mod.global_worker.run_async(_ping(), timeout=30)
        worst = max(worst, time.monotonic() - t0)
        time.sleep(0.02)
    t.join(timeout=120)
    assert done.is_set()
    # Inline spill writes would stall pings for ~0.5s each; off-loop IO
    # keeps the loop turning.
    assert worst < 0.3, f"event loop stalled {worst:.3f}s during spills"
    for i, ref in enumerate(refs):
        assert ray_tpu.get(ref, timeout=120)[0] == i


def test_pluggable_remote_spill_backend(shutdown_only, monkeypatch):
    """Spilling routes through a registered non-filesystem backend (the
    remote-storage hook, reference external_storage.py smart_open path)."""
    import json

    from ray_tpu._private import external_storage as es

    blobs = {}

    class MemStorage(es.ExternalStorage):
        def spill(self, oid, data):
            blobs[oid] = bytes(data)
            return "mem://" + oid

        def restore(self, uri, dest):
            data = blobs[uri[len("mem://") :]]
            dest[: len(data)] = data
            return len(data)

        def delete(self, uri):
            blobs.pop(uri[len("mem://") :], None)

        def destroy(self):
            blobs.clear()

    es.register_storage_backend("memtest", lambda params: MemStorage())
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG", json.dumps({"type": "memtest"})
    )
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=ARENA)
    n = 2 * ARENA // OBJ
    refs = [ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)) for i in range(n)]
    # Wait until some spill writes land in the fake remote store.
    import time

    deadline = time.monotonic() + 30
    while not blobs and time.monotonic() < deadline:
        time.sleep(0.1)
    assert blobs, "no objects were spilled through the registered backend"
    for i, ref in enumerate(refs):
        assert ray_tpu.get(ref, timeout=60)[0] == i


def _spill_files(spill_dir):
    import glob

    return [
        f
        for f in glob.glob(os.path.join(spill_dir, "**"), recursive=True)
        if os.path.isfile(f) and not f.endswith(".tmp")
    ]


def test_spill_file_deleted_on_free(shutdown_only, monkeypatch, tmp_path):
    """Regression for the spill-file leak: freeing a spilled object must
    delete its backing file from external storage, not just the spilled[]
    table entry (reference: local_object_manager.cc spilled-object deletion
    on ref release)."""
    import gc
    import json
    import time

    spill_dir = str(tmp_path / "spill")
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG",
        json.dumps(
            {"type": "filesystem", "params": {"directory_path": spill_dir}}
        ),
    )
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=ARENA)
    n = 2 * ARENA // OBJ
    refs = [ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)) for i in range(n)]
    deadline = time.monotonic() + 30
    while not _spill_files(spill_dir) and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _spill_files(spill_dir), "pressure never spilled anything"
    # Drop the only refs: the owner's free must reach the raylet and the
    # raylet must unlink every spilled file, not only forget the URI.
    del refs
    gc.collect()
    deadline = time.monotonic() + 30
    while _spill_files(spill_dir) and time.monotonic() < deadline:
        time.sleep(0.2)
    leaked = _spill_files(spill_dir)
    assert not leaked, f"freed objects leaked spill files: {leaked}"


def test_spill_files_deleted_on_shutdown(shutdown_only, monkeypatch, tmp_path):
    """Session teardown deletes every still-spilled object's backing file
    (per-entry delete runs before the IO pool shuts down; destroy() then
    removes the session subtree)."""
    import json
    import time

    spill_dir = str(tmp_path / "spill")
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG",
        json.dumps(
            {"type": "filesystem", "params": {"directory_path": spill_dir}}
        ),
    )
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=ARENA)
    n = 2 * ARENA // OBJ
    refs = [ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)) for i in range(n)]
    deadline = time.monotonic() + 30
    while not _spill_files(spill_dir) and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _spill_files(spill_dir), "pressure never spilled anything"
    assert refs  # keep the refs live until shutdown
    ray_tpu.shutdown()
    leaked = _spill_files(spill_dir)
    assert not leaked, f"shutdown leaked spill files: {leaked}"


def test_pinned_object_never_spills(small_store):
    """A pinned object survives pressure that spills everything else, and
    spilling it explicitly is rejected."""
    import time

    from ray_tpu._private import worker as worker_mod

    pin_ref = ray_tpu.put(np.full(OBJ // 8, 7.0, dtype=np.float64))

    async def _pin(oid):
        core = worker_mod.global_worker.core
        return await core.plasma.pin(oid)

    assert worker_mod.global_worker.run_async(_pin(pin_ref.hex()), timeout=30)

    refs = [ray_tpu.put(np.full(OBJ // 8, i, dtype=np.float64)) for i in range(12)]

    async def _probe(oid):
        core = worker_mod.global_worker.core
        spill = await core.plasma.spill([oid])
        contains = await core.plasma.contains([oid])
        return spill, contains[oid]

    deadline = time.monotonic() + 30
    spilled_any = False
    while time.monotonic() < deadline and not spilled_any:
        stats = worker_mod.global_worker.run_async(_node_stats(), timeout=30)
        spilled_any = any(s.get("spilled_objects", 0) > 0 for s in stats)
        time.sleep(0.1)
    assert spilled_any, "pressure never spilled anything"
    spill_reply, in_arena = worker_mod.global_worker.run_async(
        _probe(pin_ref.hex()), timeout=30
    )
    assert pin_ref.hex() in spill_reply["rejected"]
    assert in_arena
    assert ray_tpu.get(pin_ref, timeout=60)[0] == 7.0
    assert ray_tpu.get(refs[0], timeout=60)[0] == 0.0


def test_memory_monitor_kills_runaway_actor(shutdown_only, monkeypatch):
    """With no task workers leased, an actor worker is eligible (reference:
    group-by-owner policy kills actors as last resort — a runaway actor must
    not OOM the node while the monitor only watches tasks)."""
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.0")
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_INTERVAL_S", "0.2")
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote(max_restarts=0)
    class Hog:
        def spin(self):
            import time

            time.sleep(60)
            return 1

    hog = Hog.remote()
    # Specifically the actor-death surface — a plain GetTimeoutError would
    # mean the monitor never selected the actor worker.
    from ray_tpu._private.common import ActorDiedError, ActorUnavailableError

    with pytest.raises((ActorDiedError, ActorUnavailableError)):
        ray_tpu.get(hog.spin.remote(), timeout=120)


def test_memory_monitor_kills_newest_task(shutdown_only, monkeypatch):
    """With the threshold forced to 0, the monitor kills the newest leased
    task worker; a non-retriable task surfaces WorkerCrashedError."""
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.0")
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_INTERVAL_S", "0.2")
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote(max_retries=0)
    def hog():
        import time

        time.sleep(30)
        return 1

    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(hog.remote(), timeout=60)
