"""Model + ops tests on the virtual 8-device CPU mesh: transformer forward/
loss/grad, sharded train step over a dp×tp mesh, flash-attention kernel vs
XLA reference, resnet shapes, fused ops."""

import numpy as np
import pytest


@pytest.fixture(scope="module", autouse=True)
def _cpu(request):
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)


def _tiny_cfg(**kw):
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig

    defaults = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


def test_transformer_forward_loss():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import transformer_apply, transformer_init, transformer_loss

    cfg = _tiny_cfg()
    p = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = transformer_apply(p, toks, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = transformer_loss(p, {"tokens": toks}, cfg)
    # Untrained loss ~= ln(vocab).
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 0.5


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import transformer_apply, transformer_init

    cfg = _tiny_cfg()
    p = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
    a = transformer_apply(p, toks, cfg)
    b = transformer_apply(p, toks2, cfg)
    np.testing.assert_allclose(a[0, :-1], b[0, :-1], atol=1e-5)
    assert np.abs(np.asarray(a[0, -1] - b[0, -1])).max() > 1e-4


def test_transformer_grad_nonzero():
    import jax
    from ray_tpu.models import transformer_init, transformer_loss

    cfg = _tiny_cfg()
    p = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    g = jax.grad(lambda p: transformer_loss(p, {"tokens": toks}, cfg))(p)
    total = jax.tree.reduce(
        lambda a, b: a + b, jax.tree.map(lambda x: float(abs(x).sum()), g)
    )
    assert total > 0


def test_sharded_train_step_loss_decreases():
    import jax
    import optax
    from ray_tpu.models import make_train_step
    from ray_tpu.parallel import make_mesh

    cfg = _tiny_cfg()
    mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    init_state, step, shardings = make_train_step(
        cfg, mesh, optax.adam(1e-2)
    )
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, cfg.vocab_size)
    toks = jax.device_put(toks, shardings["tokens"])
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"tokens": toks})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_param_shardings_cover_tree():
    import jax
    from ray_tpu.models import param_shardings, transformer_init
    from ray_tpu.parallel import make_mesh

    cfg = _tiny_cfg()
    mesh = make_mesh({"fsdp": 4, "tensor": 2})
    p = transformer_init(jax.random.PRNGKey(0), cfg)
    s = param_shardings(mesh, cfg)
    assert jax.tree.structure(p) == jax.tree.structure(s)


def test_flash_attention_matches_xla():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import flash_attention, mha

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (2, 96, 2, 64), jnp.float32)
    k = jax.random.normal(k2, (2, 96, 2, 64), jnp.float32)
    v = jax.random.normal(k3, (2, 96, 2, 64), jnp.float32)
    for causal in (False, True):
        ref = mha(q, k, v, causal=causal, impl="xla")
        out = flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
        )
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=3e-5)


def test_flash_attention_grad():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import flash_attention, mha

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (1, 64, 2, 32), jnp.float32)
    k = jax.random.normal(k2, (1, 64, 2, 32), jnp.float32)
    v = jax.random.normal(k3, (1, 64, 2, 32), jnp.float32)
    gd = jax.grad(
        lambda q: flash_attention(q, k, v, causal=True, interpret=True).sum()
    )(q)
    gr = jax.grad(lambda q: mha(q, k, v, causal=True, impl="xla").sum())(q)
    np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), atol=3e-5)


def test_fused_ops():
    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import fused_rmsnorm, softmax_cross_entropy

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
    w = jnp.ones((32,))
    y = fused_rmsnorm(x, w)
    rms = np.sqrt(np.mean(np.asarray(y) ** 2, axis=-1))
    np.testing.assert_allclose(rms, 1.0, atol=1e-2)

    logits = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 16)
    loss, n = softmax_cross_entropy(logits, labels)
    ref = -np.take_along_axis(
        np.asarray(jax.nn.log_softmax(logits, axis=-1)),
        np.asarray(labels)[..., None], axis=-1,
    ).mean()
    assert abs(float(loss) - ref) < 1e-5
    assert int(n) == 16
    # ignore_index drops positions
    labels2 = labels.at[0, 0].set(-100)
    _, n2 = softmax_cross_entropy(logits, labels2)
    assert int(n2) == 15


def test_resnet_forward():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import ResNetConfig, resnet_apply, resnet_init

    cfg = ResNetConfig(depth=18, num_classes=10, width=8, dtype=jnp.float32)
    p = resnet_init(jax.random.PRNGKey(0), cfg)
    imgs = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, new_p = jax.jit(
        lambda p, x: resnet_apply(p, x, cfg, train=True)
    )(p, imgs)
    assert logits.shape == (2, 10)
    # BN stats updated
    assert not np.allclose(
        np.asarray(new_p["stem_bn"]["mean"]), np.asarray(p["stem_bn"]["mean"])
    )


def test_ring_attention_in_transformer():
    """attention_impl='ring' under shard_map over a sequence axis matches the
    dense forward."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.models import transformer_apply, transformer_init
    from ray_tpu.parallel import make_mesh

    cfg = _tiny_cfg(n_kv_heads=4)
    ring_cfg = _tiny_cfg(n_kv_heads=4, attention_impl="ring")
    p = transformer_init(jax.random.PRNGKey(0), cfg)
    B, T = 2, 32
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    dense = transformer_apply(p, toks, cfg)

    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    nseq = mesh.shape["sequence"]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def fwd(p, toks, pos):
        return transformer_apply(
            p, toks, ring_cfg, positions=pos, seq_axis="sequence",
            seq_size=nseq,
        )

    spec = P(None, "sequence")
    ring = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(P(), spec, spec),
            out_specs=P(None, "sequence", None),
        )
    )(p, toks, positions)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ring), atol=2e-2, rtol=2e-2
    )
