"""Multi-agent RLlib tests (reference: rllib/env/multi_agent_env_runner.py,
core/rl_module/multi_rl_module.py, examples/multi_agent): PPO with two
independent policies on a 2-agent cooperative env must reach a reward
threshold."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner


@pytest.fixture(scope="module", autouse=True)
def _cpu(request):
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(1)


@pytest.fixture
def ma_cluster(shutdown_only):
    from ray_tpu.testing import cpu_mesh_worker_env

    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=cpu_mesh_worker_env(1))
    yield


def _env_factory():
    """Factory closure (shipped by value to runner actors)."""

    def make(cfg):
        import gymnasium as gym
        import numpy as np

        from ray_tpu.rllib import MultiAgentEnv

        class ContextMatch(MultiAgentEnv):
            """Each agent sees its own random one-hot context and is paid
            1.0 for picking the hot index. Optimal per-episode return with
            2 agents and 8 steps = 16; random play = 4."""

            def __init__(self, config):
                self.horizon = int(config.get("horizon", 8))
                n = int(config.get("num_agents", 2))
                self._agents = [f"agent_{i}" for i in range(n)]
                self.observation_spaces = {
                    a: gym.spaces.Box(0.0, 1.0, (4,), dtype=np.float32)
                    for a in self._agents
                }
                self.action_spaces = {
                    a: gym.spaces.Discrete(4) for a in self._agents
                }
                self._rng = np.random.default_rng(config.get("seed", 0))
                self._t = 0
                self._ctx = {}

            def _draw(self):
                self._ctx = {}
                obs = {}
                for a in self._agents:
                    hot = int(self._rng.integers(0, 4))
                    vec = np.zeros(4, dtype=np.float32)
                    vec[hot] = 1.0
                    self._ctx[a] = hot
                    obs[a] = vec
                return obs

            def reset(self, *, seed=None):
                self._t = 0
                return self._draw(), {}

            def step(self, action_dict):
                rewards = {
                    a: float(action_dict[a] == self._ctx[a])
                    for a in self._agents
                }
                self._t += 1
                done = self._t >= self.horizon
                obs = self._draw()
                terms = {a: done for a in self._agents}
                terms["__all__"] = done
                truncs = {a: False for a in self._agents}
                truncs["__all__"] = False
                return obs, rewards, terms, truncs, {}

        return ContextMatch(cfg)

    return make


def test_multi_agent_runner_shapes(ma_cluster):
    runner = MultiAgentEnvRunner(
        _env_factory(),
        policies=["p0", "p1"],
        policy_mapping_fn=lambda aid: "p0" if aid == "agent_0" else "p1",
        seed=1,
    )
    out = runner.sample(16)
    assert out["env_steps"] == 16
    assert set(out["policies"]) == {"p0", "p1"}
    for pid in ("p0", "p1"):
        b = out["policies"][pid]
        assert b["obs"].shape == (16, 1, 4)
        assert b["actions"].shape == (16, 1)
        assert b["rewards"].shape == (16, 1)
        assert b["bootstrap_value"].shape == (1,)
    # Episode bookkeeping: horizon 8 -> 2 completed episodes in 16 steps.
    assert len(out["episode_stats"]) == 2
    # Spaces map per policy.
    assert runner.get_spaces() == {"p0": (4, 4), "p1": (4, 4)}
    runner.stop()


def test_multi_agent_ppo_learns(ma_cluster):
    """PPO with two independent policies on the 2-agent context game must
    reach >=13/16 mean episode return (random = 4, optimal = 16)."""
    config = (
        PPOConfig()
        .environment(env=_env_factory(), env_config={"horizon": 8})
        .env_runners(num_env_runners=1)
        .multi_agent(
            policies=["p0", "p1"],
            policy_mapping_fn=lambda aid: "p0" if aid == "agent_0" else "p1",
        )
        .training(
            train_batch_size=512,
            minibatch_size=64,
            num_epochs=6,
            lr=3e-3,
            entropy_coeff=0.003,
        )
        .debugging(seed=5)
    )
    algo = config.build_algo()
    try:
        best = -np.inf
        for _ in range(40):
            result = algo.train()
            ret = result.get("episode_return_mean", float("nan"))
            if np.isfinite(ret):
                best = max(best, ret)
            if best >= 13.0:
                break
        assert best >= 13.0, f"multi-agent PPO failed to learn: best={best}"
        # Both policies actually trained (per-policy metrics present).
        assert any(k.startswith("p0/") for k in result)
        assert any(k.startswith("p1/") for k in result)
    finally:
        algo.stop()


def test_multi_agent_shared_policy(ma_cluster):
    """Many agents can map onto ONE shared policy (parameter sharing)."""
    config = (
        PPOConfig()
        .environment(
            env=_env_factory(), env_config={"horizon": 4, "num_agents": 3}
        )
        .multi_agent(
            policies=["shared"], policy_mapping_fn=lambda aid: "shared"
        )
        .training(train_batch_size=128, minibatch_size=32, num_epochs=2)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert "episode_return_mean" in result
        # 3 agents share one policy: batch axis is 3.
        out = algo.env_runner_group.sample(4)[0]
        assert out["policies"]["shared"]["obs"].shape == (4, 3, 4)
    finally:
        algo.stop()


def test_multi_agent_runner_vectorized_envs(ma_cluster):
    """num_envs=4: one batched forward per policy covers all env copies —
    the batch axis is num_envs * n_agents and throughput scales with env
    count per jitted call (reference: MultiAgentEnvRunner over vector
    envs)."""
    runner = MultiAgentEnvRunner(
        _env_factory(),
        policies=["p0", "p1"],
        policy_mapping_fn=lambda aid: "p0" if aid == "agent_0" else "p1",
        seed=3,
        num_envs=4,
    )
    out = runner.sample(8)
    # 8 lockstep steps x 4 envs = 32 env steps from one sample() call.
    assert out["env_steps"] == 32
    for pid in ("p0", "p1"):
        b = out["policies"][pid]
        assert b["obs"].shape == (8, 4, 4)  # [T, num_envs * 1 agent, obs]
        assert b["actions"].shape == (8, 4)
        assert b["mask"].shape == (8, 4)
        assert b["bootstrap_value"].shape == (4,)
    # Every env copy completed its horizon-8 episode.
    assert len(out["episode_stats"]) == 4
    # Rewards are per-env meaningful: each env's reward depends on its own
    # context, so the 4 env slots are not identical copies.
    rew = out["policies"]["p0"]["rewards"]
    assert rew.shape == (8, 4)
    runner.stop()
