"""Scalability-envelope tests (reference: release/benchmarks/README.md bars:
10k+ queued tasks per node, 40k actors, 1k PGs cluster-wide — scaled to a
single CI host). Excluded from the default run (`-m 'not scale'`); run with:

    python -m pytest -m scale tests/test_scale.py -q
"""

import time

import pytest

import ray_tpu

pytestmark = pytest.mark.scale


@pytest.fixture
def big_cluster(shutdown_only, monkeypatch):
    monkeypatch.setenv("RAY_TPU_MAX_WORKERS_PER_NODE", "300")
    monkeypatch.setenv("RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S", "800")
    ray_tpu.init(num_cpus=256, num_tpus=0)
    yield


@pytest.mark.timeout(900)
def test_10k_queued_tasks(big_cluster):
    """10,000 tasks queued at once all complete (reference bar: 1M queued on
    one m4.16xlarge; scaled to CI)."""

    @ray_tpu.remote(num_cpus=8)  # bound worker-process count to ~32
    def tick(i):
        return i

    refs = [tick.remote(i) for i in range(10_000)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(10_000))


@pytest.mark.timeout(900)
def test_200_actors(big_cluster):
    """200 concurrent actors all answer (reference bar: 40k cluster-wide)."""

    @ray_tpu.remote(num_cpus=0.5)
    class Cell:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [Cell.remote(i) for i in range(200)]
    out = ray_tpu.get([a.who.remote() for a in actors], timeout=600)
    assert out == list(range(200))
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.timeout(900)
def test_50_placement_groups(big_cluster):
    """50 simultaneous placement groups become ready and host work
    (reference bar: 1k+ cluster-wide)."""
    from ray_tpu.util.placement_group import placement_group, remove_placement_group
    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    @ray_tpu.remote(num_cpus=1)
    def inside():
        return 1

    pgs = [placement_group([{"CPU": 1}]) for _ in range(50)]
    for pg in pgs:
        assert pg.wait(timeout=120)
    refs = [
        inside.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)
        ).remote()
        for pg in pgs
    ]
    assert sum(ray_tpu.get(refs, timeout=600)) == 50
    for pg in pgs:
        remove_placement_group(pg)


@pytest.mark.timeout(1800)
def test_100k_queued_tasks(big_cluster):
    """100,000 tasks queued at once all complete (reference bar: 1M queued
    on one m4.16xlarge — this is the 10% point on a 1-core CI host)."""

    @ray_tpu.remote(num_cpus=8)  # bound worker-process count to ~32
    def tick(i):
        return i

    t0 = time.perf_counter()
    refs = [tick.remote(i) for i in range(100_000)]
    t_submit = time.perf_counter() - t0
    out = ray_tpu.get(refs, timeout=1500)
    t_total = time.perf_counter() - t0
    assert out == list(range(100_000))
    print(
        f"\n100k queued tasks: submit {100_000 / t_submit:.0f}/s, "
        f"end-to-end {100_000 / t_total:.0f}/s"
    )


@pytest.mark.timeout(1800)
def test_1000_actors(big_cluster):
    """1,000 concurrent actors all answer (reference bar: 40k across a
    64-host cluster). Worker-process spawn is the expected wall on one
    host; the print records where the control plane saturates."""

    @ray_tpu.remote(num_cpus=0.25)
    class Cell:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    t0 = time.perf_counter()
    actors = [Cell.remote(i) for i in range(1000)]
    out = ray_tpu.get([a.who.remote() for a in actors], timeout=1500)
    dt = time.perf_counter() - t0
    assert out == list(range(1000))
    print(f"\n1000 actors alive+answering in {dt:.0f}s ({1000 / dt:.1f}/s)")
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.timeout(1800)
def test_200_placement_groups(big_cluster):
    """200 simultaneous placement groups become ready and host work
    (reference bar: 1k+ cluster-wide)."""
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    @ray_tpu.remote(num_cpus=1)
    def inside():
        return 1

    t0 = time.perf_counter()
    pgs = [placement_group([{"CPU": 1}]) for _ in range(200)]
    for pg in pgs:
        assert pg.wait(timeout=600)
    t_ready = time.perf_counter() - t0
    refs = [
        inside.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg
            )
        ).remote()
        for pg in pgs
    ]
    assert sum(ray_tpu.get(refs, timeout=900)) == 200
    print(f"\n200 PGs ready in {t_ready:.1f}s")
    for pg in pgs:
        remove_placement_group(pg)


# -- simulated-cluster scheduler scale (ray_tpu._private.sim_cluster) --------


def _sim_schedule(cluster, client, n_tasks, concurrency=64, latencies=None):
    """Run n_tasks 1-CPU lease/release cycles round-robin over every node
    as the entry point, optionally recording per-lease grant latency."""
    import asyncio

    async def schedule_all():
        sem = asyncio.Semaphore(concurrency)
        entries = [tuple(r.addr) for r in cluster.raylets.values()]
        loop = asyncio.get_running_loop()

        async def one(i):
            async with sem:
                t0 = loop.time()
                grant = await client.lease(
                    {"CPU": 1.0}, entry_addr=entries[i % len(entries)]
                )
                if latencies is not None:
                    latencies.append(loop.time() - t0)
                await client.release(grant)

        await asyncio.gather(*(one(i) for i in range(n_tasks)))

    cluster.run(schedule_all(), timeout=600)


@pytest.mark.timeout(900)
def test_sim_500_nodes_10k_tasks():
    """The headline bar: 500 in-process raylets stand up and 10,000 lease
    cycles schedule through the real spillback protocol."""
    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    cluster = SimCluster(500).start()
    try:
        assert len(cluster.raylets) == 500
        client = SimLeaseClient(cluster)
        t0 = time.perf_counter()
        _sim_schedule(cluster, client, 10_000)
        dt = time.perf_counter() - t0
        print(
            f"\n10k tasks over 500 sim nodes in {dt:.1f}s "
            f"({10_000 / dt:.0f} leases/s)"
        )
        cluster.run(client.close(), timeout=30)
    finally:
        cluster.shutdown()


def _median_lease_latency_s(num_nodes, samples=1500):
    import statistics

    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    cluster = SimCluster(num_nodes).start()
    try:
        client = SimLeaseClient(cluster)
        _sim_schedule(cluster, client, min(samples, 500))  # warmup
        lat = []
        _sim_schedule(cluster, client, samples, concurrency=16, latencies=lat)
        cluster.run(client.close(), timeout=30)
        return statistics.median(lat)
    finally:
        cluster.shutdown()


@pytest.mark.timeout(900)
def test_sim_lease_latency_o_k_not_o_n():
    """The per-lease scheduling decision is O(k), not O(cluster): median
    grant latency at 500 nodes stays within 2x of 50 nodes. (The old
    GetAllNodes-per-lease path was O(N) and blew this bound by an order of
    magnitude.) A 250us absolute floor keeps sub-millisecond timing noise
    from flaking the ratio on a fast host."""
    m50 = _median_lease_latency_s(50)
    m500 = _median_lease_latency_s(500)
    print(f"\nmedian lease latency: 50 nodes {m50 * 1e3:.2f}ms, "
          f"500 nodes {m500 * 1e3:.2f}ms ({m500 / m50:.2f}x)")
    assert m500 <= max(2.0 * m50, m50 + 250e-6), (
        f"lease latency grew {m500 / m50:.1f}x from 50 to 500 nodes "
        f"({m50 * 1e3:.2f}ms -> {m500 * 1e3:.2f}ms): scheduling is "
        "scanning the cluster again"
    )


@pytest.mark.timeout(900)
def test_sim_autoscaler_scales_to_500_nodes():
    """The autoscaler control loop drives the sim provider past 500 nodes
    on sustained synthetic demand, then runs a clean steady-state round on
    real harness stats."""
    from ray_tpu._private.sim_cluster import SimCluster, SimNodeProvider
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig

    target = 500
    cluster = SimCluster(16).start()
    try:
        provider = SimNodeProvider(
            cluster,
            node_types={
                "sim.cpu4": {"resources": {"CPU": 4}, "max_workers": 2000}
            },
        )

        def state():
            stats = cluster.node_stats()
            if len(cluster.raylets) < target:
                # Sustained unmet demand until the fleet reaches target.
                stats[0]["pending_leases"] = 256
                stats[0]["pending_demand"] = [{"CPU": 10000}] * 64
            return stats

        asc = Autoscaler(
            provider,
            AutoscalerConfig(
                upscale_delay_s=0.0,
                idle_timeout_s=3600.0,
                max_launches_per_round=64,
            ),
            state_fn=state,
        )
        t0 = time.perf_counter()
        rounds = 0
        while len(cluster.raylets) < target and rounds < 40:
            asc.update()
            rounds += 1
        dt = time.perf_counter() - t0
        assert len(cluster.raylets) >= target, (
            f"autoscaler stalled at {len(cluster.raylets)} nodes "
            f"after {rounds} rounds"
        )
        print(
            f"\nautoscaled 16 -> {len(cluster.raylets)} sim nodes in "
            f"{rounds} rounds / {dt:.1f}s"
        )
        # Steady state: a round on real stats must neither launch nor kill.
        out = asc.update()
        assert out["launched"] == 0 and out["terminated"] == 0
    finally:
        cluster.shutdown()


@pytest.mark.timeout(1800)
def test_sim_1000_node_failover_reconnect_storm():
    """HA failover at the scale bar: 1000 in-process raylets lose the GCS
    *machine* (process + its replicated-log member), the warm standby
    promotes from the follower log, and the full 1000-raylet reconnect
    wave re-targets the new leader through the leader file — converging to
    a complete ALIVE node view without melting the control plane."""
    import asyncio
    import os
    import shutil
    import tempfile

    from ray_tpu._private import gcs_ha, rpc
    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    n = 1000
    tmp = tempfile.mkdtemp(prefix="ha_scale_")
    cluster = SimCluster(
        n,
        persist_path=os.path.join(tmp, "gcs.wal"),
        ha=True,
        env={
            "RAY_TPU_GCS_LEADER_LEASE_S": "1.0",
            "RAY_TPU_GCS_STANDBY_POLL_S": "0.05",
        },
    ).start()
    try:
        assert len(cluster.raylets) == n
        client = SimLeaseClient(cluster)
        _sim_schedule(cluster, client, 500)  # warm: every node registered
        t0 = time.perf_counter()
        assert cluster.run(cluster.kill_gcs_host_async(), timeout=120)
        t_promote = time.perf_counter() - t0

        async def converged() -> float:
            # Probe through the leader file like the raylets do: under the
            # reconnect wave a promoted leader can miss its own lease and a
            # second standby takes over, fencing term N and closing its
            # connections — re-resolve and re-dial instead of dying on the
            # demoted address. GetAllNodes is a read; re-issuing is safe.
            leader_file = cluster.gcs_leader_file()

            async def dial() -> "rpc.Connection":
                addr = gcs_ha.resolve_leader_file(leader_file)
                return await rpc.connect(*(addr or cluster.gcs_addr))

            conn = None
            try:
                deadline = asyncio.get_running_loop().time() + 600
                while True:
                    try:
                        if conn is None:
                            conn = await dial()
                        reply = await conn.call("GetAllNodes", timeout=60)
                    except (rpc.RpcError, OSError):
                        if asyncio.get_running_loop().time() > deadline:
                            raise
                        if conn is not None:
                            await conn.close()
                            conn = None
                        await asyncio.sleep(0.25)
                        continue
                    alive = sum(
                        1 for node in reply["nodes"]
                        if node["state"] == "ALIVE"
                    )
                    if alive >= n:
                        return time.perf_counter() - t0
                    if asyncio.get_running_loop().time() > deadline:
                        raise AssertionError(
                            f"only {alive}/{n} nodes re-registered"
                        )
                    await asyncio.sleep(0.25)
            finally:
                if conn is not None:
                    await conn.close()

        t_converge = cluster.run(converged(), timeout=700)
        # The promoted leader still schedules: a fresh lease burst works.
        _sim_schedule(cluster, client, 500)
        cluster.run(client.close(), timeout=30)
        print(
            f"\n{n}-node failover: promoted in {t_promote:.2f}s, full "
            f"reconnect storm converged in {t_converge:.1f}s"
        )
    finally:
        cluster.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)


@pytest.mark.timeout(1800)
def test_256mb_broadcast_to_8_nodes(shutdown_only):
    """One 256 MB object broadcast to tasks pinned on 8 raylets — the
    PushManager fan-out pattern (reference bar: 1 GiB to 50+ nodes)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    cluster = Cluster()
    head = cluster.add_node(num_cpus=2, object_store_memory=600 * 1024 * 1024)
    ray_tpu.init(address=cluster.address)
    nodes = [head] + [
        cluster.add_node(
            num_cpus=2, object_store_memory=600 * 1024 * 1024
        )
        for _ in range(7)
    ]

    @ray_tpu.remote(num_cpus=1)
    def digest(arr):
        return int(arr[0]), int(arr[-1]), arr.nbytes

    payload = np.arange(256 * 1024 * 1024 // 8, dtype=np.float64)
    ref = ray_tpu.put(payload)
    t0 = time.perf_counter()
    refs = [
        digest.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n.node_id, soft=False
            )
        ).remote(ref)
        for n in nodes
    ]
    out = ray_tpu.get(refs, timeout=900)
    dt = time.perf_counter() - t0
    assert all(o == (0, len(payload) - 1, payload.nbytes) for o in out)
    total_gb = 256 / 1024 * len(nodes)
    print(
        f"\n256MB broadcast to {len(nodes)} nodes in {dt:.1f}s "
        f"({total_gb / dt:.2f} GB/s aggregate)"
    )
    cluster.shutdown()
