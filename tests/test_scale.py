"""Scalability-envelope tests (reference: release/benchmarks/README.md bars:
10k+ queued tasks per node, 40k actors, 1k PGs cluster-wide — scaled to a
single CI host). Excluded from the default run (`-m 'not scale'`); run with:

    python -m pytest -m scale tests/test_scale.py -q
"""

import time

import pytest

import ray_tpu

pytestmark = pytest.mark.scale


@pytest.fixture
def big_cluster(shutdown_only, monkeypatch):
    monkeypatch.setenv("RAY_TPU_MAX_WORKERS_PER_NODE", "300")
    monkeypatch.setenv("RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S", "800")
    ray_tpu.init(num_cpus=256, num_tpus=0)
    yield


@pytest.mark.timeout(900)
def test_10k_queued_tasks(big_cluster):
    """10,000 tasks queued at once all complete (reference bar: 1M queued on
    one m4.16xlarge; scaled to CI)."""

    @ray_tpu.remote(num_cpus=8)  # bound worker-process count to ~32
    def tick(i):
        return i

    refs = [tick.remote(i) for i in range(10_000)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(10_000))


@pytest.mark.timeout(900)
def test_200_actors(big_cluster):
    """200 concurrent actors all answer (reference bar: 40k cluster-wide)."""

    @ray_tpu.remote(num_cpus=0.5)
    class Cell:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [Cell.remote(i) for i in range(200)]
    out = ray_tpu.get([a.who.remote() for a in actors], timeout=600)
    assert out == list(range(200))
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.timeout(900)
def test_50_placement_groups(big_cluster):
    """50 simultaneous placement groups become ready and host work
    (reference bar: 1k+ cluster-wide)."""
    from ray_tpu.util.placement_group import placement_group, remove_placement_group
    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    @ray_tpu.remote(num_cpus=1)
    def inside():
        return 1

    pgs = [placement_group([{"CPU": 1}]) for _ in range(50)]
    for pg in pgs:
        assert pg.wait(timeout=120)
    refs = [
        inside.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)
        ).remote()
        for pg in pgs
    ]
    assert sum(ray_tpu.get(refs, timeout=600)) == 50
    for pg in pgs:
        remove_placement_group(pg)
