"""Observability closure: Prometheus file-SD, generated Grafana dashboards,
structured events (reference: _private/metrics_agent.py:595,
dashboard/modules/metrics/, src/ray/util/event.cc + _private/event/)."""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util.metrics_export import (
    PrometheusServiceDiscoveryWriter,
    generate_grafana_dashboard,
    write_grafana_dashboards,
)


def test_file_sd_output_matches_prometheus_schema(tmp_path):
    """The written JSON is exactly what a stock Prometheus file_sd_config
    consumes: a list of {targets: [str], labels: {str: str}} groups."""
    targets = ["127.0.0.1:8265", "10.0.0.2:8265"]
    w = PrometheusServiceDiscoveryWriter(
        lambda: list(targets), str(tmp_path), labels={"cluster": "test"}
    )
    path = w.write_once()
    groups = json.loads(open(path).read())
    assert isinstance(groups, list) and len(groups) == 1
    g = groups[0]
    assert set(g) == {"targets", "labels"}
    assert g["targets"] == sorted(targets)
    assert g["labels"]["job"] == "ray_tpu"
    assert g["labels"]["cluster"] == "test"
    assert all(isinstance(t, str) for t in g["targets"])
    assert all(
        isinstance(k, str) and isinstance(v, str)
        for k, v in g["labels"].items()
    )
    # Background refresh picks up target changes.
    w.interval_s = 0.05
    w.start()
    targets.append("10.0.0.3:8265")
    deadline = time.time() + 5
    while time.time() < deadline:
        if "10.0.0.3:8265" in json.loads(open(path).read())[0]["targets"]:
            break
        time.sleep(0.05)
    w.stop()
    assert "10.0.0.3:8265" in json.loads(open(path).read())[0]["targets"]


def test_grafana_dashboard_generation(tmp_path):
    dash = generate_grafana_dashboard(extra_metrics=["my_app_qps"])
    assert dash["uid"] == "ray-tpu-core"
    assert dash["panels"], "dashboard must have panels"
    exprs = [p["targets"][0]["expr"] for p in dash["panels"]]
    assert "my_app_qps" in exprs
    for p in dash["panels"]:
        assert p["type"] == "timeseries"
        assert p["targets"][0]["refId"] == "A"
    out = write_grafana_dashboards(str(tmp_path), ["my_app_qps"])
    written = json.loads(open(out).read())
    assert written["title"] == "Ray TPU Core"


def test_structured_events_emitted_and_queryable(shutdown_only):
    """Node membership and actor failure produce events, queryable via the
    state API and durably appended to the session's event log file."""
    from ray_tpu.util.state.api import list_cluster_events

    ray_tpu.init(num_cpus=2, num_tpus=0)

    events = list_cluster_events()
    labels = [e["label"] for e in events]
    assert "NODE_ADDED" in labels

    @ray_tpu.remote(max_restarts=0)
    class Doomed:
        def die(self):
            import os

            os._exit(1)

    a = Doomed.remote()
    with pytest.raises(Exception):
        ray_tpu.get(a.die.remote())
    deadline = time.time() + 30
    while time.time() < deadline:
        dead = list_cluster_events(label="ACTOR_DEAD")
        if dead:
            break
        time.sleep(0.2)
    assert dead and dead[-1]["severity"] == "ERROR"
    assert "custom_fields" in dead[-1] and dead[-1]["custom_fields"]["actor_id"]

    # Severity filter works.
    assert all(
        e["severity"] == "ERROR" for e in list_cluster_events(severity="ERROR")
    )

    # Durable JSONL file parses back to the same events.
    from ray_tpu._private.events import read_event_log
    from ray_tpu._private.worker import global_worker

    session = global_worker.node.session_name
    on_disk = read_event_log(session, "GCS")
    assert any(e["label"] == "NODE_ADDED" for e in on_disk)
