"""Compiled device tensor channels (DeviceTensorChannel): the shm slot
carries a control frame and the payload hops device-to-device through a
cached compiled ppermute program (docs/collectives.md).

On the CPU-forced 8-device mesh the channel runs in "loopback" mode — the
hop executes for real (device 0 -> device N over the virtual mesh) and the
dst-device array is handed to a same-process reader, while the frame also
carries the raw bytes so a cross-process reader degrades to the
TensorChannel wire instead of deadlocking. The true multi-controller "ici"
mode shares all of this machinery minus the byte fallback; the CPU backend
cannot form cross-process XLA computations, so that path is exercised on
hardware via the MULTICHIP harness.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _cpu():
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)


def _pair(name, meta=None, size=1 << 22):
    from ray_tpu.dag.tensor_channel import DeviceTensorChannel

    w = DeviceTensorChannel(name, size, create=True, meta=meta)
    r = DeviceTensorChannel(name, size, meta=meta)
    return w, r


def test_device_channel_loopback_hop():
    """Same-process read returns the hopped dst-device array — the payload
    crossed the mesh, not the shm slot."""
    import jax

    w, r = _pair("rtdag_test_dev1", meta={"src": 0, "dst": 3})
    try:
        arr = np.arange(64, dtype=np.float32).reshape(8, 8)
        w.write(arr)
        assert w._mode == "loopback"
        out = r.read(timeout=10)
        assert isinstance(out, jax.Array)
        assert list(out.devices())[0] == jax.devices()[3], (
            "payload must land on the dst device"
        )
        np.testing.assert_array_equal(np.asarray(out), arr)
    finally:
        w.close(unlink=True)


def test_device_channel_ok_wire_tuple():
    """The exec-loop ("ok", value) wire survives the device path."""
    w, r = _pair("rtdag_test_dev2", meta={"src": 0, "dst": 1})
    try:
        arr = np.full((16, 16), 2.5, dtype=np.float32)
        w.write(("ok", arr))
        kind, val = r.read(timeout=10)
        assert kind == "ok"
        np.testing.assert_array_equal(np.asarray(val), arr)
    finally:
        w.close(unlink=True)


def test_device_channel_nonarray_falls_back_to_shm():
    """STOP sentinels, dicts, and error wires ride the inherited pickle
    path in every mode (teardown must work identically)."""
    from ray_tpu.dag.exec_loop import STOP

    w, r = _pair("rtdag_test_dev3", meta={"src": 0, "dst": 2})
    try:
        w.write({"cfg": [1, 2]})
        assert r.read(timeout=10) == {"cfg": [1, 2]}
        w.write(("err", "boom"))
        assert r.read(timeout=10) == ("err", "boom")
        w.write(STOP)
        assert r.read(timeout=10) == STOP
    finally:
        w.close(unlink=True)


def test_device_channel_cross_process_degrade():
    """A reader that missed the device slot (different process in real
    deployments) decodes the control frame's byte payload instead."""
    from ray_tpu.dag import tensor_channel as tc

    w, r = _pair("rtdag_test_dev4", meta={"src": 0, "dst": 5})
    try:
        arr = np.arange(100, dtype=np.int64).reshape(10, 10)
        w.write(arr)
        # Simulate the reader living in another process: no parked slot.
        tc._DEVICE_SLOTS.pop("rtdag_test_dev4", None)
        out = r.read(timeout=10)
        assert isinstance(out, np.ndarray) and out.dtype == np.int64
        np.testing.assert_array_equal(out, arr)
    finally:
        w.close(unlink=True)


def test_make_channel_dispatches_device_kind():
    from ray_tpu.dag.channel import make_channel
    from ray_tpu.dag.tensor_channel import DeviceTensorChannel

    spec = ("rtdag_test_dev5", 1 << 20, "device",
            {"group": "g1", "src": 2, "dst": 6})
    ch = make_channel(spec, create=True)
    try:
        assert isinstance(ch, DeviceTensorChannel)
        assert ch.group_name == "g1" and ch.src == 2 and ch.dst == 6
    finally:
        ch.close(unlink=True)


def test_device_channel_program_reuse():
    """Repeat writes of the same (shape, dtype) reuse one compiled permute
    program — the per-message cost is staging + dispatch, not retracing."""
    w, r = _pair("rtdag_test_dev6", meta={"src": 0, "dst": 7})
    try:
        for i in range(3):
            w.write(np.full((32, 32), float(i), dtype=np.float32))
            out = r.read(timeout=10)
            assert float(np.asarray(out)[0, 0]) == float(i)
        assert len(w._engine._programs) == 1
    finally:
        w.close(unlink=True)


def test_compiled_dag_device_transport_end_to_end(ray_start_regular):
    """A compiled DAG edge annotated with_tensor_transport("device"): the
    producer actor's writes take the device path (loopback hop on the CPU
    mesh), the consumer decodes the frame, values stay exact, and teardown's
    STOP sentinel crosses the same channel."""
    from ray_tpu import dag

    @ray_tpu.remote
    class Producer:
        def make(self, seed):
            return np.full((128, 128), float(seed), dtype=np.float32)

    @ray_tpu.remote
    class Consumer:
        def total(self, x):
            return float(np.asarray(x).sum())

    p, c = Producer.remote(), Consumer.remote()
    with dag.InputNode() as inp:
        graph = c.total.bind(
            p.make.bind(inp).with_tensor_transport(
                "device", group_name="dag_g", src=0, dst=1
            )
        )
    compiled = graph.experimental_compile()
    try:
        for i in (1, 2, 5):
            assert compiled.execute(i).get() == 128 * 128 * i
    finally:
        compiled.teardown()


def test_device_edge_spec_kind():
    """Graph compilation marks producer-annotated actor->actor edges as
    "device" specs carrying the group/src/dst meta; driver-facing edges
    degrade to "tensor"."""
    from ray_tpu.dag.nodes import ClassMethodNode, InputNode

    class _FakeHandle:
        _actor_id = "a1"

    node = ClassMethodNode(_FakeHandle(), "m", (), {})
    node.with_tensor_transport("device", group_name="g", src=1, dst=2)
    assert node._tensor_transport == "device"
    assert node._transport_meta == {"group": "g", "src": 1, "dst": 2}
    inp = InputNode()
    inp.with_tensor_transport("device")
    # InputNode edges are written by the driver: never "device".
    from ray_tpu.dag.compiled import CompiledDAG  # noqa: F401 (import check)
