"""Core API end-to-end tests: tasks, objects, errors.

Models the reference's python/ray/tests/test_basic.py coverage.
"""

import time

import numpy as np
import pytest

import ray_tpu


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42


def test_put_get_large_numpy(ray_start_regular):
    arr = np.arange(1_000_000, dtype=np.float32)  # 4 MB -> shm path
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2


def test_task_with_ref_arg(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * 2

    ref = ray_tpu.put(21)
    assert ray_tpu.get(f.remote(ref)) == 42


def test_chained_tasks(ray_start_regular):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(4):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 5


def test_many_parallel_tasks(ray_start_regular):
    @ray_tpu.remote
    def f(i):
        return i * i

    refs = [f.remote(i) for i in range(20)]
    assert ray_tpu.get(refs) == [i * i for i in range(20)]


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def f():
        return 1, 2, 3

    a, b, c = f.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("broken")

    with pytest.raises(ValueError, match="broken"):
        ray_tpu.get(boom.remote())


def test_large_task_result(ray_start_regular):
    @ray_tpu.remote
    def make():
        return np.ones((512, 1024), dtype=np.float32)  # 2 MB

    out = ray_tpu.get(make.remote())
    assert out.shape == (512, 1024)
    assert out.dtype == np.float32


def test_large_task_arg(ray_start_regular):
    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    arr = np.ones(500_000, dtype=np.float64)
    assert ray_tpu.get(total.remote(arr)) == 500_000.0


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    # Warm the worker pool so cold-start latency can't eat the wait window.
    assert ray_tpu.get(fast.remote()) == "fast"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=4)
    assert ready == [f]
    assert not_ready == [s]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=1)


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(0)) == 11


def test_kwargs_and_defaults(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3)) == 6


def test_nested_object_ref_passthrough(ray_start_regular):
    @ray_tpu.remote
    def identity(d):
        # Nested refs arrive as refs, not values (reference semantics).
        assert isinstance(d["ref"], ray_tpu.ObjectRef)
        return ray_tpu.get(d["ref"])

    inner_ref = ray_tpu.put(7)
    assert ray_tpu.get(identity.remote({"ref": inner_ref})) == 7


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU") == 4.0
