"""Lineage reconstruction: lost task-return objects are recomputed by
re-running the producing task (reference analog:
python/ray/tests/test_reconstruction.py; owner-side recovery per
src/ray/core_worker/object_recovery_manager.h)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

# Above max_direct_call_object_size so returns land in plasma on the
# producing node (inline returns live in the owner and cannot be lost).
SIZE = (600, 600)  # ~2.9 MB float64


@pytest.fixture
def cluster_with_victim():
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    cluster.connect()
    yield cluster
    cluster.shutdown()


def _victim_node_id():
    for n in ray_tpu.nodes():
        if n["state"] == "ALIVE" and "victim" in {
            k.split(":")[0] for k in n["total"]
        }:
            return n["node_id"]
    raise AssertionError("victim node not found")


def test_reconstruct_lost_object(cluster_with_victim):
    """Kill the node holding a task's plasma return; get() still succeeds."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def produce():
        return np.ones(SIZE)

    ref = produce.remote()
    # Materialize once so the object exists on the victim node.
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 360000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    # Replacement node so the re-executed task has somewhere to run.
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    value = ray_tpu.get(ref, timeout=120)
    assert float(value.sum()) == 360000.0


def test_reconstruct_chain(cluster_with_victim):
    """Loss of an intermediate object recovers recursively through its deps."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def base():
        return np.ones(SIZE)

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    assert float(ray_tpu.get(d, timeout=60).sum()) == 720000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    # Both b's and d's primary copies died; getting d must re-run base then
    # double (the worker resolving double's arg triggers owner-side recovery
    # of b).
    value = ray_tpu.get(d, timeout=120)
    assert float(value.sum()) == 720000.0


def test_borrower_triggers_owner_recovery(cluster_with_victim):
    """A consumer task on another node hits the lost copy and asks the owner
    to reconstruct (RecoverObject RPC path)."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def produce():
        return np.ones(SIZE)

    ref = produce.remote()
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 360000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=120) == 360000.0


def test_put_objects_are_not_reconstructable(cluster_with_victim):
    """ray.put objects have no lineage; loss is a terminal ObjectLostError
    (reference semantics)."""
    cluster = cluster_with_victim

    # Put via a task running ON the victim node so the primary copy is there
    # but ownership stays with that worker... simpler: put from the driver
    # always lands on the head node which we cannot kill. Instead assert the
    # error path directly: lost + no lineage raises.
    @ray_tpu.remote(num_cpus=1, resources={"victim": 1})
    def put_and_return_ref():
        return ray_tpu.put(np.ones(SIZE))

    inner_ref = ray_tpu.get(put_and_return_ref.remote(), timeout=60)
    cluster.remove_node(cluster.raylets[_victim_node_id()])
    time.sleep(0.5)
    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(inner_ref, timeout=30)


def test_reconstruct_actor_task_return(cluster_with_victim):
    """Actor-task returns with max_task_retries>0 are reconstructable by
    resubmitting through the restarted actor (reference:
    task_manager.cc actor-task resubmission)."""
    cluster = cluster_with_victim

    @ray_tpu.remote
    class Producer:
        def produce(self):
            return np.ones(SIZE)

    a = Producer.options(
        max_restarts=3,
        max_task_retries=3,
        num_cpus=1,
        resources={"victim": 1},
    ).remote()
    ref = a.produce.remote()
    # Materialize WITHOUT fetching (a driver-side get would leave a local
    # copy that survives the node kill and masks reconstruction).
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    # The primary copy died with the node AND the actor did too; recovery
    # waits for the restarted incarnation and re-runs the method.
    value = ray_tpu.get(ref, timeout=120)
    assert float(value.sum()) == 360000.0


def test_actor_task_not_reconstructable_without_retries(cluster_with_victim):
    """max_task_retries=0 actor returns keep the old behavior: loss is a
    terminal ObjectLostError."""
    cluster = cluster_with_victim

    @ray_tpu.remote
    class Producer:
        def produce(self):
            return np.ones(SIZE)

    a = Producer.options(
        max_restarts=3, num_cpus=1, resources={"victim": 1}
    ).remote()
    ref = a.produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(ref, timeout=60)
