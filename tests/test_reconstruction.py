"""Lineage reconstruction: lost task-return objects are recomputed by
re-running the producing task (reference analog:
python/ray/tests/test_reconstruction.py; owner-side recovery per
src/ray/core_worker/object_recovery_manager.h)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

# Above max_direct_call_object_size so returns land in plasma on the
# producing node (inline returns live in the owner and cannot be lost).
SIZE = (600, 600)  # ~2.9 MB float64


@pytest.fixture
def cluster_with_victim():
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    cluster.connect()
    yield cluster
    cluster.shutdown()


def _victim_node_id():
    for n in ray_tpu.nodes():
        if n["state"] == "ALIVE" and "victim" in {
            k.split(":")[0] for k in n["total"]
        }:
            return n["node_id"]
    raise AssertionError("victim node not found")


def test_reconstruct_lost_object(cluster_with_victim):
    """Kill the node holding a task's plasma return; get() still succeeds."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def produce():
        return np.ones(SIZE)

    ref = produce.remote()
    # Materialize once so the object exists on the victim node.
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 360000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    # Replacement node so the re-executed task has somewhere to run.
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    value = ray_tpu.get(ref, timeout=120)
    assert float(value.sum()) == 360000.0


def test_reconstruct_chain(cluster_with_victim):
    """Loss of an intermediate object recovers recursively through its deps."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def base():
        return np.ones(SIZE)

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    assert float(ray_tpu.get(d, timeout=60).sum()) == 720000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    # Both b's and d's primary copies died; getting d must re-run base then
    # double (the worker resolving double's arg triggers owner-side recovery
    # of b).
    value = ray_tpu.get(d, timeout=120)
    assert float(value.sum()) == 720000.0


def test_borrower_triggers_owner_recovery(cluster_with_victim):
    """A consumer task on another node hits the lost copy and asks the owner
    to reconstruct (RecoverObject RPC path)."""
    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def produce():
        return np.ones(SIZE)

    ref = produce.remote()
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 360000.0

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=120) == 360000.0


def test_put_objects_are_not_reconstructable(cluster_with_victim):
    """ray.put objects have no lineage; loss is a terminal ObjectLostError
    (reference semantics)."""
    cluster = cluster_with_victim

    # Put via a task running ON the victim node so the primary copy is there
    # but ownership stays with that worker... simpler: put from the driver
    # always lands on the head node which we cannot kill. Instead assert the
    # error path directly: lost + no lineage raises.
    @ray_tpu.remote(num_cpus=1, resources={"victim": 1})
    def put_and_return_ref():
        return ray_tpu.put(np.ones(SIZE))

    inner_ref = ray_tpu.get(put_and_return_ref.remote(), timeout=60)
    cluster.remove_node(cluster.raylets[_victim_node_id()])
    time.sleep(0.5)
    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(inner_ref, timeout=30)


def test_lineage_pruned_raises_typed_error(monkeypatch):
    """With lineage_bytes_limit squeezed to near zero, older producing specs
    are LRU-pruned; losing such an object raises
    ObjectReconstructionFailedError (typed: a tuning problem, not an
    unreconstructable-by-design object)."""
    from ray_tpu._private.common import config

    monkeypatch.setenv("RAY_TPU_LINEAGE_BYTES_LIMIT", "1")
    config.refresh()
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    try:
        cluster.add_node(num_cpus=2, resources={"victim": 2})
        cluster.connect()

        @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
        def produce(i):
            return np.full(SIZE, float(i))

        first = produce.remote(1)
        ready, _ = ray_tpu.wait([first], num_returns=1, timeout=60)
        assert ready
        # A second spilling-sized return prunes first's lineage (the cap
        # keeps only the newest entry).
        second = produce.remote(2)
        ready, _ = ray_tpu.wait([second], num_returns=1, timeout=60)
        assert ready

        cluster.remove_node(cluster.raylets[_victim_node_id()])
        cluster.add_node(num_cpus=2, resources={"victim": 2})
        time.sleep(0.5)

        with pytest.raises(ray_tpu.ObjectReconstructionFailedError):
            ray_tpu.get(first, timeout=60)
    finally:
        cluster.shutdown()
        monkeypatch.delenv("RAY_TPU_LINEAGE_BYTES_LIMIT")
        config.refresh()


def test_node_death_triggers_eager_reconstruction(cluster_with_victim):
    """The owner's node-death subscription recomputes lost primaries without
    waiting for a get: after the victim dies, the owned marker re-points at
    a live raylet on its own."""
    from ray_tpu._private import worker as worker_mod

    cluster = cluster_with_victim

    @ray_tpu.remote(num_cpus=1, resources={"victim": 1}, max_retries=3)
    def produce():
        return np.ones(SIZE)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready
    core = worker_mod.global_worker.core
    dead_addr = core.memory_store.get(ref.hex()).plasma_addr
    assert dead_addr is not None

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        entry = core.memory_store.get(ref.hex())
        if entry is not None and entry.plasma_addr != dead_addr:
            break
        time.sleep(0.2)
    entry = core.memory_store.get(ref.hex())
    assert entry is not None and entry.plasma_addr != dead_addr, (
        "node-death pubsub did not trigger eager reconstruction"
    )
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 360000.0


def test_torn_spill_file_reconstructs_via_lineage(shutdown_only, monkeypatch):
    """A spilled copy whose backing file is torn is a *lost* copy, not a
    transient error: restore fails with the typed integrity error, the
    raylet drops the entry, and the owner's lineage re-runs the producer —
    the consumer still sees correct bytes."""
    import json

    from ray_tpu._private import external_storage as es

    torn = {"count": 0}

    class TornFS(es.FileSystemStorage):
        def restore(self, uri, dest):
            if torn["count"] == 0:
                torn["count"] += 1
                raise es.SpillIntegrityError(uri, len(dest), len(dest) // 2)
            return super().restore(uri, dest)

    es.register_storage_backend(
        "tornfs",
        lambda params: TornFS(
            params.get("directory_path", "/tmp/ray_tpu_tornfs_test")
        ),
    )
    monkeypatch.setenv(
        "RAY_TPU_OBJECT_SPILLING_CONFIG", json.dumps({"type": "tornfs"})
    )
    arena = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=arena)

    @ray_tpu.remote(max_retries=3)
    def produce(i):
        return np.full((1024, 1024), float(i))  # 8 MB each

    refs = [produce.remote(i) for i in range(12)]  # 96 MB through 64 MB
    ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    assert len(ready) == len(refs)
    # Every value comes back right even though one restore hit a torn file
    # (that object's spilled copy was dropped and its producer re-ran).
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=120)
        assert out[0, 0] == float(i) and out[-1, -1] == float(i)


def test_reconstruct_actor_task_return(cluster_with_victim):
    """Actor-task returns with max_task_retries>0 are reconstructable by
    resubmitting through the restarted actor (reference:
    task_manager.cc actor-task resubmission)."""
    cluster = cluster_with_victim

    @ray_tpu.remote
    class Producer:
        def produce(self):
            return np.ones(SIZE)

    a = Producer.options(
        max_restarts=3,
        max_task_retries=3,
        num_cpus=1,
        resources={"victim": 1},
    ).remote()
    ref = a.produce.remote()
    # Materialize WITHOUT fetching (a driver-side get would leave a local
    # copy that survives the node kill and masks reconstruction).
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    # The primary copy died with the node AND the actor did too; recovery
    # waits for the restarted incarnation and re-runs the method.
    value = ray_tpu.get(ref, timeout=120)
    assert float(value.sum()) == 360000.0


def test_actor_task_not_reconstructable_without_retries(cluster_with_victim):
    """max_task_retries=0 actor returns keep the old behavior: loss is a
    terminal ObjectLostError."""
    cluster = cluster_with_victim

    @ray_tpu.remote
    class Producer:
        def produce(self):
            return np.ones(SIZE)

    a = Producer.options(
        max_restarts=3, num_cpus=1, resources={"victim": 1}
    ).remote()
    ref = a.produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready

    cluster.remove_node(cluster.raylets[_victim_node_id()])
    cluster.add_node(num_cpus=2, resources={"victim": 2})
    time.sleep(0.5)

    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(ref, timeout=60)
