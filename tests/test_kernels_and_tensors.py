"""Regression tests for the pallas flash-attention backward, the chunked
LM-head cross entropy, and the Arrow tensor-column extension (all on the CPU
interpreter / CPU arrays — gradient parity against XLA reference math)."""

import numpy as np
import pytest

from ray_tpu.testing import force_cpu_mesh

force_cpu_mesh(8)  # before first backend use, like every jax-facing test

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.ops.flash_attention import (  # noqa: E402
    _xla_attention_bhtd,
    flash_attention,
)
from ray_tpu.ops.fused import (  # noqa: E402
    lm_head_cross_entropy,
    softmax_cross_entropy,
)


def _ref_mha(q, k, v, causal):
    import math

    B, T, H, D = q.shape
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    of = _xla_attention_bhtd(
        qf, kf, vf, causal=causal, scale=1.0 / math.sqrt(D)
    )
    return of.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq", [128, 192])  # 192 exercises block padding
def test_flash_backward_matches_xla(causal, seq):
    q = jax.random.normal(jax.random.PRNGKey(1), (2, seq, 2, 64), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(2), (2, seq, 2, 64), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(3), (2, seq, 2, 64), jnp.float32)

    def f(q, k, v):
        return flash_attention(q, k, v, causal=causal, interpret=True).sum()

    def g(q, k, v):
        return _ref_mha(q, k, v, causal).sum()

    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gg):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_lm_head_ce_matches_dense():
    B, T, d, V = 2, 96, 32, 257  # deliberately non-multiples of the chunk
    hidden = jax.random.normal(jax.random.PRNGKey(0), (B, T, d), jnp.float32)
    unembed = jax.random.normal(jax.random.PRNGKey(1), (d, V), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, V)

    def chunked(h, w):
        loss, _ = lm_head_cross_entropy(h, w, targets, chunk_tokens=64)
        return loss

    def dense(h, w):
        logits = (h @ w).astype(jnp.float32)
        loss, _ = softmax_cross_entropy(logits, targets)
        return loss

    lc = chunked(hidden, unembed)
    ld = dense(hidden, unembed)
    np.testing.assert_allclose(lc, ld, rtol=1e-5)
    gc = jax.grad(chunked, argnums=(0, 1))(hidden, unembed)
    gd = jax.grad(dense, argnums=(0, 1))(hidden, unembed)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_lm_head_ce_ignore_index():
    hidden = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 16), jnp.float32)
    unembed = jax.random.normal(jax.random.PRNGKey(1), (16, 33), jnp.float32)
    targets = np.random.RandomState(0).randint(0, 33, (1, 8))
    targets[0, :4] = -100  # masked positions
    loss, n = lm_head_cross_entropy(
        hidden, unembed, jnp.asarray(targets), chunk_tokens=4
    )
    assert float(n) == 4.0
    logits = np.asarray(hidden[0] @ unembed, dtype=np.float64)
    lse = np.log(np.exp(logits).sum(-1))
    per = lse - logits[np.arange(8), np.where(targets[0] < 0, 0, targets[0])]
    expect = per[4:].mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)


def test_tensor_column_roundtrip_through_blocks():
    import pyarrow as pa

    from ray_tpu.data import block as B
    from ray_tpu.data.tensor_extension import (
        is_tensor_type,
        tensor_column_to_numpy,
    )

    imgs = np.random.randint(0, 255, (16, 48), dtype=np.uint8)
    labels = np.arange(16, dtype=np.int64)
    blk = B.batch_to_block({"image": imgs, "label": labels})
    assert is_tensor_type(blk.schema.field("image").type)

    # numpy batch view is the stacked array (zero-copy reshape)
    batch = B.block_to_batch(blk, "numpy")
    np.testing.assert_array_equal(batch["image"], imgs)

    # slicing and concat preserve tensor semantics
    merged = B.concat_blocks([B.slice_block(blk, 0, 4), B.slice_block(blk, 4, 16)])
    np.testing.assert_array_equal(
        tensor_column_to_numpy(merged.column("image")), imgs
    )

    # rows come back as per-row ndarrays
    rows = B.block_to_rows(blk)
    assert isinstance(rows[0]["image"], np.ndarray)
    np.testing.assert_array_equal(rows[3]["image"], imgs[3])

    # rows_to_block stacks uniform ndarray rows back into a tensor column
    blk2 = B.rows_to_block(rows)
    assert is_tensor_type(blk2.schema.field("image").type)
    np.testing.assert_array_equal(
        B.block_to_batch(blk2, "numpy")["image"], imgs
    )


def test_tensor_column_through_object_store(ray_start_regular):
    import ray_tpu
    from ray_tpu.data import block as B

    imgs = np.random.randint(0, 255, (32, 1024), dtype=np.uint8)
    blk = B.batch_to_block({"image": imgs})
    ref = ray_tpu.put(blk)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(
        B.block_to_batch(out, "numpy")["image"], imgs
    )


def test_concat_mixed_tensor_and_ragged_blocks():
    """Blocks whose ndarray rows differ in shape across blocks must still
    concatenate (tensor columns downgrade to plain lists)."""
    from ray_tpu.data import block as B

    uniform = B.rows_to_block(
        [{"x": np.arange(4, dtype=np.int64)} for _ in range(3)]
    )
    other_shape = B.rows_to_block(
        [{"x": np.arange(6, dtype=np.int64)} for _ in range(2)]
    )
    ragged = B.rows_to_block(
        [{"x": np.arange(3, dtype=np.int64)}, {"x": np.arange(5, dtype=np.int64)}]
    )
    out = B.concat_blocks([uniform, other_shape, ragged])
    rows = B.block_to_rows(out)
    assert len(rows) == 7
    assert list(rows[0]["x"]) == [0, 1, 2, 3]
    assert list(rows[4]["x"]) == [0, 1, 2, 3, 4, 5]
    assert list(rows[6]["x"]) == [0, 1, 2, 3, 4]


def test_prefetch_iterator_early_exit_stops_producer():
    import threading
    import time

    from ray_tpu.data.iterator import prefetch_iterator

    cleaned = threading.Event()

    def gen():
        try:
            for i in range(1000):
                yield i
        finally:
            cleaned.set()

    it = prefetch_iterator(gen(), 2)
    assert next(it) == 0
    it.close()  # consumer abandons mid-stream
    # Fill thread must notice and run the generator's finally block.
    deadline = time.monotonic() + 5
    while not cleaned.is_set() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert cleaned.is_set(), "producer thread leaked after early exit"
    assert not any(
        t.name == "batch-prefetch" and t.is_alive()
        for t in threading.enumerate()
    )
