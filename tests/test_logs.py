"""Log pipeline: worker stdout/stderr is captured to session log files,
streamed to the driver, and queryable via the state API (reference:
python/ray/_private/log_monitor.py, state get_log at util/state/api.py:1183)."""

import time

import pytest

import ray_tpu
from ray_tpu.util.state import get_log, list_logs


@pytest.fixture
def ray2(shutdown_only):
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield


def _wait_for(pred, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.25)
    return False


def test_task_print_streams_to_driver(ray2, capfd):
    @ray_tpu.remote
    def shouty():
        print("hello-from-task-xyzzy")
        return 1

    assert ray_tpu.get(shouty.remote()) == 1

    def seen():
        return "hello-from-task-xyzzy" in capfd.readouterr().err

    # Lines ride the 0.2s pubsub batch flush.
    deadline = time.monotonic() + 10
    found = False
    while time.monotonic() < deadline and not found:
        time.sleep(0.3)
        out = capfd.readouterr()
        found = "hello-from-task-xyzzy" in out.err or "hello-from-task-xyzzy" in out.out
    assert found


def test_get_log_returns_worker_output(ray2):
    @ray_tpu.remote
    class Chatty:
        def speak(self):
            print("actor-line-plugh")
            return "ok"

    c = Chatty.remote()
    assert ray_tpu.get(c.speak.remote()) == "ok"

    def has_line():
        logs = list_logs()
        for node_id, files in logs.items():
            for fname in files:
                if fname.endswith(".out"):
                    lines = get_log(node_id=node_id, filename=fname)
                    if any("actor-line-plugh" in ln for ln in lines):
                        return True
        return False

    assert _wait_for(has_line)


def test_list_logs_has_worker_files(ray2):
    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote())
    logs = list_logs()
    files = [f for fl in logs.values() for f in fl]
    assert any(f.startswith("worker-") for f in files)
