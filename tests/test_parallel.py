"""Parallel-layer tests on the virtual 8-device CPU mesh: mesh construction,
ring attention vs full attention, Ulysses all-to-all attention, gradients."""

import numpy as np
import pytest


@pytest.fixture(scope="module", autouse=True)
def _cpu(request):
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)


def test_make_mesh_infer():
    from ray_tpu.parallel import make_mesh

    mesh = make_mesh({"data": -1, "tensor": 2})
    assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2


def test_make_mesh_bad_shape():
    from ray_tpu.parallel import make_mesh

    with pytest.raises(ValueError):
        make_mesh({"data": 3, "tensor": 2})


def test_batch_sharding_roundtrip():
    import jax

    from ray_tpu.parallel import batch_sharding, make_mesh

    mesh = make_mesh({"data": 8})
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    xs = jax.device_put(x, batch_sharding(mesh))
    assert len(xs.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(xs), x)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    import jax

    from ray_tpu.parallel import full_attention, make_mesh, ring_attention_sharded

    mesh = make_mesh({"sequence": 8})
    B, T, H, D = 2, 32, 4, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)

    ring = ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_mixed_mesh():
    """data x sequence mesh: batch and sequence both sharded."""
    from ray_tpu.parallel import full_attention, make_mesh, ring_attention_sharded

    mesh = make_mesh({"data": 2, "sequence": 4})
    B, T, H, D = 4, 16, 2, 8
    rng = np.random.RandomState(1)
    q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    from ray_tpu.parallel import full_attention, make_mesh, ulysses_attention_sharded

    mesh = make_mesh({"sequence": 8})
    B, T, H, D = 2, 32, 8, 16  # H divisible by 8
    rng = np.random.RandomState(2)
    q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))
    out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_grad():
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import full_attention, make_mesh, ring_attention_sharded

    import jax as _jax

    mesh = make_mesh({"sequence": 4}, devices=_jax.devices()[:4])
    B, T, H, D = 1, 16, 2, 8
    rng = np.random.RandomState(3)
    q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_fsdp_leaf_sharding():
    import jax

    from ray_tpu.parallel import fsdp_sharding_for_leaf, make_mesh

    mesh = make_mesh({"fsdp": 8})
    w = np.zeros((128, 64), dtype=np.float32)
    s = fsdp_sharding_for_leaf(mesh, w)
    ws = jax.device_put(w, s)
    assert len(ws.sharding.device_set) == 8
    # scalar falls back to replication
    b = np.zeros((), dtype=np.float32)
    s2 = fsdp_sharding_for_leaf(mesh, b)
    jax.device_put(b, s2)
