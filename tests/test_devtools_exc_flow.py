"""Fixtures for the exception-propagation pass (exc_flow).

Each rule gets positive fixtures (must flag) and negative fixtures (the
clean idiom must stay quiet) over throwaway trees whose file layout maps
onto the service topology (``_private/gcs.py`` -> gcs, ...). Fixture
handlers register real ``wire.py`` method names so the schema facts the
rules consume (``errors=``, retry class, dedup key) are the shipped ones.
Also covered: the retry-class cross-checks (SAFE-with-mutation fires,
DEDUP-after-key-check clean), ack-before-persist in both orders, the
``# exc-flow:`` waiver family + stale-suppression audit, both sides of
the ``swallow_cancel`` mutation gate, the shared per-file inventory
cache, and the repo-clean / wire-doc-current acceptance pins.
"""

import os
import textwrap

import pytest

from ray_tpu._private import wire
from ray_tpu.devtools import aio_lint, exc_flow, lint, rpc_check


def _rules(findings):
    return {f.rule for f in findings}


def _tree(tmp_path, sources):
    """Write {relpath: source} under tmp_path; returns check() paths."""
    for name, src in sources.items():
        dest = tmp_path / name
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_text(textwrap.dedent(src))
    return [str(tmp_path)]


# ---------------------------------------------------------------------------
# error-wire-undeclared
# ---------------------------------------------------------------------------


def test_undeclared_direct_raise(tmp_path):
    # RegisterNode declares errors=(): a typed raise escaping the handler
    # crosses the wire untyped and must be flagged.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("RegisterNode", self._register_node)

                async def _register_node(self, conn, p):
                    if p["node_id"] in self.dead:
                        raise WorkerCrashedError("node re-registered dead")
                    return {"ok": True}
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_UNDECLARED
    ]
    assert findings and "WorkerCrashedError" in findings[0].message


def test_undeclared_store_write_fact(tmp_path):
    # The replicated-store fact: store.put in a gcs-service file can raise
    # StaleLeaderError, interprocedurally through a persist helper.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("RegisterNode", self._register_node)

                async def _register_node(self, conn, p):
                    self.nodes[p["node_id"]] = p
                    self._persist_nodes()
                    return {"ok": True}

                def _persist_nodes(self):
                    self.store.put("nodes", self.nodes)
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_UNDECLARED
    ]
    assert findings and "StaleLeaderError" in findings[0].message


def test_undeclared_negative_declared_schema(tmp_path):
    # CreateActor declares StaleLeaderError: the same escape is clean.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("CreateActor", self._create_actor)

                async def _create_actor(self, conn, p):
                    self.store.put("actors", p["spec"])
                    return {"ok": True}
            """,
        },
    )
    assert exc_flow.RULE_UNDECLARED not in _rules(exc_flow.check(paths))


def test_undeclared_negative_caught_raise(tmp_path):
    # A raise caught by a matching clause (not re-raised) does not escape.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("RegisterNode", self._register_node)

                async def _register_node(self, conn, p):
                    try:
                        self._validate(p)
                    except ObjectLostError:
                        return {"ok": False}
                    return {"ok": True}

                def _validate(self, p):
                    raise ObjectLostError(p["node_id"])
            """,
        },
    )
    assert exc_flow.RULE_UNDECLARED not in _rules(exc_flow.check(paths))


# ---------------------------------------------------------------------------
# swallowed-control-error
# ---------------------------------------------------------------------------


def test_swallow_cancelled_bare_except(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/worker_main.py": """
            class Worker:
                async def teardown_guard(self):
                    try:
                        await self.drain()
                    except BaseException:
                        return None
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_SWALLOW
    ]
    assert findings and "CancelledError" in findings[0].message


def test_swallow_negative_except_exception_misses_cancel(tmp_path):
    # Python >= 3.8: CancelledError subclasses BaseException, so `except
    # Exception` around an await swallows nothing control-flow.
    paths = _tree(
        tmp_path,
        {
            "_private/worker_main.py": """
            class Worker:
                async def teardown_guard(self):
                    try:
                        await self.drain()
                    except Exception:
                        return None
            """,
        },
    )
    assert exc_flow.RULE_SWALLOW not in _rules(exc_flow.check(paths))


def test_swallow_negative_reraise(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/worker_main.py": """
            class Worker:
                async def teardown_guard(self):
                    try:
                        await self.drain()
                    except BaseException:
                        self.log()
                        raise
            """,
        },
    )
    assert exc_flow.RULE_SWALLOW not in _rules(exc_flow.check(paths))


def test_swallow_typed_flow_on_handler_path(tmp_path):
    # CreateActor declares StaleLeaderError, so the nested RPC can re-raise
    # it; the broad except on the handler path eats the fencing signal.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("RegisterWorker", self._register_worker)

                async def _register_worker(self, conn, p):
                    try:
                        await self.gcs.call("CreateActor", {"spec": p})
                    except Exception:
                        pass
                    return {"ok": True}
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_SWALLOW
    ]
    assert findings and "StaleLeaderError" in findings[0].message


def test_swallow_negative_dedicated_clause_first(tmp_path):
    # An earlier dedicated clause that re-raises the control error makes
    # the trailing broad clause legitimate.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("RegisterWorker", self._register_worker)

                async def _register_worker(self, conn, p):
                    try:
                        await self.gcs.call("CreateActor", {"spec": p})
                    except StaleLeaderError:
                        raise
                    except Exception:
                        pass
                    return {"ok": True}
            """,
        },
    )
    assert exc_flow.RULE_SWALLOW not in _rules(exc_flow.check(paths))


# ---------------------------------------------------------------------------
# retry-unsafe-mutation: retry-class cross-checks
# ---------------------------------------------------------------------------


def test_retry_safe_with_list_append_fires(tmp_path):
    # ObjSeal is RETRY_SAFE; an append in a closure helper double-applies
    # on a lost-reply retry.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ObjSeal", self._obj_seal)

                async def _obj_seal(self, conn, p):
                    self._log_seal(p["oid"])
                    return {"ok": True}

                def _log_seal(self, oid):
                    self.seal_log.append(oid)
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_RETRY
    ]
    assert findings and "seal_log.append" in findings[0].message


def test_retry_safe_counter_increment_fires(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ObjSeal", self._obj_seal)

                async def _obj_seal(self, conn, p):
                    self.sealed_count += 1
                    return {"ok": True}
            """,
        },
    )
    assert exc_flow.RULE_RETRY in _rules(exc_flow.check(paths))


def test_retry_safe_negative_keyed_and_idempotent(tmp_path):
    # Keyed dict writes, set.add, and observability counters are all
    # idempotent or exempt under re-delivery.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ObjSeal", self._obj_seal)

                async def _obj_seal(self, conn, p):
                    self.sealed[p["oid"]] = True
                    self.seen.add(p["oid"])
                    self.stats["seals"] += 1
                    return {"ok": True}
            """,
        },
    )
    assert exc_flow.RULE_RETRY not in _rules(exc_flow.check(paths))


def test_retry_dedup_mutation_before_key_check_fires(tmp_path):
    # RequestWorkerLease is RETRY_DEDUP on lease_id: state mutated before
    # the first read of the dedup key double-applies on re-delivery.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("RequestWorkerLease", self._lease)

                async def _lease(self, conn, p):
                    self.grant_audit.append(p)
                    lease_id = p["lease_id"]
                    if lease_id in self.ledger:
                        return self.ledger[lease_id]
                    return {"granted": True}
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_RETRY
    ]
    assert findings and "lease_id" in findings[0].message


def test_retry_dedup_negative_mutation_after_key_check(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("RequestWorkerLease", self._lease)

                async def _lease(self, conn, p):
                    lease_id = p["lease_id"]
                    if lease_id in self.ledger:
                        return self.ledger[lease_id]
                    self.grant_audit.append(p)
                    return {"granted": True}
            """,
        },
    )
    assert exc_flow.RULE_RETRY not in _rules(exc_flow.check(paths))


# ---------------------------------------------------------------------------
# ack-before-persist, both orders
# ---------------------------------------------------------------------------


def test_ack_before_persist_fires(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("KVPut", self._kv_put)

                async def _kv_put(self, conn, p):
                    self.kv[p["key"]] = p["value"]
                    return {"ok": True}

                def _persist_kv(self):
                    self.store.put("kv", self.kv)
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_ACK
    ]
    assert findings and "kv" in findings[0].message


def test_persist_before_ack_clean(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("KVPut", self._kv_put)

                async def _kv_put(self, conn, p):
                    self.kv[p["key"]] = p["value"]
                    self._persist_kv()
                    return {"ok": True}

                def _persist_kv(self):
                    self.store.put("kv", self.kv)
            """,
        },
    )
    assert exc_flow.RULE_ACK not in _rules(exc_flow.check(paths))


def test_waiter_ack_before_persist_fires(tmp_path):
    # fut.set_result is externally visible the moment it runs — it counts
    # as an ack even in a non-handler helper (the _fail_actor shape).
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                def _fail(self, actor, fut):
                    actor.state = "DEAD"
                    fut.set_result({"actor": actor.actor_id})
                    self._persist_actor(actor)
            """,
        },
    )
    findings = [
        f for f in exc_flow.check(paths) if f.rule == exc_flow.RULE_ACK
    ]
    assert findings and "set_result" in findings[0].message


def test_helper_return_is_not_an_ack(tmp_path):
    # A non-handler helper returning a value to the scheduler loop is not
    # a wire reply (the _try_place_actor shape).
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            class Gcs:
                async def _try_place(self, actor, node):
                    actor.node_id = node.node_id
                    return True
            """,
        },
    )
    assert exc_flow.RULE_ACK not in _rules(exc_flow.check(paths))


# ---------------------------------------------------------------------------
# suppression + the stale-suppression audit for the exc-flow family
# ---------------------------------------------------------------------------


def test_suppression_masks_finding(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ObjSeal", self._obj_seal)

                async def _obj_seal(self, conn, p):
                    # guarded upstream by a keyed membership check
                    self.seal_log.append(p["oid"])  # exc-flow: disable=retry-unsafe-mutation
                    return {"ok": True}
            """,
        },
    )
    assert exc_flow.RULE_RETRY not in _rules(exc_flow.check(paths))
    raw = exc_flow.check(paths, apply_suppressions=False)
    assert exc_flow.RULE_RETRY in _rules(raw)
    # ...and the audit sees the waiver as live, not stale.
    audit = lint.audit_suppressions(paths)
    assert [f for f in audit if f.rule == lint.RULE_STALE] == []


def test_stale_exc_flow_suppression_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        "x = 1  # exc-flow: disable=ack-before-persist\n"
    )
    findings = lint.audit_suppressions([str(tmp_path)])
    assert [f.rule for f in findings] == [lint.RULE_STALE]


# ---------------------------------------------------------------------------
# mutation gate, both sides
# ---------------------------------------------------------------------------


def test_mutation_seeds_detectable_swallow():
    findings = exc_flow.check(mutate="swallow_cancel")
    swallows = [f for f in findings if f.rule == exc_flow.RULE_SWALLOW]
    assert swallows, "seeded CancelledError swallow must be detected"
    assert any("<mutant>" in f.path for f in swallows)


def test_mutation_gate_cli_passes_on_mutant(capsys):
    assert (
        exc_flow.main(["--mutate", "swallow_cancel", "--expect-violation"])
        == 0
    )
    assert "mutation detected" in capsys.readouterr().out


def test_expect_violation_fails_on_clean_tree(capsys):
    # The other side of the gate: with no seeded defect the clean tree
    # must NOT satisfy --expect-violation (a toothless pass would).
    assert exc_flow.main(["--expect-violation"]) == 1


# ---------------------------------------------------------------------------
# wire.py errors= declarations
# ---------------------------------------------------------------------------


def test_unknown_error_name_rejected():
    with pytest.raises(ValueError, match="unknown error name"):
        wire._s(["k"], errors=("NoSuchError",))


def test_every_schema_declares_within_taxonomy():
    for method, schema in wire.SCHEMAS.items():
        assert set(schema.errors) <= wire.KNOWN_ERRORS, method


def test_durable_gcs_writers_declare_stale_leader():
    # The write-through methods whose handlers reach the replicated store.
    for method in (
        "CreateActor",
        "ReportActorReady",
        "ReportWorkerDied",
        "KillActor",
        "KVPut",
    ):
        assert "StaleLeaderError" in wire.SCHEMAS[method].errors, method


# ---------------------------------------------------------------------------
# shared per-file inventory cache + lint-gate integration
# ---------------------------------------------------------------------------


def test_inventory_cache_hits_and_invalidates(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("async def f(conn):\n    await conn.call('KVGet', {})\n")
    t1, f1 = rpc_check._scan_file(str(p))
    t2, f2 = rpc_check._scan_file(str(p))
    assert t1 is t2 and f1 is f2  # cache hit: same parse, same fragment
    p.write_text("async def f(conn):\n    await conn.call('KVPut', {})\n")
    os.utime(p, (1, 1))  # force a distinct mtime signature
    t3, f3 = rpc_check._scan_file(str(p))
    assert t3 is not t1
    assert {c.method for c in f3.calls} == {"KVPut"}


def test_lint_gate_times_exc_flow(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    _findings, timings = lint.run_timed([str(tmp_path)])
    assert "exc-flow" in {name for name, _ in timings}


# ---------------------------------------------------------------------------
# acceptance: the shipped tree and its committed wire doc
# ---------------------------------------------------------------------------


def test_repo_is_exc_flow_clean():
    assert [str(f) for f in exc_flow.check()] == []


def test_repo_wire_doc_is_current():
    root = os.path.dirname(aio_lint._default_root())
    doc = os.path.join(root, "docs", "wire_protocol.md")
    with open(doc, "r", encoding="utf-8") as fh:
        assert fh.read() == rpc_check.markdown_table() + "\n"


def test_wire_doc_has_errors_column():
    assert "| Errors |" in rpc_check.markdown_table()
