"""GCS store backends (gcs_store.py): WAL framing/recovery semantics —
torn-tail truncation, CRC rejection, group commit, snapshot compaction,
crash-vs-close — and op-sequence parity across all three backends."""

import asyncio
import os
import struct
import zlib

import pytest

from ray_tpu._private import gcs_store
from ray_tpu._private.gcs_store import (
    InMemoryStoreClient,
    SqliteStoreClient,
    WalStoreClient,
    inject_torn_tail,
    make_store,
)


@pytest.fixture
def wal_path(tmp_path):
    return str(tmp_path / "gcs.wal")


def test_wal_basic_roundtrip(wal_path):
    s = WalStoreClient(wal_path)
    s.put("kv", "a", b"1")
    s.put("kv", "b", b"2")
    s.put("kv", "a", b"3")  # overwrite
    s.delete("kv", "b")
    assert s.get("kv", "a") == b"3"
    assert s.get("kv", "b") is None
    s.close()
    s2 = WalStoreClient(wal_path)
    assert s2.get_all("kv") == {"a": b"3"}
    s2.close()


def test_wal_torn_tail_truncated(wal_path):
    s = WalStoreClient(wal_path)
    s.put("actors", "x", b"alive")
    s.crash()
    size_before = os.path.getsize(wal_path)
    assert inject_torn_tail(wal_path)
    assert os.path.getsize(wal_path) > size_before
    s2 = WalStoreClient(wal_path)
    # The torn frame is truncated away; every intact record survives.
    assert s2.get("actors", "x") == b"alive"
    s2.close()
    assert os.path.getsize(wal_path) == size_before


def test_wal_crc_rejection(wal_path):
    s = WalStoreClient(wal_path)
    s.put("kv", "good", b"v")
    s.put("kv", "bad", b"w")
    s.close()
    # Flip a byte inside the LAST record's body: its CRC no longer matches,
    # so recovery must stop before it (and keep everything earlier).
    with open(wal_path, "r+b") as f:
        data = f.read()
        f.seek(len(data) - 2)
        f.write(bytes([data[-2] ^ 0xFF]))
    s2 = WalStoreClient(wal_path)
    assert s2.get("kv", "good") == b"v"
    assert s2.get("kv", "bad") is None
    s2.close()


def test_wal_group_commit_one_write_per_tick(wal_path):
    s = WalStoreClient(wal_path)

    async def burst():
        for i in range(256):
            s.put("kv", f"k{i}", b"v" * 64)
        # Buffered until the scheduled call_soon flush runs.
        assert s._pending
        await asyncio.sleep(0)
        assert not s._pending

    asyncio.run(burst())
    s.crash()
    s2 = WalStoreClient(wal_path)
    assert len(s2.get_all("kv")) == 256
    s2.close()


def test_wal_compaction_preserves_state(wal_path):
    s = WalStoreClient(wal_path, compact_bytes=2048)
    for i in range(100):
        s.put("kv", f"k{i % 10}", (b"v%d" % i) * 30)
    s.delete("kv", "k0")
    s.close()
    # Log stayed bounded (~one snapshot, not 100 records)...
    assert os.path.getsize(wal_path) < 20 * 2048
    # ...and replays to the same state.
    s2 = WalStoreClient(wal_path)
    kv = s2.get_all("kv")
    assert set(kv) == {f"k{i}" for i in range(1, 10)}
    assert kv["k9"] == b"v99" * 30
    s2.close()


def test_wal_crash_keeps_acknowledged_state(wal_path):
    s = WalStoreClient(wal_path)
    for i in range(32):
        s.put("jobs", f"j{i}", b"running")
    s.crash()  # no fsync, no checkpoint — but the tail reaches the OS
    s2 = WalStoreClient(wal_path)
    assert len(s2.get_all("jobs")) == 32
    s2.close()


def test_wal_sync_always_flushes_inline(wal_path):
    s = WalStoreClient(wal_path, sync="always")

    async def one():
        s.put("kv", "k", b"v")
        assert not s._pending  # no group-commit buffering

    asyncio.run(one())
    s.crash()
    assert WalStoreClient(wal_path).get("kv", "k") == b"v"


def test_wal_refuses_sqlite_file(tmp_path):
    p = str(tmp_path / "gcs.db")
    sq = SqliteStoreClient(p)
    sq.put("kv", "k", b"v")
    sq.close()
    with pytest.raises(ValueError):
        WalStoreClient(p)
    assert not inject_torn_tail(p)
    # The refused open must not have damaged the sqlite file.
    sq2 = SqliteStoreClient(p)
    assert sq2.get("kv", "k") == b"v"
    sq2.close()


def test_sqlite_close_checkpoints_wal(tmp_path):
    p = str(tmp_path / "gcs.db")
    s = SqliteStoreClient(p)
    s.put("kv", "k", b"v")
    assert os.path.getsize(p + "-wal") > 0
    s.close()
    # Graceful close folds the -wal file into the main db.
    assert (
        not os.path.exists(p + "-wal") or os.path.getsize(p + "-wal") == 0
    )
    s2 = SqliteStoreClient(p)
    assert s2.get("kv", "k") == b"v"
    s2.close()


def test_sqlite_crash_leaves_wal_replayable(tmp_path):
    p = str(tmp_path / "gcs.db")
    s = SqliteStoreClient(p)
    s.put("kv", "k", b"v")
    s.crash()  # no checkpoint: -wal left behind
    s2 = SqliteStoreClient(p)
    assert s2.get("kv", "k") == b"v"  # sqlite replays its WAL on open
    s2.close()


_OPS = [
    ("put", "kv", "a", b"1"),
    ("put", "actors", "x", b"spec"),
    ("put", "kv", "a", b"2"),
    ("put", "kv", "b", b"3"),
    ("del", "kv", "a", None),
    ("put", "named", "all", b"{}"),
    ("del", "kv", "missing", None),
    ("put", "pgs", "pg1", b"pending"),
    ("put", "pgs", "pg1", b"created"),
]


def _apply(store):
    for op, table, key, value in _OPS:
        if op == "put":
            store.put(table, key, value)
        else:
            store.delete(table, key)


def test_backend_parity(tmp_path):
    """Same op sequence -> same get_all across all three backends, both
    live and (for the durable two) after a reopen."""
    stores = {
        "memory": InMemoryStoreClient(),
        "sqlite": SqliteStoreClient(str(tmp_path / "p.db")),
        "wal": WalStoreClient(str(tmp_path / "p.wal")),
    }
    tables = ("kv", "actors", "named", "jobs", "pgs")
    for s in stores.values():
        _apply(s)
    expect = {t: stores["memory"].get_all(t) for t in tables}
    for name, s in stores.items():
        assert {t: s.get_all(t) for t in tables} == expect, name
        s.close()
    for name, reopened in (
        ("sqlite", SqliteStoreClient(str(tmp_path / "p.db"))),
        ("wal", WalStoreClient(str(tmp_path / "p.wal"))),
    ):
        assert {t: reopened.get_all(t) for t in tables} == expect, name
        reopened.close()


def test_make_store_backend_selection(tmp_path, monkeypatch):
    from ray_tpu._private.common import config

    assert isinstance(make_store(None), InMemoryStoreClient)
    assert isinstance(
        make_store(str(tmp_path / "a.wal")), WalStoreClient
    )  # default knob = wal
    assert isinstance(
        make_store(str(tmp_path / "b.db"), backend="sqlite"), SqliteStoreClient
    )
    assert isinstance(
        make_store(str(tmp_path / "c"), backend="memory"), InMemoryStoreClient
    )
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST_BACKEND", "sqlite")
    config.refresh()
    try:
        assert isinstance(
            make_store(str(tmp_path / "d.db")), SqliteStoreClient
        )
        with pytest.raises(ValueError):
            make_store(str(tmp_path / "e"), backend="bogus")
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_PERSIST_BACKEND")
        config.refresh()


# -- replicated store (HA): log shipping, fencing, machine loss --------------


@pytest.fixture
def repl_path(tmp_path):
    return str(tmp_path / "gcs.wal")


def test_replicated_ships_to_followers(repl_path):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        follower_paths,
    )

    s = ReplicatedStoreClient(repl_path)
    s.put("kv", "a", b"1")
    s.put("actors", "x", b"alive")
    s.flush()
    s.close()
    # Every member of the replication group holds the full acknowledged
    # state, independently replayable from its own file.
    for member in [repl_path] + follower_paths(repl_path):
        with open(member, "rb") as f:
            tables, _, _, _ = gcs_store._parse_replicated(f.read())
        assert tables["kv"]["a"] == b"1", member
        assert tables["actors"]["x"] == b"alive", member


def test_replicated_survives_primary_host_loss(repl_path):
    from ray_tpu._private.gcs_store import ReplicatedStoreClient, drop_host

    s = ReplicatedStoreClient(repl_path, term=1)
    s.put("kv", "k", b"v")
    s.flush()
    s.crash()  # process death: no graceful close
    drop_host(repl_path)  # the machine (and its log member) is gone
    # A successor opens the group, adopts the surviving follower's state,
    # and re-creates the lost member via snapshot catch-up.
    s2 = ReplicatedStoreClient(repl_path, term=2)
    assert s2.get("kv", "k") == b"v"
    assert s2.term == 2
    s2.put("kv", "k2", b"v2")
    s2.flush()
    s2.close()
    assert os.path.exists(repl_path)  # re-created by catch-up


def test_replicated_fences_stale_writer(repl_path):
    from ray_tpu._private.gcs_store import ReplicatedStoreClient
    from ray_tpu._private.rpc import StaleLeaderError

    old = ReplicatedStoreClient(repl_path, term=1)
    old.put("kv", "pre", b"1")
    old.flush()
    new = ReplicatedStoreClient(repl_path, term=2)
    # The deposed leader's next acknowledged write must be rejected, not
    # silently applied (split-brain prevention).
    with pytest.raises(StaleLeaderError):
        old.put("kv", "post", b"2")
        old.flush()
    new.flush()
    assert new.get("kv", "pre") == b"1"
    assert new.get("kv", "post") is None
    old.close()
    new.close()


def test_replicated_open_below_fence_rejected(repl_path):
    from ray_tpu._private.gcs_store import ReplicatedStoreClient
    from ray_tpu._private.rpc import StaleLeaderError

    s = ReplicatedStoreClient(repl_path, term=3)
    s.put("kv", "a", b"1")
    s.flush()
    with pytest.raises(StaleLeaderError):
        ReplicatedStoreClient(repl_path, term=2)
    s.close()


def test_replicated_fence_survives_restart(repl_path):
    from ray_tpu._private.gcs_store import ReplicatedStoreClient
    from ray_tpu._private.rpc import StaleLeaderError

    s = ReplicatedStoreClient(repl_path, term=5)
    s.put("kv", "a", b"1")
    s.flush()
    s.close()
    # The fence is durable: after every in-process handle is gone, a
    # reopened group still rejects terms below the highest ever accepted.
    with pytest.raises(StaleLeaderError):
        ReplicatedStoreClient(repl_path, term=4)
    s2 = ReplicatedStoreClient(repl_path, term=5)
    assert s2.get("kv", "a") == b"1"
    s2.close()


def test_replicated_crash_keeps_acknowledged_state(repl_path):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        follower_paths,
    )

    s = ReplicatedStoreClient(repl_path, term=1)
    for i in range(10):
        s.put("kv", f"k{i}", str(i).encode())
    s.crash()  # pending group-commit buffer lands on every member
    for member in [repl_path] + follower_paths(repl_path):
        with open(member, "rb") as f:
            tables, _, _, _ = gcs_store._parse_replicated(f.read())
        for i in range(10):
            assert tables["kv"][f"k{i}"] == str(i).encode(), member


def test_replica_tailer_follows_and_survives_compaction(repl_path):
    from ray_tpu._private.gcs_store import (
        ReplicaTailer,
        ReplicatedStoreClient,
        follower_paths,
    )

    s = ReplicatedStoreClient(repl_path, term=1, compact_bytes=2048)
    tailer = ReplicaTailer(follower_paths(repl_path)[0])
    s.put("kv", "a", b"1")
    s.flush()
    tailer.poll()
    assert tailer.get("kv", "a") == b"1"
    assert tailer.term == 1
    # Push the log past the compaction threshold: the member file is
    # rewritten in place and the tailer must detect the new inode/shorter
    # file and replay from scratch rather than tailing garbage.
    for i in range(200):
        s.put("kv", "big", b"x" * 64 + str(i).encode())
    s.flush()
    s.put("kv", "last", b"z")
    s.flush()
    tailer.poll()
    assert tailer.get("kv", "last") == b"z"
    assert tailer.get("kv", "a") == b"1"
    s.close()


def test_make_store_replicated_selection(tmp_path, monkeypatch):
    from ray_tpu._private.common import config
    from ray_tpu._private.gcs_store import ReplicatedStoreClient

    s = make_store(str(tmp_path / "r.wal"), backend="replicated", term=1)
    assert isinstance(s, ReplicatedStoreClient)
    assert s.term == 1
    s.close()
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST_BACKEND", "replicated")
    config.refresh()
    try:
        s = make_store(str(tmp_path / "r2.wal"))
        assert isinstance(s, ReplicatedStoreClient)
        s.close()
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_PERSIST_BACKEND")
        config.refresh()


# ---------------------------------------------------------------------------
# Quorum replication (>= 3-member groups)
# ---------------------------------------------------------------------------


@pytest.fixture
def quorum_heal():
    """Partitions are module-global fault injection; never leak them."""
    yield
    gcs_store.heal_all_partitions()


def _member_state(path):
    with open(path, "rb") as f:
        return gcs_store._parse_replicated(f.read())


def test_quorum_acks_at_exact_majority(repl_path, quorum_heal):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        follower_paths,
        partition_host,
    )

    fols = follower_paths(repl_path, 2)
    s = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    assert s.quorum == 2  # ceil((3+1)/2)... floor(3/2)+1: 2 of 3
    partition_host(fols[1])
    commits = []
    s.commit_listener = lambda seq, n_ops: commits.append((seq, n_ops))
    s.put("kv", "a", b"1")
    s.flush()
    # Exactly the majority (leader + one follower) is reachable: the
    # commit must ack and the leader must stay un-fenced.
    assert commits == [(1, 1)]
    assert not s.fenced
    assert s.get("kv", "a") == b"1"
    tables, _, _, _ = _member_state(fols[0])
    assert tables["kv"]["a"] == b"1"
    # The dark minority member holds nothing and shows up as lag.
    tables, _, _, _ = _member_state(fols[1])
    assert "a" not in tables.get("kv", {})
    assert s.replica_lag()[os.path.basename(fols[1])] == 1
    assert s.replica_lag()[os.path.basename(fols[0])] == 0
    s.close()


def test_quorum_loss_demotes_leader_without_acking(repl_path, quorum_heal):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        follower_paths,
        partition_host,
    )
    from ray_tpu._private.rpc import StaleLeaderError

    fols = follower_paths(repl_path, 2)
    s = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    commits = []
    s.commit_listener = lambda seq, n_ops: commits.append(seq)
    partition_host(fols[0])
    partition_host(fols[1])
    s.put("kv", "a", b"1")
    s.flush()
    # Every follower is unreachable: no majority can hold the write, so
    # the leader demotes itself rather than acking it.
    assert commits == []
    assert s.fenced
    with pytest.raises(StaleLeaderError):
        s.put("kv", "b", b"2")
        s.flush()
    s.close()


def test_quorum_laggard_catches_up_via_snapshot(repl_path, quorum_heal):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        follower_paths,
        heal_host,
        partition_host,
    )

    fols = follower_paths(repl_path, 2)
    s = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    partition_host(fols[1])
    for i in range(5):
        s.put("kv", f"k{i}", str(i).encode())
        s.flush()
    assert s.replica_lag()[os.path.basename(fols[1])] == 5
    heal_host(fols[1])
    # The next commit notices the healed member is behind the stream and
    # ships the full state as one snapshot frame on its lane.
    s.put("kv", "post", b"p")
    s.flush()
    s.wait_replication()
    tables, term, seq, _ = _member_state(fols[1])
    assert term == 1 and seq == s.seq
    assert tables["kv"]["post"] == b"p"
    for i in range(5):
        assert tables["kv"][f"k{i}"] == str(i).encode()
    assert s.replica_lag()[os.path.basename(fols[1])] == 0
    s.close()


def test_quorum_freshest_election_beats_file_freshest(repl_path, quorum_heal, tmp_path):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        drop_host,
        follower_paths,
        heal_host,
        partition_host,
    )

    fols = follower_paths(repl_path, 2)
    # Phase 1: 6KB of overwrites of one key land on every member.
    s1 = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    for i in range(4):
        s1.put("kv", "x", bytes([65 + i]) * 1500)
        s1.flush()
    s1.close()
    # Phase 2: fol0 partitions; the new term compacts the survivors down
    # to a ~1.5KB snapshot and commits a fresh key on the majority.
    partition_host(fols[0])
    s2 = ReplicatedStoreClient(
        repl_path, followers=fols, term=2, compact_bytes=2048, sync="off"
    )
    s2.put("kv", "fresh", b"F")
    s2.flush()
    s2.wait_replication()
    s2.crash()
    drop_host(repl_path)
    heal_host(fols[0])
    # fol0 has the LARGEST file (the long un-compacted term-1 log) but the
    # LOWEST (term, seq); fol1 is byte-small but quorum-fresh. Election
    # must adopt fol1 — a file-size/mtime heuristic would resurrect stale
    # state and lose the acked "fresh" key.
    assert os.path.getsize(fols[0]) > os.path.getsize(fols[1])
    s3 = ReplicatedStoreClient(repl_path, followers=fols, term=3, sync="off")
    assert s3.get("kv", "fresh") == b"F"
    assert s3.get("kv", "x") == b"D" * 1500
    s3.close()


def test_quorum_lost_error_until_majority_heals(repl_path, quorum_heal):
    from ray_tpu._private.gcs_store import (
        QuorumLostError,
        ReplicatedStoreClient,
        follower_paths,
        heal_host,
        partition_host,
    )

    fols = follower_paths(repl_path, 2)
    s = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    s.put("kv", "a", b"1")
    s.flush()
    s.close()
    partition_host(fols[0])
    partition_host(fols[1])
    # Only the leader member is reachable (1 of 3): the election must
    # fail closed — it cannot prove it sees every possibly-acked write.
    with pytest.raises(QuorumLostError):
        ReplicatedStoreClient(repl_path, followers=fols, term=2, sync="off")
    heal_host(fols[0])
    s2 = ReplicatedStoreClient(repl_path, followers=fols, term=2, sync="off")
    assert s2.get("kv", "a") == b"1"
    s2.close()


def test_quorum_rejoin_gets_fence_bump(repl_path, quorum_heal):
    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        drop_host,
        follower_paths,
        heal_host,
        partition_host,
    )

    fols = follower_paths(repl_path, 2)
    s1 = ReplicatedStoreClient(repl_path, followers=fols, term=1, sync="off")
    partition_host(fols[1])
    s1.put("kv", "a", b"1")
    s1.flush()
    s1.crash()
    drop_host(repl_path)
    # Successor elects over the reachable majority while fol1 is dark...
    s2 = ReplicatedStoreClient(repl_path, followers=fols, term=2, sync="off")
    s2.put("kv", "b", b"2")
    s2.flush()
    # ...and fol1's rejoin rides the catch-up snapshot, which carries the
    # new term: the fence bump that locks out the dead term-1 leadership.
    heal_host(fols[1])
    s2.put("kv", "c", b"3")
    s2.flush()
    s2.wait_replication()
    tables, term, _, _ = _member_state(fols[1])
    assert term == 2
    assert tables["kv"] == {"a": b"1", "b": b"2", "c": b"3"}
    s2.close()


def test_quorum_stale_catchup_snapshot_rejected(repl_path, tmp_path, quorum_heal):
    from ray_tpu._private.gcs_store import ReplicatedStoreClient, follower_paths
    from ray_tpu._private.rpc import StaleLeaderError

    # Regression (found by the interleaving explorer): a deposed leader
    # whose follower moved on sees it as a "laggard" and ships a catch-up
    # snapshot of its own stale state. reset_with must fence that exactly
    # like append, or the old term overwrites the new term's log wholesale.
    shared = follower_paths(repl_path, 1)[0]
    old = ReplicatedStoreClient(repl_path, followers=[shared], term=1, sync="off")
    old.put("kv", "old", b"1")
    old.flush()

    async def race():
        # Under a running loop the put's group commit is deferred to a
        # call_soon tick, so the promotion lands between the (passing)
        # put-side fence check and the flush — the explorer's schedule.
        old.put("kv", "late", b"3")
        new = ReplicatedStoreClient(
            str(tmp_path / "b.wal"), followers=[shared], term=2
        )
        new.put("kv", "new", b"2")
        new.flush()
        await asyncio.sleep(0)  # old's deferred flush fires here
        return new

    new = asyncio.run(race())
    # The deposed leader saw the follower's seq ahead of its stream,
    # shipped its stale state as a catch-up snapshot, and was rejected.
    assert old.fenced
    with pytest.raises(StaleLeaderError):
        old.put("kv", "even-later", b"4")
    tables, term, _, _ = _member_state(shared)
    assert term == 2
    assert tables["kv"].get("new") == b"2"
    assert "late" not in tables["kv"]
    old.close()
    new.close()
