"""Compiled DAG + workflow + runtime_env + metrics + autoscaler tests
(analog of python/ray/dag/tests, workflow/tests, runtime_env tests)."""

import os
import time

import numpy as np
import pytest


# -- compiled DAG -------------------------------------------------------------


def test_channel_roundtrip():
    from ray_tpu.dag.channel import Channel

    ch = Channel("rtdag_test_ch1", 1 << 20, create=True)
    try:
        reader = Channel("rtdag_test_ch1", 1 << 20)
        ch.write({"x": 1})
        assert reader.read(timeout=5) == {"x": 1}
        ch.write([1, 2, 3])
        assert reader.read(timeout=5) == [1, 2, 3]
        with pytest.raises(TimeoutError):
            reader.read(timeout=0.2)  # nothing new
    finally:
        ch.close(unlink=True)


def test_compiled_dag_linear(ray_start_regular):
    import ray_tpu
    from ray_tpu import dag

    @ray_tpu.remote
    class Adder:
        def add(self, x):
            return x + 1

    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return x * 2

    a, b = Adder.remote(), Doubler.remote()
    with dag.InputNode() as inp:
        graph = b.double.bind(a.add.bind(inp))
    compiled = graph.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i).get() == (i + 1) * 2
    finally:
        compiled.teardown()


def test_tensor_channel_roundtrip():
    from ray_tpu.dag.tensor_channel import TensorChannel

    ch = TensorChannel("rtdag_test_tch1", 1 << 22, create=True)
    try:
        reader = TensorChannel("rtdag_test_tch1", 1 << 22)
        arr = np.arange(1 << 18, dtype=np.float32).reshape(512, 512)
        ch.write(arr)
        out = reader.read(timeout=5)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        assert np.array_equal(out, arr)
        # Non-array values still round-trip (pickle fallback: STOP sentinel,
        # error tuples).
        ch.write({"k": [1, 2]})
        assert reader.read(timeout=5) == {"k": [1, 2]}
        ch.write(("ok", np.ones(4, dtype=np.int64)))
        kind, val = reader.read(timeout=5)
        assert kind == "ok" and np.array_equal(val, np.ones(4, dtype=np.int64))
        # 0-d arrays keep scalar shape (ascontiguousarray promotes to (1,)
        # internally; the original shape must win on the wire).
        ch.write(np.array(3.5))
        z = reader.read(timeout=5)
        assert z.shape == () and float(z) == 3.5
        # A plain 2-tuple headed by an array must not trip the wire-tuple
        # check (elementwise == on arrays).
        ch.write((np.arange(3), "tail"))
        t = reader.read(timeout=5)
        assert np.array_equal(t[0], np.arange(3)) and t[1] == "tail"
    finally:
        ch.close(unlink=True)


def test_compiled_dag_tensor_transport(ray_start_regular):
    """Arrays move between DAG actors through array-native channels
    (reference analog: with_tensor_transport -> NCCL/typed channels)."""
    import ray_tpu
    from ray_tpu import dag

    @ray_tpu.remote
    class Producer:
        def make(self, seed):
            return np.full((256, 256), float(seed), dtype=np.float32)

    @ray_tpu.remote
    class Consumer:
        def total(self, x):
            assert isinstance(x, np.ndarray) and x.dtype == np.float32
            return float(x.sum())

    p, c = Producer.remote(), Consumer.remote()
    with dag.InputNode() as inp:
        graph = c.total.bind(p.make.bind(inp).with_tensor_transport())
    compiled = graph.experimental_compile()
    try:
        for i in (1, 2, 3):
            assert compiled.execute(i).get() == 256 * 256 * i
    finally:
        compiled.teardown()


def test_ici_device_to_device_transfer():
    """The jitted ppermute hop moves one device's shard to another device's
    slot over the mesh fabric (ICI on real TPU; virtual CPU mesh here)."""
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)
    import jax
    import numpy as np_
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from ray_tpu.dag.tensor_channel import make_ici_transfer

    devices = np_.array(jax.devices()[:8])
    mesh = Mesh(devices, ("x",))
    hop = make_ici_transfer(mesh, "x", src=0, dst=3)
    arr = jax.device_put(
        np_.arange(32, dtype=np_.float32).reshape(8, 4),
        NamedSharding(mesh, P("x")),
    )
    out = hop(arr)
    host = np_.asarray(out)
    src_shard = np_.arange(32, dtype=np_.float32).reshape(8, 4)[0:1]
    # dst (row-block 3) now holds src's shard; untouched rows keep theirs.
    assert np_.array_equal(host[3:4], src_shard)
    assert np_.array_equal(host[1:2], np_.arange(32, dtype=np_.float32).reshape(8, 4)[1:2])


def test_compiled_dag_multi_output(ray_start_regular):
    import ray_tpu
    from ray_tpu import dag

    @ray_tpu.remote
    class Plus:
        def __init__(self, k):
            self.k = k

        def go(self, x):
            return x + self.k

    p1, p2 = Plus.remote(1), Plus.remote(2)
    with dag.InputNode() as inp:
        graph = dag.MultiOutputNode([p1.go.bind(inp), p2.go.bind(inp)])
    compiled = graph.experimental_compile()
    try:
        assert compiled.execute(10).get() == [11, 12]
    finally:
        compiled.teardown()


def test_compiled_dag_error_propagation(ray_start_regular):
    import ray_tpu
    from ray_tpu import dag

    @ray_tpu.remote
    class Boom:
        def go(self, x):
            if x == 3:
                raise ValueError("boom at 3")
            return x

    b = Boom.remote()
    with dag.InputNode() as inp:
        graph = b.go.bind(inp)
    compiled = graph.experimental_compile()
    try:
        assert compiled.execute(1).get() == 1
        with pytest.raises(ValueError, match="boom at 3"):
            compiled.execute(3).get()
        # The loop survives the error.
        assert compiled.execute(4).get() == 4
    finally:
        compiled.teardown()


# -- workflow -----------------------------------------------------------------


def test_workflow_run_and_resume(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu import workflow

    calls_file = tmp_path / "calls.txt"

    @ray_tpu.remote
    def add(a, b):
        with open(calls_file, "a") as f:
            f.write("x")
        return a + b

    node = add.bind(add.bind(1, 2), add.bind(3, 4))
    out = workflow.run(node, workflow_id="wf-test", storage=str(tmp_path))
    assert out == 10
    assert len(calls_file.read_text()) == 3
    assert workflow.get_output("wf-test", storage=str(tmp_path)) == 10
    meta = workflow.get_metadata("wf-test", storage=str(tmp_path))
    assert meta["status"] == "SUCCESSFUL"

    # Resume: everything checkpointed -> no re-execution.
    assert workflow.resume("wf-test", storage=str(tmp_path)) == 10
    assert len(calls_file.read_text()) == 3
    assert ("wf-test", "SUCCESSFUL") in workflow.list_all(str(tmp_path))


def test_workflow_failure_and_partial_resume(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu import workflow

    flag = tmp_path / "fail_once"
    flag.write_text("1")
    count_file = tmp_path / "count"
    count_file.write_text("")

    @ray_tpu.remote
    def step_a():
        with open(count_file, "a") as f:
            f.write("a")
        return 5

    @ray_tpu.remote
    def step_b(x, fail_path):
        if os.path.exists(fail_path):
            os.unlink(fail_path)
            raise RuntimeError("transient")
        return x * 2

    node = step_b.bind(step_a.bind(), str(flag))
    with pytest.raises(Exception):
        workflow.run(node, workflow_id="wf-fail", storage=str(tmp_path))
    assert workflow.get_metadata("wf-fail", storage=str(tmp_path))["status"] == "FAILED"
    # Resume skips step_a (checkpointed) and completes.
    assert workflow.resume("wf-fail", storage=str(tmp_path)) == 10
    assert count_file.read_text() == "a"


# -- runtime_env --------------------------------------------------------------


def test_runtime_env_env_vars(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    class EnvReader:
        def read(self, key):
            return os.environ.get(key)

    a = EnvReader.options(
        runtime_env={"env_vars": {"MY_RT_ENV": "hello42"}}
    ).remote()
    assert ray_tpu.get(a.read.remote("MY_RT_ENV")) == "hello42"


def test_runtime_env_working_dir(ray_start_regular, tmp_path):
    import ray_tpu

    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "my_rt_module.py").write_text("VALUE = 1234\n")

    @ray_tpu.remote
    class Importer:
        def go(self):
            import my_rt_module

            return my_rt_module.VALUE

    a = Importer.options(runtime_env={"working_dir": str(pkg)}).remote()
    assert ray_tpu.get(a.go.remote()) == 1234


# -- metrics ------------------------------------------------------------------


def test_metrics_render():
    from ray_tpu.util import metrics as M

    c = M.Counter("test_requests", "reqs", tag_keys=("route",))
    c.inc(2, tags={"route": "/a"})
    c.inc(1, tags={"route": "/b"})
    g = M.Gauge("test_temp", "", tag_keys=())
    g.set(42.5)
    h = M.Histogram("test_lat", "", boundaries=[1, 10], tag_keys=())
    h.observe(0.5)
    h.observe(5)
    h.observe(50)

    snap = M._collect_local()
    text = M.render_prometheus({"w1": snap})
    assert 'test_requests{route="/a"} 2.0' in text
    assert "test_temp 42.5" in text
    assert 'test_lat_bucket{le="1"} 1' in text
    assert 'test_lat_bucket{le="+Inf"} 3' in text
    assert "test_lat_count 3" in text


# -- autoscaler ---------------------------------------------------------------


def test_autoscaler_up_down(shutdown_only):
    import ray_tpu
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    ray_tpu.init(address=cluster.address)

    provider = FakeNodeProvider(
        cluster,
        node_types={
            "worker": {"resources": {"CPU": 2.0}, "min_workers": 0, "max_workers": 2}
        },
    )
    scaler = Autoscaler(
        provider,
        AutoscalerConfig(upscale_delay_s=0.2, idle_timeout_s=2.0),
    )

    # Saturate the 1-CPU head so leases queue up.
    @ray_tpu.remote
    def slow():
        time.sleep(4)
        return 1

    refs = [slow.options(num_cpus=1).remote() for _ in range(4)]
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not provider.non_terminated_nodes():
        scaler.update()
        time.sleep(0.3)
    assert provider.non_terminated_nodes(), "autoscaler never launched a node"

    assert ray_tpu.get(refs, timeout=60) == [1] * 4

    # After the work drains, idle nodes are reclaimed.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and provider.non_terminated_nodes():
        scaler.update()
        time.sleep(0.5)
    assert not provider.non_terminated_nodes(), "idle node was not terminated"
    cluster.shutdown()


def test_workflow_continuation_recursion(ray_start_regular, tmp_path):
    """A step returning workflow.continuation(...) recurses: the sub-DAG's
    steps checkpoint under the parent step's namespace (reference: workflow
    continuations in task_executor.py)."""
    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return workflow.continuation(fact.bind(n - 1, acc * n))

    out = workflow.run(
        fact.bind(5), workflow_id="wf-cont", storage=str(tmp_path)
    )
    assert out == 120
    steps = workflow.get_step_metadata("wf-cont", storage=str(tmp_path))
    # 5 fact evaluations: the root step + 4 chain links, each recorded
    # under the root's namespace (iterative tail-chain: no frames or
    # thread pools stack with recursion depth).
    fact_steps = [s for s in steps if "fact" in s]
    assert len(fact_steps) == 5
    chain = [s for s in fact_steps if "." in s]
    assert len(chain) == 4
    assert all(steps[s]["status"] == "SUCCESSFUL" for s in fact_steps)


def test_workflow_step_retries_and_catch(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu import workflow

    marker = tmp_path / "flaky_attempts"

    @ray_tpu.remote
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise ValueError(f"attempt {n} fails")
        return "ok"

    node = flaky.bind().options(max_retries=3)
    assert workflow.run(
        node, workflow_id="wf-retry", storage=str(tmp_path)
    ) == "ok"
    steps = workflow.get_step_metadata("wf-retry", storage=str(tmp_path))
    (sid,) = [s for s in steps if "flaky" in s]
    assert steps[sid]["attempts"] == 3
    assert steps[sid]["status"] == "SUCCESSFUL"

    @ray_tpu.remote
    def always_fails():
        raise RuntimeError("nope")

    result, err = workflow.run(
        always_fails.bind().options(catch_exceptions=True),
        workflow_id="wf-catch",
        storage=str(tmp_path),
    )
    assert result is None
    assert isinstance(err, Exception)


def test_workflow_parallel_fanout(ray_start_regular, tmp_path):
    """Independent branches execute concurrently (wave executor), and the
    join sees both checkpointed values."""
    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def slow(x):
        import time as _t

        _t.sleep(0.5)
        return x

    @ray_tpu.remote
    def join(a, b):
        return a + b

    # Warm two workers so spawn cost is outside the timed window.
    ray_tpu.get([slow.remote(0), slow.remote(0)])
    t0 = time.time()
    out = workflow.run(
        join.bind(slow.bind(1), slow.bind(2)),
        workflow_id="wf-par",
        storage=str(tmp_path),
    )
    dt = time.time() - t0
    assert out == 3
    # Serial would be >=1.0s of sleeps; the wave executor overlaps them.
    assert dt < 0.95, f"branches did not run concurrently ({dt:.2f}s)"
