"""Serve load-path tests: loadgen outcome classification, admission-control
sheds (queue cap + deadline-unreachable), continuous-batching edges
(batch-of-1, full batch, straggler join, cancelled waiter), and queue-EWMA
autoscaler hysteresis."""

import asyncio

import pytest


# -- loadgen unit tests (no cluster) ------------------------------------------


def test_percentile_nearest_rank():
    from ray_tpu.loadgen import percentile

    assert percentile([], 0.99) == 0.0
    vals = [float(i) for i in range(1, 101)]
    assert percentile(vals, 0.0) == 1.0
    assert percentile(vals, 1.0) == 100.0
    assert percentile(vals, 0.99) == 99.0  # round(0.99 * 99) = 98
    assert 50.0 <= percentile(vals, 0.5) <= 51.0


class _ScriptedRouter:
    """assign_request stub: runs the supplied coroutine function."""

    def __init__(self, fn):
        self._fn = fn

    async def assign_request(self, dep, meta, args, kwargs, timeout_s=None):
        return await self._fn()


def test_loadgen_outcome_classification():
    from ray_tpu import loadgen
    from ray_tpu._private import rpc
    from ray_tpu.serve._private.common import DeploymentOverloadedError

    async def main():
        res = loadgen.PhaseResult("t")

        async def ok():
            return 1

        async def shed_q():
            raise DeploymentOverloadedError("d", "queue_full", "full")

        async def shed_d():
            raise DeploymentOverloadedError("d", "deadline_unreachable", "x")

        async def cut():
            raise rpc.DeadlineExceeded("late")

        async def boom():
            raise RuntimeError("kaput")

        for fn in (ok, shed_q, shed_d, cut, boom):
            await loadgen._issue_one(_ScriptedRouter(fn), "d", 0, 1.0, res)
        assert res.issued == 5
        assert res.ok == 1
        assert res.shed_queue_full == 1
        assert res.shed_deadline == 1
        assert res.shed == 2
        assert res.deadline_cut == 1
        assert res.errors == 1 and "kaput" in res.error_samples[0]
        assert res.overruns == 0

    asyncio.run(main())


def test_loadgen_success_past_deadline_is_overrun():
    """A SUCCESS delivered past deadline + grace is the invariant violation
    the harness exists to catch — it must land in `overruns`, not `ok`."""
    from ray_tpu import loadgen
    from ray_tpu._private.common import config

    async def main():
        res = loadgen.PhaseResult("t")

        async def late_success():
            await asyncio.sleep(0.05 + config.rpc_deadline_grace_s + 0.1)
            return "fine"

        await loadgen._issue_one(
            _ScriptedRouter(late_success), "d", 0, 0.05, res
        )
        assert res.overruns == 1
        assert res.ok == 0 and not res.latencies_ms

    asyncio.run(main())


def test_loadgen_loops_and_gate_json_shape():
    from ray_tpu import loadgen

    async def main():
        async def fast():
            await asyncio.sleep(0.001)
            return 1

        router = _ScriptedRouter(fast)
        closed = await loadgen.closed_loop(
            router, "d", concurrency=4, duration_s=0.2, timeout_s=1.0
        )
        opened = await loadgen.open_loop(
            router, "d", rps=200.0, duration_s=0.2, timeout_s=1.0
        )
        return closed, opened

    closed, opened = asyncio.run(main())
    assert closed.issued > 0 and closed.ok == closed.issued
    # Open loop fires on the arrival schedule regardless of completions.
    assert 20 <= opened.issued <= 120
    out = __import__("ray_tpu.loadgen", fromlist=["to_gate_json"]).to_gate_json(
        closed, opened
    )
    for key in (
        "serve_rps",
        "serve_p50_ms",
        "serve_p99_ms",
        "serve_p999_ms",
        "serve_goodput_rps",
        "serve_offered_rps",
        "serve_shed",
        "serve_deadline_cut",
        "serve_overruns",
        "serve_errors",
    ):
        assert key in out, key
    assert out["serve_rps"] > 0
    assert out["serve_overruns"] == 0 and out["serve_errors"] == 0


# -- continuous-batching edges (no cluster) -----------------------------------


def _batch_queue(method, max_batch_size, wait_s, concurrent=1):
    from ray_tpu.serve._private.replica import _BatchQueue, _BatchStats

    stats = _BatchStats()
    return _BatchQueue(method, max_batch_size, wait_s, concurrent, stats), stats


def test_batch_of_one_flushes_on_wait_timeout():
    async def main():
        async def method(xs):
            return [x + 1 for x in xs]

        bq, stats = _batch_queue(method, 8, 0.02)
        try:
            assert await bq.submit(41) == 42
        finally:
            bq.close()
        d = stats.to_dict()
        assert d["batches"] == 1 and d["size_max"] == 1

    asyncio.run(main())


def test_full_batch_dispatches_without_waiting():
    async def main():
        calls = []

        async def method(xs):
            calls.append(list(xs))
            return [x * 2 for x in xs]

        # Wait window long enough that a split would be visible: reaching
        # max_batch_size must dispatch immediately, not after the window.
        bq, stats = _batch_queue(method, 4, 5.0)
        try:
            out = await asyncio.wait_for(
                asyncio.gather(*(bq.submit(i) for i in range(4))), timeout=2.0
            )
        finally:
            bq.close()
        assert out == [0, 2, 4, 6]
        assert calls == [[0, 1, 2, 3]]
        assert stats.to_dict()["size_max"] == 4

    asyncio.run(main())


def test_straggler_joins_batch_within_wait_window():
    async def main():
        calls = []

        async def method(xs):
            calls.append(list(xs))
            return list(xs)

        bq, stats = _batch_queue(method, 8, 0.3)
        try:
            t1 = asyncio.ensure_future(bq.submit("a"))
            await asyncio.sleep(0.05)  # well inside the 0.3s window
            t2 = asyncio.ensure_future(bq.submit("b"))
            assert await asyncio.gather(t1, t2) == ["a", "b"]
        finally:
            bq.close()
        assert calls == [["a", "b"]]
        d = stats.to_dict()
        assert d["batches"] == 1 and d["size_max"] == 2

    asyncio.run(main())


def test_batch_result_length_mismatch_is_typed_error():
    async def main():
        async def method(xs):
            return [1]  # wrong length for a batch of 2

        bq, _ = _batch_queue(method, 2, 1.0)
        try:
            results = await asyncio.gather(
                bq.submit("a"), bq.submit("b"), return_exceptions=True
            )
        finally:
            bq.close()
        assert all(isinstance(r, TypeError) for r in results)

    asyncio.run(main())


def test_cancelled_waiter_is_dropped_at_formation():
    """A request cancelled while still queued must never occupy a batch
    slot (the pump skips done futures when forming)."""

    async def main():
        gate = asyncio.Event()
        seen = []

        async def method(xs):
            seen.append(list(xs))
            await gate.wait()
            return list(xs)

        bq, _ = _batch_queue(method, 1, 0.0, concurrent=1)
        try:
            ta = asyncio.ensure_future(bq.submit("a"))
            await asyncio.sleep(0.05)  # [a] dispatched, holds the only slot
            tb = asyncio.ensure_future(bq.submit("b"))
            tc = asyncio.ensure_future(bq.submit("c"))
            await asyncio.sleep(0.05)  # [b] formed (awaiting slot), c queued
            tc.cancel()
            with pytest.raises(asyncio.CancelledError):
                await tc
            gate.set()
            assert await ta == "a"
            assert await tb == "b"
            assert await bq.submit("d") == "d"
        finally:
            bq.close()
        assert seen == [["a"], ["b"], ["d"]]

    asyncio.run(main())


# -- autoscaler hysteresis (no cluster) ---------------------------------------


def _autoscale_fixture():
    from ray_tpu.serve._private.common import DeploymentID
    from ray_tpu.serve._private.controller import _DeploymentState
    from ray_tpu.serve.schema import AutoscalingConfig

    ac = AutoscalingConfig(
        min_replicas=1,
        max_replicas=5,
        target_ongoing_requests=2.0,
        upscale_delay_s=1.0,
        downscale_delay_s=2.0,
        look_back_period_s=10.0,
    )
    state = _DeploymentState(DeploymentID("d"), {"config": {}})
    state.config.autoscaling_config = ac
    return state, ac


def test_autoscale_upscale_requires_sustained_load():
    from ray_tpu.serve._private.controller import autoscale_tick

    state, ac = _autoscale_fixture()
    state.metrics_window.append((0.0, 8))  # desired = ceil(8/2) = 4
    assert autoscale_tick(state, ac, 0.0) is None  # timer just started
    state.metrics_window.append((0.5, 8))
    assert autoscale_tick(state, ac, 0.5) is None  # 0.5s < upscale_delay 1s
    state.metrics_window.append((1.1, 8))
    assert autoscale_tick(state, ac, 1.1) == 4  # held past the delay
    state.current_target = 4
    assert state.target_replicas == 4


def test_autoscale_downscale_has_longer_fuse():
    from ray_tpu.serve._private.controller import autoscale_tick

    state, ac = _autoscale_fixture()
    state.current_target = 4
    state.metrics_window.append((20.0, 0))
    assert autoscale_tick(state, ac, 20.0) is None
    state.metrics_window.append((21.0, 0))
    assert autoscale_tick(state, ac, 21.0) is None  # 1s < downscale_delay 2s
    state.metrics_window.append((22.5, 0))
    assert autoscale_tick(state, ac, 22.5) == 1  # clamped to min_replicas

    # And never below min_replicas even from min.
    state.current_target = 1
    state.metrics_window.append((23.0, 0))
    assert autoscale_tick(state, ac, 23.0) is None


def test_autoscale_flapping_load_resets_hysteresis_timer():
    from ray_tpu.serve._private.controller import autoscale_tick

    state, ac = _autoscale_fixture()
    state.metrics_window = [(0.0, 8)]
    assert autoscale_tick(state, ac, 0.0) is None  # above-timer starts
    # Load falls back to target before the delay elapses: timer must reset.
    state.metrics_window = [(0.6, 2)]
    assert autoscale_tick(state, ac, 0.6) is None
    assert state.above_since is None
    # Load spikes again: the delay restarts from here, not from t=0.
    state.metrics_window = [(0.8, 8)]
    assert autoscale_tick(state, ac, 0.8) is None
    state.metrics_window.append((1.5, 8))
    assert autoscale_tick(state, ac, 1.5) is None  # 0.7s < 1s
    state.metrics_window.append((1.9, 8))
    assert autoscale_tick(state, ac, 1.9) == 4


def test_autoscale_queue_ewma_drives_scaling_when_ongoing_saturates():
    """Queued (not-yet-absorbed) load must scale the deployment even when
    per-replica ongoing counts plateau at max_ongoing_requests."""
    from ray_tpu.serve._private.controller import autoscale_tick

    state, ac = _autoscale_fixture()
    state.queue_ewma = 6.0  # routers report deep queues
    state.metrics_window = [(0.0, 0)]
    assert autoscale_tick(state, ac, 0.0) is None
    state.metrics_window.append((1.1, 0))
    assert autoscale_tick(state, ac, 1.1) == 3  # ceil(6/2)


def test_autoscale_empty_window_is_a_no_op():
    from ray_tpu.serve._private.controller import autoscale_tick

    state, ac = _autoscale_fixture()
    assert autoscale_tick(state, ac, 100.0) is None
    # Stale samples beyond look_back are pruned, leaving a no-op.
    state.metrics_window = [(0.0, 8)]
    assert autoscale_tick(state, ac, 100.0) is None
    assert state.metrics_window == []


def test_replica_set_evict_drops_corpse_and_wakes_queued():
    """A data-plane-observed death must take effect immediately: the corpse
    leaves the set, its phantom ongoing slots vanish, affinity pins are
    released, and queued pickers wake up to re-route."""

    async def run():
        from ray_tpu.serve._private.common import RunningReplicaInfo
        from ray_tpu.serve._private.router import _ReplicaSet

        rs = _ReplicaSet()
        infos = [
            RunningReplicaInfo(
                replica_id_str=f"r{i}",
                deployment_id_str="default#d",
                actor_id=f"a{i}",
                max_ongoing_requests=4,
                max_queued_requests=32,
            )
            for i in range(2)
        ]
        rs.update(infos)
        rs.ongoing["r0"] = 3
        rs.model_affinity["m"] = "r0"
        rs.slot_freed.clear()

        rs.evict("r0")
        assert [r.replica_id_str for r in rs.replicas] == ["r1"]
        assert rs.evicted == 1
        assert "r0" not in rs.ongoing
        assert "m" not in rs.model_affinity
        assert rs.slot_freed.is_set()  # queued pickers must re-run the pick
        assert rs.nonempty.is_set()  # a live replica remains

        # Unknown / already-evicted ids are no-ops.
        rs.evict("r0")
        assert rs.evicted == 1

        rs.evict("r1")
        assert rs.replicas == []
        assert rs.evicted == 2
        assert not rs.nonempty.is_set()  # empty set parks new arrivals

    asyncio.run(run())


# -- cluster integration ------------------------------------------------------


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def _meta():
    return {"call_method": "__call__", "request_id": "", "multiplexed_model_id": ""}


def test_admission_control_sheds_typed(serve_cluster):
    serve = serve_cluster
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.serve import handle as handle_mod
    from ray_tpu.serve._private.common import DeploymentOverloadedError

    @serve.deployment(
        num_replicas=1, max_ongoing_requests=1, max_queued_requests=2
    )
    class Slow:
        async def __call__(self, x):
            await asyncio.sleep(0.3)
            return x

    serve.run(Slow.bind(), route_prefix=None)
    dep = "default#Slow"

    async def burst():
        router = await handle_mod._get_router()
        # Warm the router: the queue cap rides the replica info delivered by
        # long-poll, so until the first push arrives the router only has the
        # config-default cap. One request waits that push out (and seeds the
        # service-time EWMA for the deadline-unreachable probe below).
        assert await router.assign_request(
            dep, _meta(), (-1,), {}, timeout_s=10.0
        ) == -1

        async def one(i):
            try:
                return await router.assign_request(
                    dep, _meta(), (i,), {}, timeout_s=10.0
                )
            except DeploymentOverloadedError as e:
                return e

        results = await asyncio.gather(*(one(i) for i in range(10)))

        # With the EWMA warmed by the completions above, a budget smaller
        # than the service estimate is shed at the door.
        tight_reason = None
        try:
            await router.assign_request(dep, _meta(), (99,), {}, timeout_s=0.02)
        except DeploymentOverloadedError as e:
            tight_reason = e.reason
        return results, tight_reason, router.stats()[dep]

    results, tight_reason, stats = worker_mod.global_worker.run_async(
        burst(), timeout=60
    )
    sheds = [r for r in results if isinstance(r, DeploymentOverloadedError)]
    oks = [
        (i, r) for i, r in enumerate(results) if not isinstance(r, Exception)
    ]
    # 1 in flight + 2 queued admitted; the rest of the burst is shed typed.
    assert sheds, f"expected queue-cap sheds, got {results}"
    assert all(e.reason == "queue_full" for e in sheds)
    assert oks and all(r == i for i, r in oks)
    assert tight_reason == "deadline_unreachable"
    assert stats["shed_queue_full"] == len(sheds)
    assert stats["shed_deadline"] >= 1
    assert stats["completed"] >= len(oks)


def test_batched_deployment_end_to_end(serve_cluster):
    serve = serve_cluster

    @serve.deployment(
        num_replicas=1,
        max_ongoing_requests=16,
        max_batch_size=4,
        batch_wait_timeout_s=0.05,
    )
    class Tripler:
        async def __call__(self, batch):
            assert isinstance(batch, list)
            return [b * 3 for b in batch]

    handle = serve.run(Tripler.bind(), route_prefix=None)
    responses = [handle.remote(i) for i in range(8)]
    assert [r.result(timeout_s=30) for r in responses] == [
        i * 3 for i in range(8)
    ]


def test_loadgen_smoke_no_overruns():
    """End-to-end loadgen smoke: overload comes back as typed sheds or
    deadline cuts — zero admitted requests overrun, zero untyped errors."""
    from ray_tpu import loadgen

    out = loadgen.run_smoke(
        closed_concurrency=8,
        closed_duration_s=0.6,
        open_duration_s=0.6,
        overload_factor=5.0,
        num_replicas=2,
        verbose=False,
    )
    assert out["serve_rps"] > 0
    assert out["serve_offered_rps"] > out["serve_goodput_rps"]
    assert out["serve_overruns"] == 0
    assert out["serve_errors"] == 0
    # Overload must be visible as typed backpressure.
    assert out["serve_shed"] + out["serve_deadline_cut"] > 0
