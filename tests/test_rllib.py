"""RLlib tests (analog of rllib/tests + rllib/tuned_examples learning runs):
PPO/DQN learn CartPole, IMPALA async pipeline runs and improves, learner-group
grad averaging stays in sync, env-runner fault tolerance, checkpoint restore."""

import numpy as np
import pytest


@pytest.fixture(scope="module", autouse=True)
def _cpu(request):
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)


@pytest.fixture
def rl_cluster():
    import ray_tpu
    from ray_tpu.testing import cpu_mesh_worker_env

    ray_tpu.init(num_cpus=8, num_tpus=0, worker_env=cpu_mesh_worker_env(1))
    yield None
    ray_tpu.shutdown()


def test_ppo_cartpole_learns_local():
    """Reference parity: rllib/tuned_examples/ppo/cartpole-ppo.yaml reward
    threshold run, local mode."""
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .training(
            train_batch_size=2048,
            minibatch_size=256,
            num_epochs=10,
            lr=3e-4,
            entropy_coeff=0.01,
        )
        .debugging(seed=42)
        .build_algo()
    )
    best = 0.0
    for _ in range(40):
        result = algo.train()
        ret = result.get("episode_return_mean")
        if ret == ret:  # not NaN
            best = max(best, ret)
        if best >= 150:
            break
    algo.stop()
    assert best >= 150, f"PPO failed to learn CartPole: best={best}"


def test_ppo_distributed_env_runners(rl_cluster):
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
        .training(train_batch_size=1024, minibatch_size=256, num_epochs=6)
        .build_algo()
    )
    r1 = algo.train()
    r2 = algo.train()
    assert r2["num_env_steps_sampled_lifetime"] >= 2048
    assert "total_loss" in r2
    algo.stop()


def test_dqn_cartpole_improves_local():
    from ray_tpu.rllib import DQNConfig

    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                     rollout_fragment_length=8)
        .training(
            train_batch_size=64,
            updates_per_iteration=8,
            lr=1e-3,
            num_steps_sampled_before_learning_starts=1000,
            epsilon_timesteps=8000,
            target_network_update_freq=500,
        )
        .debugging(seed=7)
        .build_algo()
    )
    best = 0.0
    # Early-exit on success keeps the pass-path fast; the generous budget
    # absorbs the run-to-run variance of epsilon-greedy exploration (the
    # environment's episode stream is not fully determined by the seeds).
    for _ in range(600):
        result = algo.train()
        ret = result.get("episode_return_mean")
        if ret == ret:
            best = max(best, ret)
        if best >= 100:
            break
    algo.stop()
    assert best >= 100, f"DQN failed to improve on CartPole: best={best}"


def test_bc_offline_imitation(shutdown_only):
    """BC clones a scripted expert from a ray_tpu.data Dataset: action
    accuracy on the logged policy climbs well above chance (reference:
    rllib/algorithms/bc + offline data pipeline)."""
    import gymnasium as gym
    import numpy as np

    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.rllib import BCConfig

    ray_tpu.init(num_cpus=4, num_tpus=0)

    # Scripted CartPole expert: push toward the pole's lean.
    env = gym.make("CartPole-v1")
    rows = []
    obs, _ = env.reset(seed=0)
    for _ in range(2000):
        action = int(obs[2] + 0.3 * obs[3] > 0)
        rows.append({"obs": obs.astype(np.float32).tolist(), "actions": action})
        obs, _, term, trunc, _ = env.step(action)
        if term or trunc:
            obs, _ = env.reset()
    env.close()

    ds = rd.from_items(rows)
    algo = (
        BCConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
        .offline_data(input_=ds)
        .training(train_batch_size=128, updates_per_iteration=16, lr=1e-3)
        .debugging(seed=5)
        .build_algo()
    )
    acc = 0.0
    for _ in range(100):
        result = algo.train()
        acc = max(acc, result["action_accuracy"])
        if acc >= 0.95:
            break
    algo.stop()
    assert acc >= 0.93, f"BC never fit the expert: accuracy={acc}"


def test_sac_pendulum_improves_local():
    """SAC on Pendulum-v1 (continuous Box actions): squashed-Gaussian actor,
    twin Q + polyak targets, auto-tuned entropy temperature. Pendulum starts
    near -1500 mean return; crossing -900 demonstrates real learning."""
    from ray_tpu.rllib import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                     rollout_fragment_length=8)
        .training(
            train_batch_size=128,
            updates_per_iteration=16,
            lr=1e-3,
            num_steps_sampled_before_learning_starts=1000,
        )
        .debugging(seed=3)
        .build_algo()
    )
    best = float("-inf")
    for _ in range(500):
        result = algo.train()
        ret = result.get("episode_return_mean")
        if ret == ret:  # not NaN
            best = max(best, ret)
        if best >= -900:
            break
    algo.stop()
    assert best >= -900, f"SAC failed to improve on Pendulum: best={best}"


def test_impala_async_pipeline(rl_cluster):
    from ray_tpu.rllib import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .training(batches_per_iteration=8, lr=5e-4)
        .build_algo()
    )
    first = None
    best = 0.0
    for _ in range(25):
        result = algo.train()
        ret = result.get("episode_return_mean")
        if ret == ret:
            if first is None:
                first = ret
            best = max(best, ret)
        if best >= 80:
            break
    algo.stop()
    assert first is not None
    assert best > max(first, 40), f"IMPALA did not improve: first={first} best={best}"
    assert result["mean_weight_staleness"] >= 0


def test_learner_group_grad_averaging(rl_cluster):
    """Two remote learners stay weight-synced via grad averaging."""
    import jax

    from ray_tpu.rllib import LearnerGroup, RLModuleSpec
    from ray_tpu.rllib.algorithms.ppo import PPOLearner

    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    loss_cfg = {
        "clip_param": 0.2,
        "vf_clip_param": 10.0,
        "vf_loss_coeff": 0.5,
        "entropy_coeff": 0.0,
    }

    def build():
        return PPOLearner(spec, loss_cfg, lr=1e-3, seed=3)

    group = LearnerGroup(build, num_learners=2)
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 4).astype(np.float32),
        "actions": rng.randint(0, 2, 64),
        "logp_old": -0.7 * np.ones(64, np.float32),
        "advantages": rng.randn(64).astype(np.float32),
        "value_targets": rng.randn(64).astype(np.float32),
        "values_old": np.zeros(64, np.float32),
    }
    metrics = group.update_from_batch(batch)
    assert "total_loss" in metrics
    # Both learners should hold identical weights after the averaged update.
    import ray_tpu

    w = [
        ray_tpu.get(a.get_weights.remote())
        for a in group._manager.actors
    ]
    flat0 = jax.tree_util.tree_leaves(w[0])
    flat1 = jax.tree_util.tree_leaves(w[1])
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)
    group.shutdown()


def test_env_runner_fault_tolerance(rl_cluster):
    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=2)
        .build_algo()
    )
    algo.train()
    # Kill one env runner; next train() should heal and still produce a batch.
    victim = algo.env_runner_group._manager.actors[0]
    ray_tpu.kill(victim)
    result = algo.train()
    assert result["num_env_steps_sampled_lifetime"] > 0
    algo.stop()


def test_algorithm_checkpoint_restore(tmp_path):
    from ray_tpu.rllib import PPOConfig

    def make():
        return (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
            .training(train_batch_size=256, minibatch_size=64, num_epochs=2)
            .build_algo()
        )

    algo = make()
    algo.train()
    algo.train()
    ckpt = algo.save(str(tmp_path / "ckpt"))
    it = algo.iteration
    algo.stop()

    algo2 = make()
    algo2.restore(ckpt)
    assert algo2.iteration == it
    result = algo2.train()
    assert result["training_iteration"] == it + 1
    algo2.stop()


def _logged_cartpole(n=2000, noise=0.3, seed=0):
    """Offline rows from a decent-but-noisy scripted CartPole policy
    (mixed-quality data, the offline-RL setting): full transitions with
    per-episode reward/done structure."""
    import gymnasium as gym
    import numpy as np

    rng = np.random.RandomState(seed)
    env = gym.make("CartPole-v1")
    rows = []
    obs, _ = env.reset(seed=seed)
    for _ in range(n):
        expert = int(obs[2] + 0.3 * obs[3] > 0)
        action = expert if rng.rand() > noise else rng.randint(2)
        nxt, rew, term, trunc, _ = env.step(action)
        rows.append(
            {
                "obs": obs.astype(np.float32).tolist(),
                "actions": action,
                "rewards": float(rew),
                "next_obs": nxt.astype(np.float32).tolist(),
                "dones": bool(term or trunc),
            }
        )
        obs = nxt
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    return rows


def test_marwil_beats_bc_weighting(shutdown_only):
    """MARWIL's exp(beta*advantage) weighting learns from MIXED-quality
    logs: the learned policy's action accuracy against the expert rule
    exceeds the noisy behavior policy's own consistency (reference:
    rllib/algorithms/marwil learning tests)."""
    import numpy as np

    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.rllib import MARWILConfig

    ray_tpu.init(num_cpus=4, num_tpus=0)
    rows = _logged_cartpole(n=3000, noise=0.35, seed=3)

    algo = (
        MARWILConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
        .offline_data(input_=rd.from_items(rows))
        .training(train_batch_size=256, updates_per_iteration=24, lr=2e-3)
        .debugging(seed=7)
        .build_algo()
    )
    for _ in range(30):
        result = algo.train()
    assert "policy_loss" in result and "vf_loss" in result
    assert result["mean_weight"] > 0.0
    # Greedy accuracy vs the expert rule on held-out states.
    import jax.numpy as jnp

    from ray_tpu.rllib.core.rl_module import forward_pi_vf

    learner = algo.learner_group._local
    test_obs = np.asarray([r["obs"] for r in rows[:500]], dtype=np.float32)
    expert = np.asarray(
        [int(o[2] + 0.3 * o[3] > 0) for o in test_obs], dtype=np.int64
    )
    logits, _ = forward_pi_vf(learner.params, jnp.asarray(test_obs))
    acc = float(np.mean(np.argmax(np.asarray(logits), axis=-1) == expert))
    # The behavior policy agrees with the expert only ~65% of the time;
    # advantage weighting must push past it.
    assert acc > 0.75, f"MARWIL greedy accuracy {acc:.2f}"
    algo.stop()


def test_cql_conservative_penalty(shutdown_only):
    """CQL learns from the fixed buffer and its conservative gap shrinks;
    the penalty keeps logged-action values above the soft-max OOD value
    (reference: rllib/algorithms/cql learning tests)."""
    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.rllib import CQLConfig

    ray_tpu.init(num_cpus=4, num_tpus=0)
    rows = _logged_cartpole(n=2000, noise=0.2, seed=11)

    def train_with(alpha):
        algo = (
            CQLConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
            .offline_data(input_=rd.from_items(rows))
            .training(
                train_batch_size=64, updates_per_iteration=32, lr=1e-3,
                cql_alpha=alpha,
            )
            .debugging(seed=13)
            .build_algo()
        )
        result = {}
        for _ in range(15):
            result = algo.train()
        algo.stop()
        return result

    conservative = train_with(1.0)
    plain = train_with(0.0)
    assert "td_loss" in conservative and "total_loss" in conservative
    # The penalty's defining property: logged-action Q values sit closer to
    # the soft-max over actions than an unpenalized learner's — OOD actions
    # are pushed DOWN relative to in-distribution ones.
    assert conservative["cql_gap"] < plain["cql_gap"], (
        f"penalty had no conservative effect: alpha=1 gap "
        f"{conservative['cql_gap']:.3f} vs alpha=0 gap {plain['cql_gap']:.3f}"
    )
