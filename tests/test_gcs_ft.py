"""GCS fault tolerance: kill + restart the control service and the cluster
resumes (reference analog: python/ray/tests/test_gcs_fault_tolerance.py;
persistence via StoreClient, store_client.h:33; reconnect protocol
NotifyGCSRestart, node_manager.proto:373)."""

import time

import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod


@pytest.fixture
def ray_small(shutdown_only):
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield


def _restart_gcs():
    w = worker_mod.global_worker
    node = w.node

    async def cycle():
        await node.kill_gcs()
        await node.restart_gcs()

    w.run_async(cycle(), timeout=30)


def _crash_gcs(torn_tail=True):
    """Hard-crash cycle: no store checkpoint/fsync on the way down, plus a
    half-written record torn onto the WAL tail (power-loss shape)."""
    w = worker_mod.global_worker
    node = w.node

    async def cycle():
        await node.crash_gcs(torn_tail=torn_tail)
        await node.restart_gcs()

    w.run_async(cycle(), timeout=30)


def test_gcs_restart_cluster_resumes(ray_small):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2
    _restart_gcs()
    # Raylet re-registers via its reconnecting GCS client; new work proceeds
    # (first call may ride the reconnect backoff).
    deadline = time.monotonic() + 20
    while True:
        try:
            assert ray_tpu.get(f.remote(41), timeout=30) == 42
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_gcs_restart_detached_actor_survives(ray_small):
    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.v = 0

        def incr(self):
            self.v += 1
            return self.v

    k = Keeper.options(name="durable", lifetime="detached").remote()
    assert ray_tpu.get(k.incr.remote()) == 1

    _restart_gcs()

    # Named lookup hits the restarted GCS's reloaded actor table; the actor
    # process itself never died, so its state is intact.
    deadline = time.monotonic() + 20
    while True:
        try:
            k2 = ray_tpu.get_actor("durable")
            assert ray_tpu.get(k2.incr.remote(), timeout=30) == 2
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_gcs_restart_kv_survives(ray_small):
    w = worker_mod.global_worker
    core = w.core
    w.run_async(core.gcs.kv_put("persist_me", b"value", ns="test"))
    _restart_gcs()
    deadline = time.monotonic() + 20
    while True:
        try:
            assert w.run_async(core.gcs.kv_get("persist_me", ns="test"), timeout=30) == b"value"
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_gcs_crash_torn_wal_detached_actor_survives(ray_small):
    """Crash (not stop) the GCS with a torn WAL tail mid-session: recovery
    truncates the torn frame and every acknowledged record — the detached
    actor's ALIVE entry, its name registration — survives."""

    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.v = 0

        def incr(self):
            self.v += 1
            return self.v

    k = Keeper.options(name="crashproof", lifetime="detached").remote()
    assert ray_tpu.get(k.incr.remote()) == 1

    _crash_gcs(torn_tail=True)

    deadline = time.monotonic() + 20
    while True:
        try:
            k2 = ray_tpu.get_actor("crashproof")
            assert ray_tpu.get(k2.incr.remote(), timeout=30) == 2
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_gcs_crash_torn_wal_kv_survives(ray_small):
    w = worker_mod.global_worker
    core = w.core
    w.run_async(core.gcs.kv_put("crash_me", b"value", ns="test"))
    _crash_gcs(torn_tail=True)
    deadline = time.monotonic() + 20
    while True:
        try:
            assert (
                w.run_async(core.gcs.kv_get("crash_me", ns="test"), timeout=30)
                == b"value"
            )
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)


def test_gcs_restart_actor_restart_still_works(ray_small):
    """After a GCS restart, the actor-restart FSM (now running on reloaded
    state) still restarts a killed actor."""

    @ray_tpu.remote
    class Flaky:
        def pid(self):
            import os

            return os.getpid()

    a = Flaky.options(max_restarts=2).remote()
    pid1 = ray_tpu.get(a.pid.remote())
    _restart_gcs()
    time.sleep(2.0)  # let the raylet re-register
    import os
    import signal

    os.kill(pid1, signal.SIGKILL)
    deadline = time.monotonic() + 30
    while True:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=30)
            assert pid2 != pid1
            break
        except ray_tpu.RayTpuError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
