"""GCS active health checks + pubsub backpressure.

Reference analogs: gcs_health_check_manager.cc (periodic probe with miss
counting) and pubsub/publisher.h (per-subscriber bounded queues)."""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private import worker as worker_mod
from ray_tpu.cluster_utils import Cluster


def test_wedged_node_detected_by_health_checks(monkeypatch):
    """A raylet whose event loop stops serving RPCs (but keeps its TCP
    session) must be detected by periodic Pings with miss counting —
    connection-centric death detection alone would never notice it."""
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_INITIAL_DELAY_S", "0.1")
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_PERIOD_S", "0.2")
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_TIMEOUT_S", "0.5")
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD", "3")
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    wedged = cluster.add_node(num_cpus=1)
    cluster.connect()
    try:
        assert len([n for n in ray_tpu.nodes() if n["state"] == "ALIVE"]) == 2

        async def hang(conn, p):
            await asyncio.sleep(3600)

        wedged.server._handlers["Ping"] = hang

        # Watch for the death EVENT: after being marked DEAD the GCS drops
        # the link and the (still-connected but wedged) raylet re-registers,
        # so polling instantaneous state can miss the DEAD window.
        w = worker_mod.global_worker
        removed = []

        async def subscribe():
            core = w.core
            await core.gcs.subscribe(
                "nodes",
                lambda msg: removed.append(msg["node"]["node_id"])
                if msg.get("event") == "removed"
                else None,
            )

        w.run_async(subscribe(), timeout=30)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and wedged.node_id not in removed:
            time.sleep(0.25)
        assert wedged.node_id in removed, (
            "wedged raylet was never marked DEAD by health checks"
        )
    finally:
        cluster.shutdown()


def test_versioned_view_sync(monkeypatch):
    """Raylets converge on the scheduling head via versioned broadcasts (no
    polling): joins, resource updates, and deaths all bump the version, and
    membership changes bump the shape epoch (reference: ray_syncer.h
    streams, inverted — the GCS sorts, subscribers receive the head)."""
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    head_raylet = cluster.head_node.raylet

    def head_ids():
        return {n["node_id"] for n in head_raylet._head}

    cluster.connect()
    try:
        n2 = cluster.add_node(num_cpus=2)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if head_raylet._head_version >= 0 and n2.node_id in head_ids():
                break
            time.sleep(0.1)
        assert n2.node_id in head_ids(), "join broadcast never arrived"
        v_after_join = head_raylet._head_version
        epoch_after_join = head_raylet._head_epoch
        assert v_after_join >= 0

        cluster.remove_node(n2)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if n2.node_id not in head_ids():
                break
            time.sleep(0.1)
        assert n2.node_id not in head_ids(), "death broadcast never arrived"
        assert head_raylet._head_version > v_after_join
        assert head_raylet._head_epoch > epoch_after_join
    finally:
        cluster.shutdown()


def test_subscriber_gap_pulls_snapshot():
    """A subscriber that observes a seq jump (its backlog was shed, or it
    missed a window) must resync from a channel Snapshot instead of acting
    on a stale picture."""
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.connect()
    w = worker_mod.global_worker
    gcs = cluster.gcs_server
    try:

        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == 1
        channel = f"actor:{a._actor_id}"
        got = []

        async def provoke_gap():
            await w.core.gcs.subscribe(channel, got.append)
            # Simulate a shed backlog: jump the channel's seqno past what
            # the subscriber has seen, then publish. The client must flag
            # the gap and pull a Snapshot (the actor's current record).
            gcs.publisher.seqnos[channel] = (
                gcs.publisher.seqnos.get(channel, 0) + 5
            )
            gcs.publisher.publish(channel, {"state": "ALIVE", "probe": True})

        w.run_async(provoke_gap(), timeout=30)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if any("probe" in m for m in got) and any(
                m.get("actor_id") == a._actor_id for m in got
            ):
                break
            time.sleep(0.1)
        # Both the gap-straddling publish AND the snapshot resync arrive.
        assert any("probe" in m for m in got), got
        assert any(m.get("actor_id") == a._actor_id for m in got), got
    finally:
        cluster.shutdown()


def test_slow_subscriber_backpressure(monkeypatch):
    """A subscriber that stops reading its socket must not stall the GCS:
    its queue bounds, oldest messages drop, and other RPCs stay fast."""
    monkeypatch.setenv("RAY_TPU_PUBSUB_MAX_BUFFERED_MSGS", "50")
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.connect()
    w = worker_mod.global_worker
    gcs = cluster.gcs_server
    try:
        received = []

        async def connect_sub():
            async def on_pub(conn, p):
                received.append(p["msg"])

            async def on_pub_batch(conn, p):
                for _ch, msg, _seq in p["items"]:
                    received.append(msg)

            conn = await rpc.connect(
                *cluster.gcs_addr,
                handlers={"Pub": on_pub, "PubBatch": on_pub_batch},
            )
            await conn.call("Subscribe", {"channel": "bench"})
            return conn

        sub_conn = w.run_async(connect_sub(), timeout=30)

        async def stall_and_publish():
            # Stop reading: the server's sends back up on this transport.
            sub_conn._protocol.transport.pause_reading()
            payload = "x" * 4096
            for i in range(2000):
                gcs.publisher.publish("bench", {"i": i, "pad": payload})
            await asyncio.sleep(0.5)  # let drain tasks hit the full socket

        w.run_async(stall_and_publish(), timeout=60)
        # Other RPCs still served promptly.
        t0 = time.monotonic()
        assert any(n["state"] == "ALIVE" for n in ray_tpu.nodes())
        assert time.monotonic() - t0 < 2.0
        stats = gcs.publisher.stats()
        assert stats["total_dropped"] > 0, stats
        bench = stats["channels"]["bench"]
        assert bench["queued"] <= 50, stats

        async def resume():
            sub_conn._protocol.transport.resume_reading()

        w.run_async(resume(), timeout=10)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not received:
            time.sleep(0.1)
        # The tail of the stream (newest retained messages) arrives.
        assert received and received[-1]["i"] >= 1950, (
            len(received),
            received[-1]["i"] if received else None,
        )

        async def close_sub():
            await sub_conn.close()

        w.run_async(close_sub(), timeout=10)
    finally:
        cluster.shutdown()
