"""NodeLabelSchedulingStrategy: label-gated task + actor placement
(reference: python/ray/util/scheduling_strategies.py NodeLabelSchedulingStrategy,
policy src/ray/raylet/scheduling/policy/scheduling_options.h:30-44)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import (
    DoesNotExist,
    Exists,
    In,
    NodeLabelSchedulingStrategy,
    NotIn,
    match_label_expr,
    node_matches_labels,
)


def test_label_expression_semantics():
    labels = {"region": "us-west", "tier": "gold"}
    assert match_label_expr(In("us-west").to_wire(), labels, "region")
    assert not match_label_expr(In("eu").to_wire(), labels, "region")
    assert match_label_expr(NotIn("eu").to_wire(), labels, "region")
    # Missing label satisfies NotIn, fails In/Exists, passes DoesNotExist.
    assert match_label_expr(NotIn("x").to_wire(), labels, "absent")
    assert not match_label_expr(In("x").to_wire(), labels, "absent")
    assert match_label_expr(Exists().to_wire(), labels, "tier")
    assert not match_label_expr(Exists().to_wire(), labels, "absent")
    assert match_label_expr(DoesNotExist().to_wire(), labels, "absent")
    # Plain string sugar == In(value).
    wire = NodeLabelSchedulingStrategy(hard={"region": "us-west"}).to_wire()
    assert wire["labels"]["hard"]["region"] == {"op": "in", "values": ["us-west"]}
    assert node_matches_labels(wire["labels"]["hard"], labels)


@pytest.fixture
def label_cluster():
    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 1}
    )
    cluster.add_node(num_cpus=2, labels={"accel": "tpu", "gen": "v5e"})
    cluster.add_node(num_cpus=2, labels={"accel": "gpu"})
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_task_hard_label_affinity(label_cluster):
    @ray_tpu.remote(num_cpus=1)
    def where():
        import os

        return os.environ["RAY_TPU_NODE_ID"]

    tpu_node = {
        n["node_id"]: n.get("labels") or {}
        for n in ray_tpu.nodes()
    }
    tpu_ids = [k for k, v in tpu_node.items() if v.get("accel") == "tpu"]
    gpu_ids = [k for k, v in tpu_node.items() if v.get("accel") == "gpu"]
    assert len(tpu_ids) == 1 and len(gpu_ids) == 1

    strat = NodeLabelSchedulingStrategy(hard={"accel": In("tpu")})
    got = ray_tpu.get(
        [
            where.options(scheduling_strategy=strat).remote()
            for _ in range(4)
        ]
    )
    assert set(got) == {tpu_ids[0]}

    # Anti-affinity: NOT the tpu node.
    strat = NodeLabelSchedulingStrategy(hard={"accel": NotIn("tpu")})
    got = ray_tpu.get(
        [where.options(scheduling_strategy=strat).remote() for _ in range(4)]
    )
    assert tpu_ids[0] not in set(got)


def test_task_unsatisfiable_hard_labels(label_cluster):
    @ray_tpu.remote(num_cpus=1)
    def f():
        return 1

    strat = NodeLabelSchedulingStrategy(hard={"accel": In("nonexistent")})
    with pytest.raises(Exception):
        ray_tpu.get(f.options(scheduling_strategy=strat).remote(), timeout=30)


def test_soft_labels_prefer_but_fall_back(label_cluster):
    @ray_tpu.remote(num_cpus=1)
    def where():
        import os

        return os.environ["RAY_TPU_NODE_ID"]

    nodes = {n["node_id"]: n.get("labels") or {} for n in ray_tpu.nodes()}
    v5e = [k for k, v in nodes.items() if v.get("gen") == "v5e"]
    # Soft preference for gen=v5e lands there...
    strat = NodeLabelSchedulingStrategy(
        hard={"accel": Exists()}, soft={"gen": In("v5e")}
    )
    got = ray_tpu.get(where.options(scheduling_strategy=strat).remote())
    assert got == v5e[0]
    # ...but a soft-only miss still schedules somewhere.
    strat = NodeLabelSchedulingStrategy(soft={"gen": In("not-a-gen")})
    assert ray_tpu.get(where.options(scheduling_strategy=strat).remote()) in nodes


def test_actor_label_placement(label_cluster):
    @ray_tpu.remote(num_cpus=1)
    class Pinned:
        def where(self):
            import os

            return os.environ["RAY_TPU_NODE_ID"]

    nodes = {n["node_id"]: n.get("labels") or {} for n in ray_tpu.nodes()}
    gpu = [k for k, v in nodes.items() if v.get("accel") == "gpu"]
    a = Pinned.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"accel": In("gpu")}
        )
    ).remote()
    assert ray_tpu.get(a.where.remote()) == gpu[0]
    ray_tpu.kill(a)
