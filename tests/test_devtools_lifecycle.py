"""Fixtures for the lifecycle dataflow pass and the protocol FSM checker.

Positive fixtures prove each rule fires on its target shape; negative
fixtures prove the clean idioms used in the tree (acquire + try/finally,
guarded legal transitions, constants) stay quiet; and the repo-clean tests
pin the acceptance bar: the shipped package must lint clean under both
passes.
"""

import asyncio
import dataclasses
import textwrap

import pytest

from ray_tpu._private.pull_manager import PullManager
from ray_tpu.devtools import aio_lint, lifecycle, protocols


def _lrules(src):
    findings = lifecycle.lint_source(textwrap.dedent(src), "fixture.py")
    return {f.rule for f in findings}


def _prules(src, name="gcs.py"):
    findings = protocols.check_source(textwrap.dedent(src), name)
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# lifecycle: paired-resource dataflow


def test_leak_on_exception():
    rules = _lrules(
        """
        class R:
            async def pull(self, size):
                await self.pull_manager.acquire(size)
                data = self.decode(size)  # may raise -> quota leaks
                self.pull_manager.release(size)
                return data
        """
    )
    assert lifecycle.RULE_LEAK_EXC in rules


def test_leak_on_early_return():
    rules = _lrules(
        """
        class R:
            async def pull(self, size):
                await self.pull_manager.acquire(size)
                if size > 10:
                    return None  # skips the release
                self.pull_manager.release(size)
        """
    )
    assert lifecycle.RULE_LEAK_RETURN in rules


def test_held_across_await_without_finally():
    rules = _lrules(
        """
        class R:
            async def pull(self, size, conn):
                await self.pull_manager.acquire(size)
                await conn.call("FetchChunk", {})  # cancellation point
                self.pull_manager.release(size)
        """
    )
    assert lifecycle.RULE_HELD_AWAIT in rules


def test_double_release():
    rules = _lrules(
        """
        class R:
            async def pull(self, size):
                await self.pull_manager.acquire(size)
                self.pull_manager.release(size)
                self.pull_manager.release(size)
        """
    )
    assert lifecycle.RULE_DOUBLE_RELEASE in rules


def test_trace_span_leak_across_await():
    # The PR 13 span token is scoped: crossing an await before the reset
    # means a cancellation leaks the span onto whatever runs next on this
    # context.
    rules = _lrules(
        """
        class R:
            async def handle(self, ctx):
                tok = tracing.set_context(ctx)
                await self.invoke()
                tracing.reset_context(tok)
        """
    )
    assert lifecycle.RULE_HELD_AWAIT in rules


def test_trace_span_try_finally_is_clean():
    # The shipped idiom (worker_main, serve replica): reset in a finally.
    assert not _lrules(
        """
        class R:
            async def handle(self, ctx):
                tok = tracing.set_context(ctx)
                try:
                    return await self.invoke()
                finally:
                    tracing.reset_context(tok)
        """
    )


def test_clean_try_finally():
    assert not _lrules(
        """
        class R:
            async def pull(self, size, conn):
                await self.pull_manager.acquire(size)
                try:
                    return await conn.call("FetchChunk", {})
                finally:
                    self.pull_manager.release(size)
        """
    )


def test_conditional_release_is_quiet():
    # Branch-joined "maybe held" never fires: a conditional release
    # pattern is assumed deliberate.
    assert not _lrules(
        """
        class R:
            async def pull(self, size, ok):
                await self.pull_manager.acquire(size)
                try:
                    if ok:
                        self.pull_manager.release(size)
                finally:
                    pass
        """
    )


def test_ledger_mode_needs_balanced_scope():
    # A ledger-style acquire with no in-function release is a legitimate
    # cross-function hold (raylet deduct / store pin) — no findings.
    assert not _lrules(
        """
        class Raylet:
            def grant(self, req):
                self.available = self.available - req.demand
                self._record_granted(req.lease_id)
                self.commit(req)
        """
    )
    # But a function that both deducts and refunds is a balanced scope and
    # the hazard rules apply between them.
    rules = _lrules(
        """
        class Raylet:
            def grant(self, req):
                self.available = self.available - req.demand
                self.commit(req)  # may raise
                self.available = self.available + req.demand
        """
    )
    assert lifecycle.RULE_LEAK_EXC in rules


def test_release_only_function_is_quiet():
    assert not _lrules(
        """
        class W:
            def done(self, a, b):
                self.plasma.release_many(a)
                self.plasma.release_many(b)
        """
    )


def test_lifecycle_suppression():
    assert not _lrules(
        """
        class R:
            async def pull(self, size):
                await self.pull_manager.acquire(size)
                if size > 10:
                    # owner tracks the quota  # lifecycle: disable=lifecycle-leak-return
                    return None
                self.pull_manager.release(size)
        """
    )


# ---------------------------------------------------------------------------
# protocols: FSM checker


def test_illegal_transition_under_guard():
    rules = _prules(
        """
        DEAD = "DEAD"
        ALIVE = "ALIVE"
        class GcsServer:
            async def f(self, actor):
                if actor.state == DEAD:
                    actor.state = ALIVE  # dead actors do not resurrect
        """
    )
    assert protocols.RULE_ILLEGAL in rules


def test_unknown_state_literal():
    rules = _prules(
        """
        class GcsServer:
            async def f(self, actor):
                actor.state = "ZOMBIE"
        """
    )
    assert protocols.RULE_UNKNOWN in rules


def test_unknown_state_in_comparison():
    rules = _prules(
        """
        def f(pg):
            return pg.state == "CREATEDD"
        """
    )
    assert protocols.RULE_UNKNOWN in rules


def test_unresolvable_state_assignment():
    rules = _prules(
        """
        class GcsServer:
            def f(self, actor, rec):
                actor.state = rec["state"]
        """
    )
    assert protocols.RULE_UNRESOLVABLE in rules


def test_protocol_suppression():
    assert not _prules(
        """
        class GcsServer:
            def f(self, actor, rec):
                actor.state = rec["state"]  # protocol: disable=protocol-unresolvable
        """
    )


def test_init_must_use_initial_state():
    rules = _prules(
        """
        class ActorInfo:
            def __init__(self):
                self.state = "ALIVE"
        """
    )
    assert protocols.RULE_ILLEGAL in rules


def test_clean_guarded_transition():
    assert not _prules(
        """
        PG_CREATED = "CREATED"
        PG_RESCHEDULING = "RESCHEDULING"
        def on_node_death(pg):
            if pg.state == PG_CREATED:
                pg.state = PG_RESCHEDULING
        """
    )


def test_clean_constant_assignment():
    assert not _prules(
        """
        RESTARTING = "RESTARTING"
        def f(actor):
            actor.state = RESTARTING
        """
    )


def test_lease_ledger_booleans():
    assert not _prules(
        """
        class Raylet:
            def record(self, lease_id):
                self.granted_lease_ids[lease_id] = True
            def burn(self, lease_id):
                self.granted_lease_ids[lease_id] = False
        """,
        "raylet.py",
    )
    rules = _prules(
        """
        class Raylet:
            def record(self, lease_id):
                self.granted_lease_ids[lease_id] = "weird"
        """,
        "raylet.py",
    )
    assert protocols.RULE_UNKNOWN in rules


def test_unscanned_filenames_are_ignored():
    assert not _prules(
        """
        def f(actor):
            actor.state = "ZOMBIE"
        """,
        "dashboard.py",
    )


def test_spec_is_internally_consistent():
    assert protocols._spec_findings() == []


def test_invariant_cross_check_detects_drift():
    # Removing a terminal state from the spec must break the sync with
    # chaos TERMINAL_ACTOR_STATES (the regression ISSUE 3 demands).
    broken = dataclasses.replace(
        protocols.ACTOR, terminal=(), quiescent=("ALIVE",)
    )
    findings = protocols.check_invariants_sync(machine=broken)
    assert any(f.rule == protocols.RULE_DRIFT for f in findings)
    # And the shipped spec is in sync.
    assert protocols.check_invariants_sync() == []


def test_markdown_generation():
    text = protocols.markdown()
    assert text.startswith("# Control-plane protocol state machines")
    for machine in protocols.MACHINES:
        assert f"## {machine.name}" in text
    assert "stateDiagram-v2" in text
    # Deterministic: docs drift check in CI relies on this.
    assert text == protocols.markdown()


# ---------------------------------------------------------------------------
# acceptance: the shipped tree lints clean under both passes


def test_repo_is_lifecycle_clean():
    root = aio_lint._default_root()
    assert [str(f) for f in lifecycle.lint_paths([root])] == []


def test_repo_is_protocol_clean():
    root = aio_lint._default_root()
    assert [str(f) for f in protocols.check([root])] == []


# ---------------------------------------------------------------------------
# the pull-quota cancellation leak (the satellite fix, regression-pinned)


def test_pull_quota_cancelled_acquire_releases():
    async def main():
        pm = PullManager(100)
        await pm.acquire(80)
        waiter = asyncio.get_running_loop().create_task(pm.acquire(50))
        await asyncio.sleep(0)  # park the waiter in the heap
        # Admit the waiter (its future resolves, quota is charged) and
        # cancel before it resumes: the acquire must undo the admission.
        pm.release(80)
        assert pm.bytes_in_flight == 50 and pm.active == 1
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert pm.bytes_in_flight == 0 and pm.active == 0

    asyncio.run(main())


def test_pull_quota_cancelled_before_admission():
    async def main():
        pm = PullManager(100)
        await pm.acquire(80)
        waiter = asyncio.get_running_loop().create_task(pm.acquire(50))
        await asyncio.sleep(0)
        # Not yet admitted: cancelling must not touch the quota.
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert pm.bytes_in_flight == 80 and pm.active == 1
        pm.release(80)
        assert pm.bytes_in_flight == 0 and pm.active == 0

    asyncio.run(main())


def test_pull_quota_underflow_fails_loudly():
    pm = PullManager(100)
    with pytest.raises(AssertionError, match="underflow"):
        pm.release(10)
