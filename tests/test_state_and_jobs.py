"""State API, job submission, dashboard, and CLI tests (analog of
python/ray/tests/test_state_api*.py + dashboard/modules/job tests)."""

import json
import sys
import time
import urllib.request

import pytest


def test_state_api_lists(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="state_test_actor").remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    refs = [f.remote(i) for i in range(5)]
    assert ray_tpu.get(refs) == [1, 2, 3, 4, 5]
    time.sleep(1.5)  # task-event flush interval

    nodes = state_api.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"

    actors = state_api.list_actors()
    assert any(x["name"] == "state_test_actor" for x in actors)
    alive = state_api.list_actors(filters=[("state", "=", "ALIVE")])
    assert all(x["state"] == "ALIVE" for x in alive)

    tasks = state_api.list_tasks()
    f_tasks = [t for t in tasks if t["name"] == "f"]
    assert len(f_tasks) == 5
    assert all(t["state"] == "FINISHED" for t in f_tasks)

    workers = state_api.list_workers()
    assert len(workers) >= 1
    assert all(w["pid"] for w in workers)

    summary = state_api.summarize_tasks()
    assert summary["summary"]["f"]["FINISHED"] == 5

    a_sum = state_api.summarize_actors()
    assert a_sum["total_actors"] >= 1


def test_timeline(ray_start_regular, tmp_path):
    import ray_tpu
    from ray_tpu.util.state import timeline

    @ray_tpu.remote
    def work():
        time.sleep(0.05)
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    time.sleep(1.5)
    out = tmp_path / "timeline.json"
    events = timeline(str(out))
    spans = [e for e in events if e["name"] == "work"]
    assert len(spans) == 3
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in spans)
    assert json.loads(out.read_text())


def test_timeline_profile_events(shutdown_only, monkeypatch):
    """With profiling on, worker-side phase spans (deserialize/execute/
    store) appear in the chrome timeline (reference: RAY_PROFILING)."""
    monkeypatch.setenv("RAY_TPU_TASK_PROFILE_EVENTS", "1")
    import ray_tpu
    from ray_tpu.util.state import timeline

    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def work():
        time.sleep(0.03)
        return 1

    ray_tpu.get([work.remote() for _ in range(2)])
    time.sleep(1.5)
    events = timeline()
    phases = [e for e in events if e["cat"] == "profile"]
    names = {e["name"] for e in phases}
    assert "work::execute" in names, names
    ex = [e for e in phases if e["name"] == "work::execute"]
    assert all(e["dur"] >= 0.02 * 1e6 for e in ex)


def test_job_submission(ray_start_regular):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\""
    )
    status = client.wait_until_finish(sid, timeout_s=60)
    assert status == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(sid)
    jobs = client.list_jobs()
    assert any(j.submission_id == sid for j in jobs)


def test_job_failure_and_env(ray_start_regular):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import os;print(os.environ['MY_VAR']);raise SystemExit(3)\"",
        runtime_env={"env_vars": {"MY_VAR": "xyz123"}},
    )
    status = client.wait_until_finish(sid, timeout_s=60)
    assert status == JobStatus.FAILED
    info = client.get_job_info(sid)
    assert "exit code 3" in info.message
    assert "xyz123" in client.get_job_logs(sid)


def test_job_runs_cluster_workload(ray_start_regular):
    """A submitted job connects back to the same cluster via RAY_TPU_ADDRESS."""
    from ray_tpu.job import JobStatus, JobSubmissionClient

    script = (
        "import ray_tpu; ray_tpu.init(address='auto'); "
        "f = ray_tpu.remote(lambda x: x * 3); "
        "print('job-result', ray_tpu.get(f.remote(14))); ray_tpu.shutdown()"
    )
    client = JobSubmissionClient()
    sid = client.submit_job(entrypoint=f"{sys.executable} -c \"{script}\"")
    status = client.wait_until_finish(sid, timeout_s=120)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job-result 42" in logs


def test_dashboard_endpoints(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.dashboard.dashboard import Dashboard

    @ray_tpu.remote
    def g():
        return 1

    ray_tpu.get(g.remote())
    time.sleep(1.5)

    gcs_addr = worker_mod.global_worker.node.gcs_addr
    dash = Dashboard(gcs_addr, port=0)
    host, port = worker_mod.global_worker.run_async(dash.start())
    base = f"http://{host}:{port}"
    try:
        assert urllib.request.urlopen(f"{base}/-/healthz").read() == b"success"
        index = urllib.request.urlopen(base).read().decode()
        assert "ray_tpu dashboard" in index
        nodes = json.loads(urllib.request.urlopen(f"{base}/api/nodes").read())
        assert len(nodes["nodes"]) == 1
        summary = json.loads(
            urllib.request.urlopen(f"{base}/api/tasks/summary").read()
        )
        assert summary["summary"].get("g", {}).get("FINISHED") == 1
        status = json.loads(
            urllib.request.urlopen(f"{base}/api/cluster_status").read()
        )
        assert "nodes" in status
        events = json.loads(
            urllib.request.urlopen(f"{base}/api/events?limit=50").read()
        )
        assert any(e["label"] == "NODE_ADDED" for e in events["events"])
        sev = json.loads(
            urllib.request.urlopen(
                f"{base}/api/events?severity=ERROR"
            ).read()
        )
        assert all(e["severity"] == "ERROR" for e in sev["events"])
        pgs = json.loads(
            urllib.request.urlopen(f"{base}/api/placement_groups").read()
        )
        assert "pgs" in pgs
    finally:
        worker_mod.global_worker.run_async(dash.stop())


def test_cli_parser():
    from ray_tpu.scripts.cli import build_parser

    p = build_parser()
    args = p.parse_args(["job", "submit", "--wait", "echo", "hi"])
    assert args.job_cmd == "submit" and args.wait
    args = p.parse_args(["list", "actors", "--limit", "5"])
    assert args.kind == "actors" and args.limit == 5
    args = p.parse_args(["start", "--head", "--num-cpus", "4"])
    assert args.head and args.num_cpus == 4.0
