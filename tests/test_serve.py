"""Serve tests (analog of python/ray/serve/tests: basic deploy, handles,
composition, HTTP ingress, autoscaling config, redeploy, replica recovery)."""

import json
import time
import urllib.request

import pytest


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def _http_get(url: str, timeout: float = 10.0) -> bytes:
    return urllib.request.urlopen(url, timeout=timeout).read()


def test_deploy_and_handle(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

        def triple(self, x):
            return x * 3

    handle = serve.run(Doubler.bind(), route_prefix=None)
    assert handle.remote(21).result(timeout_s=30) == 42
    # Method routing via attribute access.
    assert handle.triple.remote(10).result(timeout_s=30) == 30


def test_multiple_replicas_and_status(serve_cluster):
    serve = serve_cluster

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            import os

            return (os.getpid(), x)

    handle = serve.run(Echo.bind(), route_prefix=None)
    pids = {handle.remote(i).result(timeout_s=30)[0] for i in range(20)}
    assert len(pids) == 2, f"expected both replicas used, saw pids {pids}"

    st = serve.status()
    app = st["default"]
    assert app["status"] == "RUNNING"
    dep = app["deployments"]["Echo"]
    assert dep["replica_states"]["RUNNING"] == 2


def test_model_composition(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def __call__(self, x):
            return x + self.increment

    @serve.deployment
    class Combiner:
        def __init__(self, a, b):
            self.a = a
            self.b = b

        async def __call__(self, x):
            ra = self.a.remote(x)
            rb = self.b.remote(x)
            return (await ra) + (await rb)

    app = Combiner.bind(Adder.bind(1), Adder.options(name="Adder2").bind(2))
    handle = serve.run(app, route_prefix=None)
    # (10+1) + (10+2) = 23
    assert handle.remote(10).result(timeout_s=30) == 23


def test_http_ingress(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class Api:
        def __call__(self, request):
            if request.path.endswith("/json"):
                return {"method": request.method, "q": request.query.get("q")}
            return f"hello {request.text() or 'world'}"

    serve.run(Api.bind(), name="app1", route_prefix="/api")
    http = serve.status()  # ensure running
    assert http["app1"]["status"] == "RUNNING"

    import ray_tpu

    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    cfg = ray_tpu.get(controller.get_http_config.remote())
    base = f"http://{cfg['host']}:{cfg['port']}"

    assert _http_get(f"{base}/-/healthz") == b"success"
    body = _http_get(f"{base}/api/json?q=5")
    assert json.loads(body) == {"method": "GET", "q": "5"}
    assert _http_get(f"{base}/api") == b"hello world"
    with pytest.raises(Exception):
        _http_get(f"{base}/nope")


def test_grpc_ingress_typed(serve_cluster):
    """Typed gRPC proxy: ServeAPIService with proto messages carrying the
    application / method routing (reference: serve.proto
    RayServeAPIService)."""
    serve = serve_cluster
    serve.start(http_options={"host": "127.0.0.1", "port": 0, "grpc_port": 0})

    @serve.deployment
    class Echo:
        def __call__(self, payload: bytes):
            return b"echo:" + payload

        def shout(self, payload: bytes):
            return payload.upper().decode()  # str -> content_type "text"

    serve.run(Echo.bind(), name="gapp", route_prefix="/gapp")
    import ray_tpu

    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    cfg = ray_tpu.get(controller.get_http_config.remote())
    assert cfg.get("grpc_port"), cfg

    import grpc

    from ray_tpu.serve.protobuf import ServeAPIStub, ServeRequest

    chan = grpc.insecure_channel(f"127.0.0.1:{cfg['grpc_port']}")
    stub = ServeAPIStub(chan)
    reply = stub.Predict(
        ServeRequest(application="gapp", payload=b"hi"), timeout=30
    )
    assert reply.payload == b"echo:hi" and reply.content_type == "bytes"
    reply = stub.Predict(
        ServeRequest(application="gapp", method="shout", payload=b"hi"),
        timeout=30,
    )
    assert reply.payload == b"HI" and reply.content_type == "text"
    with pytest.raises(grpc.RpcError):
        stub.Predict(ServeRequest(application="nope", payload=b"x"), timeout=10)
    chan.close()


def test_streaming_responses_http_and_grpc(serve_cluster):
    """Generator deployments stream: chunked HTTP body and server-streaming
    gRPC, with items forwarded as the replica produces them (reference:
    StreamingResponse + serve.proto streaming rpcs)."""
    serve = serve_cluster
    serve.start(http_options={"host": "127.0.0.1", "port": 0, "grpc_port": 0})

    @serve.deployment
    class StreamEcho:
        def __call__(self, request):
            # Works for both ingresses: HTTPRequest body or raw grpc bytes.
            data = request.body if hasattr(request, "body") else request
            for i in range(3):
                yield b"chunk%d:%s;" % (i, data)

    serve.run(StreamEcho.bind(), name="sapp", route_prefix="/sapp")
    import ray_tpu

    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    cfg = ray_tpu.get(controller.get_http_config.remote())

    # HTTP chunked streaming (opt-in via header).
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{cfg['port']}/sapp",
        data=b"hi",
        headers={"serve-streaming": "1"},
    )
    body = urllib.request.urlopen(req, timeout=30).read()
    assert body == b"chunk0:hi;chunk1:hi;chunk2:hi;"

    # gRPC server-streaming.
    import grpc

    from ray_tpu.serve.protobuf import ServeAPIStub, ServeRequest

    chan = grpc.insecure_channel(f"127.0.0.1:{cfg['grpc_port']}")
    stub = ServeAPIStub(chan)
    replies = list(
        stub.PredictStreaming(
            ServeRequest(application="sapp", payload=b"yo"), timeout=30
        )
    )
    assert [r.payload for r in replies] == [
        b"chunk0:yo;", b"chunk1:yo;", b"chunk2:yo;",
    ]
    assert all(r.content_type == "bytes" for r in replies)
    chan.close()


def test_multiplexed_model_routing(serve_cluster):
    """@serve.multiplexed caches per-model loads with LRU and the router
    keeps a model's requests sticky to its replica."""
    serve = serve_cluster

    @serve.deployment(num_replicas=2)
    class MM:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def load(self, model_id: str):
            self.loads.append(model_id)
            return f"model-{model_id}"

        async def __call__(self, payload):
            mid = serve.get_multiplexed_model_id()
            model = await self.load(mid)
            return {"model": model, "loads": len(self.loads)}

    serve.run(MM.bind(), name="mm", route_prefix="/mm")
    handle = serve.get_app_handle("mm")
    r1 = handle.options(multiplexed_model_id="a").remote("x").result(timeout_s=60)
    assert r1["model"] == "model-a"
    # Same model id again: cache hit on the SAME replica (loads unchanged).
    r2 = handle.options(multiplexed_model_id="a").remote("x").result(timeout_s=60)
    assert r2["model"] == "model-a" and r2["loads"] == r1["loads"]


def test_redeploy_and_delete(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class V:
        def __call__(self, _):
            return "v1"

    serve.run(V.bind(), route_prefix=None)
    h = serve.get_app_handle()
    assert h.remote(None).result(timeout_s=30) == "v1"

    @serve.deployment(name="V")
    class V2:
        def __call__(self, _):
            return "v2"

    serve.run(V2.bind(), route_prefix=None)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if serve.get_app_handle().remote(None).result(timeout_s=30) == "v2":
            break
        time.sleep(0.2)
    assert serve.get_app_handle().remote(None).result(timeout_s=30) == "v2"

    serve.delete("default")
    assert "default" not in serve.status()


def test_autoscaling_scales_up(serve_cluster):
    serve = serve_cluster

    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1.0,
            upscale_delay_s=0.5,
            look_back_period_s=2.0,
            metrics_interval_s=0.2,
        ),
        max_ongoing_requests=2,
    )
    class Slow:
        async def __call__(self, _):
            import asyncio

            await asyncio.sleep(0.4)
            return "ok"

    handle = serve.run(Slow.bind(), route_prefix=None)
    # Flood with concurrent requests to trigger upscale.
    responses = [handle.remote(None) for _ in range(24)]
    for r in responses:
        assert r.result(timeout_s=60) == "ok"
    deadline = time.monotonic() + 20
    saw = 1
    while time.monotonic() < deadline:
        dep = serve.status()["default"]["deployments"]["Slow"]
        saw = max(saw, dep["target_replicas"])
        if saw > 1:
            break
        responses = [handle.remote(None) for _ in range(12)]
        for r in responses:
            r.result(timeout_s=60)
    assert saw > 1, "autoscaler never scaled up"


def test_replica_recovery_after_kill(serve_cluster):
    serve = serve_cluster
    import ray_tpu

    @serve.deployment
    class Sturdy:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Sturdy.bind(), route_prefix=None)
    assert handle.remote(1).result(timeout_s=30) == 2

    # Kill the replica actor out from under the controller.
    st = serve.status()
    assert st["default"]["deployments"]["Sturdy"]["replica_states"]["RUNNING"] == 1
    names = [
        a
        for a in ray_tpu.get(
            ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
            .get_serve_status.remote()
        )
    ]
    # Find replica actor by its registered name prefix.
    import ray_tpu._private.worker as worker_mod

    reply = worker_mod.global_worker.run_async(
        worker_mod._core().gcs.call("ListNamedActors", {"namespace": "serve"})
    )
    replica_names = [
        n for n in reply.get("names", []) if n.startswith("SERVE_REPLICA::")
    ]
    assert replica_names, f"no replica actors registered: {reply}"
    victim = ray_tpu.get_actor(replica_names[0], namespace="serve")
    ray_tpu.kill(victim)

    # Controller should notice (health checks) and start a replacement.
    deadline = time.monotonic() + 60
    ok = False
    while time.monotonic() < deadline:
        try:
            if handle.remote(5).result(timeout_s=10) == 6:
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok, "service did not recover after replica kill"


def test_sse_streaming_via_accept_header(serve_cluster):
    """Accept: text/event-stream negotiates standards-compliant SSE framing
    — every yielded item becomes one `data:` event an EventSource client
    can parse (reference: serve streaming + SSE integrations)."""
    serve = serve_cluster
    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    @serve.deployment
    class Tokens:
        def __call__(self, request):
            yield "hello"
            yield {"k": 1}
            yield "multi\nline"

    serve.run(Tokens.bind(), name="sse", route_prefix="/sse")
    import ray_tpu

    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    cfg = ray_tpu.get(controller.get_http_config.remote())

    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{cfg['port']}/sse",
        data=b"x",
        headers={"Accept": "text/event-stream"},
    )
    resp = urllib.request.urlopen(req, timeout=30)
    assert resp.headers["Content-Type"].startswith("text/event-stream")
    body = resp.read().decode()
    # SSE framing: one event per yield, blank-line separated; the multiline
    # item becomes consecutive data: lines of ONE event.
    events = [e for e in body.split("\n\n") if e]
    assert events[0] == "data: hello"
    assert events[1] == 'data: {"k": 1}'
    assert events[2] == "data: multi\ndata: line"
