"""PBT, synchronous HyperBand, and class-Trainable tests (reference:
python/ray/tune/tests/test_trial_scheduler_pbt.py, test_trial_scheduler.py,
test_trainable.py)."""

import json
import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig


@pytest.fixture
def ray6(shutdown_only):
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield


def _quadratic_cls():
    """Defined inside a function so cloudpickle ships the class by value
    (trial workers cannot import this test module)."""

    class _Quadratic(tune.Trainable):
        """score grows by `lr` each step — higher lr is strictly better, so
        PBT should migrate the population toward the best lr."""

        def setup(self, config):
            self.lr = config["lr"]
            self.score = 0.0

        def step(self):
            import time

            # Slow enough that the population advances in overlapping poll
            # rounds — PBT/HyperBand compare trials at the same iteration.
            time.sleep(0.2)
            self.score += self.lr
            return {"score": self.score, "lr": self.lr}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"score": self.score}, f)

        def load_checkpoint(self, d):
            with open(os.path.join(d, "state.json")) as f:
                self.score = json.load(f)["score"]

    return _Quadratic


def test_pbt_perturbs_and_forks(ray6, tmp_path):
    sched = tune.PopulationBasedTraining(
        time_attr="training_iteration",
        perturbation_interval=2,
        hyperparam_mutations={"lr": (0.1, 10.0)},
        quantile_fraction=0.25,
        seed=7,
    )
    tuner = tune.Tuner(
        _quadratic_cls(),
        param_space={"lr": tune.grid_search([0.1, 0.5, 5.0, 9.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=sched, num_samples=1
        ),
        run_config=RunConfig(
            name="pbt",
            storage_path=str(tmp_path),
            stop={"training_iteration": 12},
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    # The scheduler provably perturbed (exploit/explore fired)...
    assert sched.num_perturbations >= 1
    trials = grid._trials
    perturbed = [t for t in trials if t.num_perturbations > 0]
    assert perturbed, "no trial was restarted with an exploited config"
    # ...and the fork actually adopted donor state: a perturbed trial's
    # score history jumps to donor level (score >> what its original lr
    # could have produced by that iteration) or its lr changed.
    for t in perturbed:
        assert t.config["lr"] != pytest.approx(
            {0.1: 0.1, 0.5: 0.5, 5.0: 5.0, 9.0: 9.0}.get(t.config["lr"], -1)
        ) or t.checkpoint_path is not None
    best = grid.get_best_result()
    # Population converged toward high-lr configs: the winner must beat what
    # the two weak starting lrs (0.1, 0.5) could ever reach in 12 steps.
    assert best.metrics["score"] > 0.5 * 12


def test_hyperband_synchronous_halving(ray6, tmp_path):
    sched = tune.HyperBandScheduler(
        time_attr="training_iteration",
        max_t=9,
        grace_period=3,
        reduction_factor=3,
    )
    tuner = tune.Tuner(
        _quadratic_cls(),
        param_space={"lr": tune.grid_search([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=sched, num_samples=1
        ),
        run_config=RunConfig(
            name="hb",
            storage_path=str(tmp_path),
            stop={"training_iteration": 9},
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    trials = grid._trials
    stopped = [t for t in trials if t.early_stopped]
    survivors = [t for t in trials if not t.early_stopped]
    # 6 trials, eta=3: the rung at t=3 keeps 2, stops 4.
    assert len(stopped) == 4
    assert len(survivors) == 2
    # The survivors are exactly the best configs.
    surv_lrs = sorted(t.config["lr"] for t in survivors)
    assert surv_lrs == [5.0, 6.0]
    # Stopped trials halted at the rung milestone, not later.
    for t in stopped:
        assert t.history[-1]["training_iteration"] == 3
    # Survivors resumed from checkpoints and ran to the stop criterion with
    # continuous score (checkpoint restore preserved state).
    for t in survivors:
        assert t.history[-1]["training_iteration"] == 9
        assert t.history[-1]["score"] == pytest.approx(9 * t.config["lr"])


def test_class_trainable_save_restore(ray6, tmp_path):
    tuner = tune.Tuner(
        _quadratic_cls(),
        param_space={"lr": tune.grid_search([2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="cls1",
            storage_path=str(tmp_path),
            stop={"training_iteration": 3},
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.metrics["score"] == pytest.approx(6.0)
    assert best.checkpoint is not None
    # Checkpoint holds the trainable's own state file.
    assert os.path.exists(os.path.join(best.checkpoint.path, "state.json"))

    # A fresh run resuming from that checkpoint continues the state.
    trial_ckpt = best.checkpoint.path
    cls = _quadratic_cls()

    def fn(config):
        from ray_tpu.train._checkpoint import Checkpoint

        t = cls(config)
        with Checkpoint(trial_ckpt).as_directory() as d:
            t.load_checkpoint(d)
        out = t.train()
        tune.report(out)

    tuner2 = tune.Tuner(
        fn,
        param_space={"lr": tune.grid_search([2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="cls2", storage_path=str(tmp_path)),
    )
    grid2 = tuner2.fit()
    assert grid2.num_errors == 0
    assert grid2.get_best_result().metrics["score"] == pytest.approx(8.0)
