"""GCETPUNodeProvider state machine + autoscaler slice-gang e2e over a fake
gcloud (reference: autoscaler/_private/gcp/node_provider.py tested via
fake_multi_node-style injection)."""

import subprocess

import pytest

from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.node_provider import (
    FAILED,
    PROVISIONING,
    READY,
    REQUESTED,
    TERMINATING,
    GCETPUNodeProvider,
    NodeCreateError,
)


class FakeGcloud:
    """Models gcloud tpu-vm lifecycle: async creates take `provision_polls`
    describes to reach READY; deletes disappear after one describe; creates
    can be told to fail N times (transient) or specific nodes can be made to
    vanish mid-provision."""

    def __init__(self, provision_polls: int = 2):
        self.provision_polls = provision_polls
        self.nodes = {}  # name -> {"state", "polls_left"}
        self.calls = []
        self.fail_next_creates = 0

    def __call__(self, cmd):
        self.calls.append(cmd)
        verb = cmd[4]  # gcloud compute tpus tpu-vm <verb> <name> ...
        name = cmd[5]
        if verb == "create":
            if self.fail_next_creates > 0:
                self.fail_next_creates -= 1
                raise subprocess.CalledProcessError(1, cmd, "quota exceeded")
            self.nodes[name] = {
                "state": "CREATING",
                "polls_left": self.provision_polls,
            }
            return ""
        if verb == "delete":
            if name in self.nodes:
                self.nodes[name]["state"] = "DELETING"
                self.nodes[name]["polls_left"] = 1
            return ""
        if verb == "describe":
            info = self.nodes.get(name)
            if info is None:
                raise subprocess.CalledProcessError(1, cmd, "NOT_FOUND")
            if info["state"] == "DELETING":
                info["polls_left"] -= 1
                if info["polls_left"] < 0:
                    del self.nodes[name]
                    raise subprocess.CalledProcessError(1, cmd, "NOT_FOUND")
                return "DELETING"  # pre-deletion describe still answers
            if info["state"] == "CREATING":
                info["polls_left"] -= 1
                if info["polls_left"] <= 0:
                    info["state"] = "READY"
                return info["state"] if info["state"] == "READY" else "CREATING"
            return info["state"]
        raise AssertionError(f"unexpected gcloud verb {verb}")

    def vanish(self, name):
        self.nodes.pop(name, None)


def _provider(gcloud, **node_types):
    return GCETPUNodeProvider(
        project="proj",
        zone="us-central2-b",
        accelerator_type="v5litepod-8",
        node_types=node_types or None,
        runner=gcloud,
    )


def test_state_machine_provision_and_terminate():
    g = FakeGcloud(provision_polls=2)
    p = _provider(g)
    pid = p.create_node("worker")
    assert p.node_state(pid) == REQUESTED
    p.poll()
    assert p.node_state(pid) == PROVISIONING
    p.poll()
    assert p.node_state(pid) == READY
    assert p.ready_nodes() == [pid]
    p.terminate_node(pid)
    assert p.node_state(pid) == TERMINATING
    assert p.non_terminated_nodes() == []
    p.poll()  # DELETING still answering
    p.poll()  # NOT_FOUND -> dropped
    assert p.node_state(pid) is None


def test_create_retries_transient_failures():
    g = FakeGcloud()
    g.fail_next_creates = 2
    p = _provider(g)
    pid = p.create_node("worker")  # third attempt succeeds
    assert p._nodes[pid]["create_attempts"] == 3
    create_calls = [c for c in g.calls if c[4] == "create"]
    assert len(create_calls) == 3
    # All retries reuse the SAME name (no duplicate half-created nodes).
    assert len({c[5] for c in create_calls}) == 1


def test_create_fails_after_exhausting_retries():
    g = FakeGcloud()
    g.fail_next_creates = 99
    p = _provider(g)
    with pytest.raises(NodeCreateError):
        p.create_node("worker")
    assert p.non_terminated_nodes() == []


def test_vanished_node_marked_failed_after_grace():
    g = FakeGcloud(provision_polls=10)
    p = _provider(g)
    pid = p.create_node("worker")
    p.poll()
    g.vanish(pid)
    # --async creates may lag visibility: a few describe misses are
    # tolerated before the node is declared lost.
    for _ in range(3):
        p.poll()
        assert p.node_state(pid) == PROVISIONING
    p.poll()
    assert p.node_state(pid) == FAILED
    assert p.failed_nodes() == [pid]
    assert pid not in p.non_terminated_nodes()
    # FAILED is terminal: no more gcloud describes are spent on it.
    before = len([c for c in g.calls if c[4] == "describe"])
    p.poll()
    after = len([c for c in g.calls if c[4] == "describe"])
    assert before == after


def test_create_adopts_already_exists():
    g = FakeGcloud()

    real = g.__call__

    def flaky(cmd):
        if cmd[4] == "create":
            real(cmd)  # server-side acceptance...
            raise subprocess.CalledProcessError(
                1, cmd, "ERROR: resource already exists"
            )  # ...but the client errors
        return real(cmd)

    p = GCETPUNodeProvider(
        project="p", zone="z", runner=flaky, create_retries=3
    )
    pid = p.create_node("worker")
    assert p.node_state(pid) == REQUESTED  # adopted, not failed
    assert len([c for c in g.calls if c[4] == "create"]) == 1


def test_terminate_failure_keeps_tracker_for_retry():
    g = FakeGcloud(provision_polls=0)
    p = _provider(g)
    pid = p.create_node("worker")
    p.poll()
    real = g.__call__
    fail_delete = {"on": True}

    def flaky(cmd):
        if cmd[4] == "delete" and fail_delete["on"]:
            raise subprocess.CalledProcessError(1, cmd, "backend error")
        return real(cmd)

    p._runner = flaky
    assert p.terminate_node(pid) is False
    assert p.node_state(pid) == READY  # unchanged; still tracked
    fail_delete["on"] = False
    assert p.terminate_node(pid) is True
    assert p.node_state(pid) == TERMINATING
    # Idempotent retry while deleting is a cheap no-op success.
    assert p.terminate_node(pid) is True


def test_autoscaler_scales_slice_gang_up_and_down(monkeypatch):
    """E2E against the fake gcloud: gang demand launches a whole 2-host
    slice, a host lost mid-provision is repaired in place, the slice reaches
    READY, and idle timeout terminates the gang together."""
    g = FakeGcloud(provision_polls=1)
    p = _provider(
        g,
        v5e_slice={
            "resources": {"TPU": 4.0, "TPU-v5litepod-8-head": 1.0},
            "tpu_pod_slice": "v5litepod-8",
            "workers_per_slice": 2,
            "min_workers": 0,
            "max_workers": 4,
        },
    )
    scaler = Autoscaler(
        p, AutoscalerConfig(upscale_delay_s=0.0, idle_timeout_s=0.05)
    )

    demand = {"pending": 0, "demands": []}

    def fake_state(self):
        stats = [
            {
                "node_id": "head",
                "pending_leases": demand["pending"],
                "pending_demand": demand["demands"],
                "num_workers": 0,
                "num_idle": 0,
            }
        ]
        return demand["pending"], stats

    monkeypatch.setattr(Autoscaler, "_cluster_state", fake_state)

    from ray_tpu._private.common import RESOURCE_UNIT

    # Gang demand appears: one lease wanting the slice-head resource.
    demand["pending"] = 1
    demand["demands"] = [
        {"TPU-v5litepod-8-head": 1 * RESOURCE_UNIT, "TPU": 4 * RESOURCE_UNIT}
    ]
    # Demand must be sustained past the upscale delay: round 1 records it,
    # round 2 launches.
    launched_total = scaler.update()["launched"] + scaler.update()["launched"]
    assert launched_total == 2, "whole slice gang must launch together"
    launched = p.non_terminated_nodes()
    assert len(launched) == 2

    # One host dies mid-provision; after the describe-miss grace period a
    # later round repairs it in place.
    g.vanish(launched[0])
    demand["pending"] = 0
    demand["demands"] = []
    for _ in range(5):  # 4 misses to FAILED + 1 repair round
        scaler.update()
    tracked = list(scaler._tracked.values())[0]
    assert len(tracked.provider_node_ids) == 2
    assert launched[0] not in tracked.provider_node_ids
    assert launched[1] in tracked.provider_node_ids

    # Subsequent polls bring the full gang to READY.
    for _ in range(4):
        p.poll()
    assert len(p.ready_nodes()) == 2

    # Idle long enough -> the whole slice terminates together.
    import time

    time.sleep(0.1)
    out = scaler.update()
    assert out["terminated"] == 2
    for _ in range(4):
        p.poll()
    assert p.non_terminated_nodes() == []
    assert not g.nodes, "fake gcloud still holds nodes after gang teardown"
