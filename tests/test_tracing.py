"""Task tracing spans with context propagation (reference:
python/ray/util/tracing/tracing_helper.py — spans injected into TaskSpec,
parent-child linkage across submit/execute boundaries)."""

import time

import pytest

import ray_tpu
from ray_tpu.util.state import api as state_api


@pytest.fixture
def traced_cluster(monkeypatch, shutdown_only):
    monkeypatch.setenv("RAY_TPU_TASK_TRACE_SPANS", "1")
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield


def _spans_by_kind(spans):
    return (
        {s["task_id"]: s for s in spans if s["kind"] == "submit"},
        {s["task_id"]: s for s in spans if s["kind"] == "execute"},
    )


def _wait_spans(min_count, trace_id=None, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        spans = state_api.list_spans(trace_id)
        if len(spans) >= min_count:
            return spans
        time.sleep(0.25)
    raise AssertionError(
        f"expected >={min_count} spans, got {state_api.list_spans(trace_id)}"
    )


def test_parent_child_spans_across_task_chain(traced_cluster):
    """Driver submits `outer`, which submits `inner`: all four spans share
    one trace id and link parent->child across the process boundaries."""

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(1)) == 20
    spans = _wait_spans(4)
    submits, executes = _spans_by_kind(spans)
    # Identify the tasks by name.
    outer_exec = next(s for s in executes.values() if s["name"] == "outer")
    inner_exec = next(s for s in executes.values() if s["name"] == "inner")
    outer_sub = submits[outer_exec["task_id"]]
    inner_sub = submits[inner_exec["task_id"]]

    # One trace end to end.
    tid = outer_sub["trace_id"]
    assert tid and all(
        s["trace_id"] == tid
        for s in (outer_exec, inner_sub, inner_exec)
    )
    # Driver-side submit of `outer` is the root.
    assert outer_sub["parent_span_id"] is None
    # execute(outer) is a child of submit(outer).
    assert outer_exec["parent_span_id"] == outer_sub["span_id"]
    # submit(inner) happened INSIDE execute(outer) on the worker.
    assert inner_sub["parent_span_id"] == outer_exec["span_id"]
    # execute(inner) is a child of submit(inner).
    assert inner_exec["parent_span_id"] == inner_sub["span_id"]
    # Execute spans carry durations.
    assert inner_exec["duration"] >= 0.0


def test_actor_method_spans(traced_cluster):
    @ray_tpu.remote
    class A:
        def work(self, x):
            return x * 2

    a = A.remote()
    assert ray_tpu.get(a.work.remote(3)) == 6
    spans = _wait_spans(2)
    submits, executes = _spans_by_kind(spans)
    ex = next(s for s in executes.values() if s["name"] == "work")
    sub = submits[ex["task_id"]]
    assert ex["parent_span_id"] == sub["span_id"]
    assert ex["trace_id"] == sub["trace_id"]


def test_spans_in_timeline(traced_cluster):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    _wait_spans(2)
    events = state_api.timeline()
    span_events = [e for e in events if e["cat"] == "span"]
    assert span_events, "timeline must export span events"
    ev = span_events[0]
    assert ev["args"]["trace_id"] and ev["args"]["span_id"]


def test_tracing_disabled_by_default(shutdown_only):
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    time.sleep(1.0)
    assert state_api.list_spans() == []
