"""Task tracing spans with context propagation (reference:
python/ray/util/tracing/tracing_helper.py — spans injected into TaskSpec,
parent-child linkage across submit/execute boundaries)."""

import time

import pytest

import ray_tpu
from ray_tpu.util.state import api as state_api


@pytest.fixture
def traced_cluster(monkeypatch, shutdown_only):
    monkeypatch.setenv("RAY_TPU_TASK_TRACE_SPANS", "1")
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield


def _spans_by_kind(spans):
    return (
        {s["task_id"]: s for s in spans if s["kind"] == "submit"},
        {s["task_id"]: s for s in spans if s["kind"] == "execute"},
    )


def _wait_spans(min_count, trace_id=None, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        spans = state_api.list_spans(trace_id)
        if len(spans) >= min_count:
            return spans
        time.sleep(0.25)
    raise AssertionError(
        f"expected >={min_count} spans, got {state_api.list_spans(trace_id)}"
    )


def test_parent_child_spans_across_task_chain(traced_cluster):
    """Driver submits `outer`, which submits `inner`: all four spans share
    one trace id and link parent->child across the process boundaries."""

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(1)) == 20
    spans = _wait_spans(4)
    submits, executes = _spans_by_kind(spans)
    # Identify the tasks by name.
    outer_exec = next(s for s in executes.values() if s["name"] == "outer")
    inner_exec = next(s for s in executes.values() if s["name"] == "inner")
    outer_sub = submits[outer_exec["task_id"]]
    inner_sub = submits[inner_exec["task_id"]]

    # One trace end to end.
    tid = outer_sub["trace_id"]
    assert tid and all(
        s["trace_id"] == tid
        for s in (outer_exec, inner_sub, inner_exec)
    )
    # Driver-side submit of `outer` is the root.
    assert outer_sub["parent_span_id"] is None
    # execute(outer) is a child of submit(outer).
    assert outer_exec["parent_span_id"] == outer_sub["span_id"]
    # submit(inner) happened INSIDE execute(outer) on the worker.
    assert inner_sub["parent_span_id"] == outer_exec["span_id"]
    # execute(inner) is a child of submit(inner).
    assert inner_exec["parent_span_id"] == inner_sub["span_id"]
    # Execute spans carry durations.
    assert inner_exec["duration"] >= 0.0


def test_actor_method_spans(traced_cluster):
    @ray_tpu.remote
    class A:
        def work(self, x):
            return x * 2

    a = A.remote()
    assert ray_tpu.get(a.work.remote(3)) == 6
    spans = _wait_spans(2)
    submits, executes = _spans_by_kind(spans)
    ex = next(s for s in executes.values() if s["name"] == "work")
    sub = submits[ex["task_id"]]
    assert ex["parent_span_id"] == sub["span_id"]
    assert ex["trace_id"] == sub["trace_id"]


def test_spans_in_timeline(traced_cluster):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    _wait_spans(2)
    events = state_api.timeline()
    span_events = [e for e in events if e["cat"] == "span"]
    assert span_events, "timeline must export span events"
    ev = span_events[0]
    assert ev["args"]["trace_id"] and ev["args"]["span_id"]


def test_tracing_disabled_by_default(shutdown_only):
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    time.sleep(1.0)
    assert state_api.list_spans() == []


# ----------------------------------------------------------- runtime spans


def _wait_until(pred, timeout=25):
    deadline = time.time() + timeout
    spans = []
    while time.time() < deadline:
        spans = state_api.list_spans()
        if pred(spans):
            return spans
        time.sleep(0.25)
    raise AssertionError(
        f"condition not met; have {sorted({(s['name'], s['kind']) for s in spans})}"
    )


def _assert_connected(trace):
    """Every span in the trace reaches a root through parent links that
    stay inside the trace (roots are spans whose parent is unrecorded)."""
    ids = {s["span_id"]: s for s in trace if s.get("span_id")}
    for s in trace:
        hops, cur = 0, s
        while cur.get("parent_span_id") in ids:
            cur = ids[cur["parent_span_id"]]
            hops += 1
            assert hops < len(trace) + 1, "parent cycle"


def test_task_trace_includes_lease_lifecycle(traced_cluster):
    """One task chain yields ONE connected trace spanning >= 3 processes
    with the raylet's lease lifecycle (request->queue->grant), the arg
    fetch, and the execute spans all parented into it."""

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(1)) == 20
    spans = _wait_until(
        lambda ss: {"lease", "execute", "arg_fetch"}
        <= {s["kind"] for s in ss}
    )
    traces = {s["trace_id"] for s in spans}
    assert len(traces) == 1, f"expected one trace, got {traces}"
    names = {s["name"] for s in spans}
    assert {"raylet.lease", "lease.queue", "lease.grant"} <= names, names
    _assert_connected(spans)
    # Driver, raylet/GCS, and at least one worker reported into the trace.
    assert len({s.get("worker_id") for s in spans}) >= 3, spans


def test_serve_request_single_connected_trace(monkeypatch, shutdown_only):
    """A cross-process serve request produces ONE connected trace: the
    router's request root, admission, per-item batch-queue wait, batched
    execution, and the replica-side actor-method execute span."""
    monkeypatch.setenv("RAY_TPU_TASK_TRACE_SPANS", "1")
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, num_tpus=0)
    try:

        @serve.deployment(
            num_replicas=1,
            max_ongoing_requests=16,
            max_batch_size=4,
            batch_wait_timeout_s=0.05,
        )
        class Tripler:
            async def __call__(self, batch):
                return [b * 3 for b in batch]

        handle = serve.run(Tripler.bind(), route_prefix=None)
        responses = [handle.remote(i) for i in range(4)]
        assert [r.result(timeout_s=30) for r in responses] == [0, 3, 6, 9]

        spans = _wait_until(
            lambda ss: {"serve.admission", "serve.batch_wait", "serve.batch_execute"}
            <= {s["name"] for s in ss}
        )
        roots = [s for s in spans if s["name"].startswith("serve.request::")]
        assert roots, f"no serve root span: {[s['name'] for s in spans]}"
        tid = roots[0]["trace_id"]
        trace = [s for s in spans if s["trace_id"] == tid]
        names = {s["name"] for s in trace}
        assert {"serve.admission", "serve.batch_wait"} <= names, names
        kinds = {s["kind"] for s in trace}
        assert "execute" in kinds, kinds  # replica-side method execution
        _assert_connected(trace)
        # Router (driver) and the replica worker both reported in.
        assert len({s.get("worker_id") for s in trace}) >= 2, trace
    finally:
        serve.shutdown()


def test_sampling_deterministic(monkeypatch):
    """Sampling is a pure function of (key, rate): every process agrees,
    repeated calls agree, and the sampled fraction tracks the rate."""
    from ray_tpu.util import tracing

    monkeypatch.setattr(tracing.config, "task_trace_spans", False)
    monkeypatch.setattr(tracing.config, "trace_sample_rate", 0.3)
    keys = [f"task-{i:05d}" for i in range(2000)]
    first = [tracing._sample(k) for k in keys]
    assert first == [tracing._sample(k) for k in keys]
    frac = sum(first) / len(first)
    assert 0.2 < frac < 0.4, frac
    monkeypatch.setattr(tracing.config, "trace_sample_rate", 1.0)
    assert all(tracing._sample(k) for k in keys)
    monkeypatch.setattr(tracing.config, "trace_sample_rate", 0.0)
    assert not any(tracing._sample(k) for k in keys)


def test_sampled_mode_traces_end_to_end(monkeypatch, shutdown_only):
    """trace_sample_rate=1.0 without task_trace_spans: sampled always-on
    mode still assembles complete traces."""
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE_RATE", "1.0")
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2
    spans = _wait_spans(2)
    assert {s["kind"] for s in spans} >= {"submit", "execute"}
    assert len({s["trace_id"] for s in spans}) == 1


def test_worker_exit_flushes_spans(monkeypatch, shutdown_only):
    """Runtime spans buffered in a worker survive its managed exit: with
    the periodic flusher disabled, handle_exit's final ReportSpans is the
    only delivery path."""
    monkeypatch.setenv("RAY_TPU_TELEMETRY_FLUSH_INTERVAL_S", "0")
    monkeypatch.setenv("RAY_TPU_TASK_TRACE_SPANS", "1")
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import tracing

    tracing.reset_flusher_for_test()
    tracing.reset()
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def leak_span():
        from ray_tpu.util import tracing as t

        t.record_span("test.exit_span", "test", time.time(), 0.001)
        return 1

    assert ray_tpu.get(leak_span.remote()) == 1

    w = worker_mod.global_worker
    node = w.node

    async def _exit_workers():
        for wk in list(node.raylet.workers.values()):
            if wk.conn is not None and not wk.conn.closed:
                try:
                    await wk.conn.call("Exit", {}, timeout=10)
                except Exception:
                    pass

    w.run_async(_exit_workers(), timeout=30)
    spans = state_api.list_spans()
    assert any(s["name"] == "test.exit_span" for s in spans), [
        s["name"] for s in spans
    ]


def test_list_spans_gcs_side_filtering(traced_cluster):
    """trace_id filtering and the limit happen in the GCS handler, and the
    result only contains the requested trace."""

    @ray_tpu.remote
    def f(x):
        return x

    assert ray_tpu.get(f.remote(1)) == 1
    assert ray_tpu.get(f.remote(2)) == 2
    spans = _wait_spans(4)
    traces = sorted({s["trace_id"] for s in spans})
    assert len(traces) == 2, traces
    only = state_api.list_spans(trace_id=traces[0])
    assert only and all(s["trace_id"] == traces[0] for s in only)
    assert len(state_api.list_spans(limit=1)) == 1


def test_critical_path_names_dominant(traced_cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(0.3)
        return 1

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(slow.remote())

    assert ray_tpu.get(outer.remote()) == 1
    _wait_spans(4)
    cp = state_api.critical_path()
    assert cp["trace_id"] and cp["total_s"] > 0
    assert cp["path"], cp
    names = [seg["name"] for seg in cp["path"]]
    assert cp["dominant"] in names
    # The chain bottoms out in the sleeping task, so it (or its executor
    # span) dominates self time.
    assert cp["segments"][0]["self_s"] >= 0.2, cp["segments"]


def test_wire_schemas_declare_trace():
    """Every wire schema takes a position on trace propagation, and the
    lint rule catches one that doesn't."""
    from ray_tpu._private import wire
    from ray_tpu.devtools import rpc_check

    assert rpc_check._check_trace_declared() == []
    undeclared = dict(wire.SCHEMAS)
    undeclared["BogusMethod"] = wire.WireSchema(
        frozenset(), frozenset(), wire.RETRY_SAFE, None, None, None
    )
    try:
        wire.SCHEMAS = undeclared
        findings = rpc_check._check_trace_declared()
        assert any(
            f.rule == "wire-trace-undeclared" and "BogusMethod" in f.message
            for f in findings
        ), findings
    finally:
        original = dict(undeclared)
        original.pop("BogusMethod")
        wire.SCHEMAS = original
