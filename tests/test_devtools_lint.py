"""Tests for the devtools static-analysis passes and the runtime probe.

Each lint rule gets at least one positive fixture (must flag) and one
negative fixture (must stay quiet); the rpc_check rules run against
throwaway fixture trees; the aiocheck probe is exercised with a real
two-task interleaving race under ``RAY_TPU_AIOCHECK=1``.
"""

import asyncio
import textwrap

import pytest

from ray_tpu.devtools import aio_lint, lint, rpc_check


def _rules(findings):
    return {f.rule for f in findings}


def _lint(src):
    return aio_lint.lint_source(textwrap.dedent(src), "fixture.py")


# ---------------------------------------------------------------------------
# aio_lint: blocking-call
# ---------------------------------------------------------------------------


def test_blocking_call_positive():
    findings = _lint(
        """
        import time

        async def f():
            time.sleep(1)
        """
    )
    assert aio_lint.RULE_BLOCKING in _rules(findings)


def test_blocking_call_open_builtin_positive():
    findings = _lint(
        """
        async def f(path):
            with open(path) as fh:
                return fh.read()
        """
    )
    assert aio_lint.RULE_BLOCKING in _rules(findings)


def test_blocking_call_negative():
    findings = _lint(
        """
        import asyncio, time

        async def f():
            await asyncio.sleep(1)

        def sync_helper():
            time.sleep(1)  # fine outside async def
        """
    )
    assert aio_lint.RULE_BLOCKING not in _rules(findings)


# ---------------------------------------------------------------------------
# aio_lint: raw-create-task
# ---------------------------------------------------------------------------


def test_raw_create_task_positive():
    findings = _lint(
        """
        import asyncio

        async def f(coro):
            asyncio.create_task(coro)
        """
    )
    assert aio_lint.RULE_CREATE_TASK in _rules(findings)


def test_raw_loop_create_task_positive():
    findings = _lint(
        """
        import asyncio

        async def f(coro):
            asyncio.get_running_loop().create_task(coro)
        """
    )
    assert aio_lint.RULE_CREATE_TASK in _rules(findings)


def test_raw_create_task_negative():
    findings = _lint(
        """
        from ray_tpu._private import rpc

        async def f(coro):
            rpc.spawn(coro)
        """
    )
    assert aio_lint.RULE_CREATE_TASK not in _rules(findings)


# ---------------------------------------------------------------------------
# aio_lint: unawaited-coro
# ---------------------------------------------------------------------------


def test_unawaited_coro_positive():
    findings = _lint(
        """
        async def g():
            return 1

        async def f():
            g()
        """
    )
    assert aio_lint.RULE_UNAWAITED in _rules(findings)


def test_unawaited_coro_negative():
    findings = _lint(
        """
        async def g():
            return 1

        async def f():
            await g()
            t = g()  # bound, not discarded: caller may await/spawn it
            await t
        """
    )
    assert aio_lint.RULE_UNAWAITED not in _rules(findings)


# ---------------------------------------------------------------------------
# aio_lint: await-interleave
# ---------------------------------------------------------------------------

_INTERLEAVE_POSITIVE = """
import asyncio

class Server:
    def __init__(self):
        self.state = {}

    async def handler(self, key):
        val = self.state[key]
        await asyncio.sleep(0)
        self.state[key] = val + 1
"""

_INTERLEAVE_NEGATIVE = """
import asyncio

class Server:
    def __init__(self):
        self.state = {}

    async def handler(self, key):
        val = self.state[key]
        self.state[key] = val + 1  # no await inside the read-write window
        await asyncio.sleep(0)
"""


def test_await_interleave_positive():
    findings = _lint(_INTERLEAVE_POSITIVE)
    assert aio_lint.RULE_INTERLEAVE in _rules(findings)


def test_await_interleave_negative():
    findings = _lint(_INTERLEAVE_NEGATIVE)
    assert aio_lint.RULE_INTERLEAVE not in _rules(findings)


def test_await_interleave_lock_negative():
    findings = _lint(
        """
        import asyncio

        class Server:
            def __init__(self):
                self.state = {}
                self.lock = asyncio.Lock()

            async def handler(self, key):
                async with self.lock:
                    val = self.state[key]
                    await asyncio.sleep(0)
                    self.state[key] = val + 1
        """
    )
    assert aio_lint.RULE_INTERLEAVE not in _rules(findings)


# ---------------------------------------------------------------------------
# aio_lint: inline suppressions
# ---------------------------------------------------------------------------


def test_suppression_same_line():
    findings = _lint(
        """
        import time

        async def f():
            time.sleep(1)  # aio-lint: disable=blocking-call
        """
    )
    assert aio_lint.RULE_BLOCKING not in _rules(findings)


def test_suppression_wrong_rule_does_not_apply():
    findings = _lint(
        """
        import time

        async def f():
            time.sleep(1)  # aio-lint: disable=raw-create-task
        """
    )
    assert aio_lint.RULE_BLOCKING in _rules(findings)


# ---------------------------------------------------------------------------
# rpc_check fixtures
# ---------------------------------------------------------------------------


def _fixture_tree(tmp_path, sources):
    for name, src in sources.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return [str(tmp_path)]


def test_unknown_rpc_method_positive(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call("NoSuchMethod", {})
            """,
        },
    )
    findings = rpc_check.check(paths)
    assert rpc_check.RULE_UNKNOWN in _rules(findings)


def test_unknown_rpc_method_negative(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call("Frobnicate", {})
            """,
            "server.py": """
            def setup(s):
                s.register("Frobnicate", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    assert rpc_check.RULE_UNKNOWN not in _rules(findings)


def test_orphan_handler_positive(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "server.py": """
            def setup(s):
                s.register("DeadEndpoint", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    assert rpc_check.RULE_ORPHAN in _rules(findings)


def test_orphan_handler_wrapper_indirection_negative(tmp_path):
    # The method name appears as a plain string elsewhere (a wrapper builds
    # the call) — lenient mode must not flag it.
    paths = _fixture_tree(
        tmp_path,
        {
            "server.py": """
            def setup(s):
                s.register("WrappedEndpoint", handle)
            """,
            "wrapper.py": """
            async def go(client):
                return await client.invoke("WrappedEndpoint")
            """,
        },
    )
    findings = rpc_check.check(paths)
    assert rpc_check.RULE_ORPHAN not in _rules(findings)


def test_payload_drift_missing_required(tmp_path):
    # KVPut requires key+value per wire.py.
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call("KVPut", {"key": b"k"})
            """,
            "server.py": """
            def setup(s):
                s.register("KVPut", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    drift = [f for f in findings if f.rule == rpc_check.RULE_DRIFT]
    assert drift and "value" in drift[0].message


def test_payload_drift_undeclared_key(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call(
                    "KVPut", {"key": b"k", "value": b"v", "bogus_extra": 1}
                )
            """,
            "server.py": """
            def setup(s):
                s.register("KVPut", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    drift = [f for f in findings if f.rule == rpc_check.RULE_DRIFT]
    assert drift and "bogus_extra" in drift[0].message


def test_payload_drift_negative(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call("KVPut", {"key": b"k", "value": b"v", "ns": ""})
            """,
            "server.py": """
            async def handle(p):
                return p["key"], p["value"], p.get("ns")

            def setup(s):
                s.register("KVPut", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    assert rpc_check.RULE_DRIFT not in _rules(findings)


def test_payload_drift_consumer_side(tmp_path):
    paths = _fixture_tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                await conn.call("KVPut", {"key": b"k", "value": b"v"})
            """,
            "server.py": """
            async def handle(p):
                return p["key"], p["renamed_field"]

            def setup(s):
                s.register("KVPut", handle)
            """,
        },
    )
    findings = rpc_check.check(paths)
    drift = [f for f in findings if f.rule == rpc_check.RULE_DRIFT]
    assert drift and any("renamed_field" in f.message for f in drift)


# ---------------------------------------------------------------------------
# The gate itself
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    """The acceptance criterion: the tree as committed has zero findings."""
    assert lint.main([]) == 0


def test_gate_fails_on_fixture(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(
        "import time\n\nasync def f():\n    time.sleep(1)\n"
    )
    assert lint.main([str(tmp_path)]) == 1
    assert "blocking-call" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Runtime interleaving probe (RAY_TPU_AIOCHECK=1)
# ---------------------------------------------------------------------------


@pytest.fixture
def aiocheck_on(monkeypatch):
    from ray_tpu._private import aiocheck

    monkeypatch.setenv("RAY_TPU_AIOCHECK", "1")
    aiocheck.reset()
    yield aiocheck
    aiocheck.reset()


def test_probe_disabled_returns_plain_dict(monkeypatch):
    from ray_tpu._private import aiocheck

    monkeypatch.delenv("RAY_TPU_AIOCHECK", raising=False)
    d = aiocheck.track("x", {"a": 1})
    assert type(d) is dict and d == {"a": 1}


def test_probe_detects_read_await_write(aiocheck_on):
    aiocheck = aiocheck_on
    d = aiocheck.track("probe.state")

    async def main():
        d["k"] = 0

        async def reader_writer():
            val = d["k"]
            await asyncio.sleep(0.01)  # interleaving window
            d["k"] = val + 1  # stale write-back

        async def interloper():
            await asyncio.sleep(0.005)
            d["k"] = 100

        await asyncio.gather(
            asyncio.create_task(reader_writer(), name="rw"),
            asyncio.create_task(interloper(), name="other"),
        )

    asyncio.run(main())
    kinds = {c.kind for c in aiocheck.conflicts()}
    assert "read-await-write" in kinds


def test_probe_detects_write_write(aiocheck_on):
    aiocheck = aiocheck_on
    d = aiocheck.track("probe.ww")

    async def main():
        async def w1():
            d["z"] = 1

        async def w2():
            await asyncio.sleep(0)
            d["z"] = 2  # blind overwrite of another task's write

        await asyncio.gather(
            asyncio.create_task(w1(), name="w1"),
            asyncio.create_task(w2(), name="w2"),
        )

    asyncio.run(main())
    assert any(
        c.kind == "write-write" and c.key == "z" for c in aiocheck.conflicts()
    )


def test_probe_quiet_on_single_task(aiocheck_on):
    aiocheck = aiocheck_on
    d = aiocheck.track("probe.single")

    async def main():
        d["k"] = 0
        val = d["k"]
        await asyncio.sleep(0)
        d["k"] = val + 1  # same task: interleaving is impossible

    asyncio.run(main())
    assert aiocheck.conflicts() == []


def test_probe_wired_into_gcs(aiocheck_on):
    from ray_tpu._private.aiocheck import TrackedDict
    from ray_tpu._private.gcs import GcsServer

    srv = GcsServer()
    assert isinstance(srv.nodes, TrackedDict)
    assert isinstance(srv.actors, TrackedDict)
    assert isinstance(srv.kv, TrackedDict)


# ---------------------------------------------------------------------------
# aio_lint: await-interleave gaps closed for async-generator yields and
# async comprehensions (both are scheduling points exactly like ``await``)
# ---------------------------------------------------------------------------


def test_interleave_async_generator_yield_positive():
    findings = _lint(
        """
        class Streamer:
            def __init__(self):
                self.state = {}

            async def stream(self, key):
                val = self.state[key]
                yield val  # consumer runs arbitrary code before __anext__
                self.state[key] = val + 1
        """
    )
    assert aio_lint.RULE_INTERLEAVE in _rules(findings)


def test_interleave_async_comprehension_positive():
    findings = _lint(
        """
        class Collector:
            def __init__(self):
                self.state = {}

            async def collect(self, items, key):
                val = self.state[key]
                got = [x async for x in items]
                self.state[key] = val + len(got)
        """
    )
    assert aio_lint.RULE_INTERLEAVE in _rules(findings)


def test_interleave_sync_comprehension_negative():
    findings = _lint(
        """
        class Collector:
            def __init__(self):
                self.state = {}

            async def collect(self, items, key):
                val = self.state[key]
                got = [x for x in items]
                self.state[key] = val + len(got)
                await asyncio.sleep(0)
        """
    )
    assert aio_lint.RULE_INTERLEAVE not in _rules(findings)


def test_interleave_async_for_regression():
    findings = _lint(
        """
        class Consumer:
            def __init__(self):
                self.state = {}

            async def consume(self, source, key):
                val = self.state[key]
                async for item in source:
                    pass
                self.state[key] = val + 1
        """
    )
    assert aio_lint.RULE_INTERLEAVE in _rules(findings)


def test_interleave_async_with_regression():
    findings = _lint(
        """
        class Guard:
            def __init__(self):
                self.state = {}

            async def guarded(self, cm, key):
                val = self.state[key]
                async with cm:
                    pass
                self.state[key] = val + 1
        """
    )
    assert aio_lint.RULE_INTERLEAVE in _rules(findings)


# ---------------------------------------------------------------------------
# aio_lint: shared-attribute footprints (the explorer's DPOR input)
# ---------------------------------------------------------------------------


def test_extract_footprints(tmp_path):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            REGISTRY = {}

            class Worker:
                def __init__(self):
                    self.jobs = []
                    self.done = 0

                def push(self, j):
                    self.jobs.append(j)

                def pull(self):
                    j = self.jobs.pop()
                    self._bump()
                    return j

                def _bump(self):
                    self.done += 1

            def register(name):
                REGISTRY[name] = 1
            """
        )
    )
    fp = aio_lint.extract_footprints([str(tmp_path / "mod.py")])
    assert "self.jobs" in fp["Worker.push"]["writes"]
    # Transitive closure folds _bump's write into pull.
    assert "self.done" in fp["Worker.pull"]["writes"]
    assert "self.jobs" in fp["Worker.pull"]["writes"]
    assert "mod:REGISTRY" in fp["register"]["writes"]


# ---------------------------------------------------------------------------
# lint: stale-suppression audit
# ---------------------------------------------------------------------------


def test_stale_suppression_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        "x = 1  # aio-lint: disable=blocking-call\n"
    )
    findings = lint.audit_suppressions([str(tmp_path)])
    assert [f.rule for f in findings] == [lint.RULE_STALE]


def test_live_suppression_not_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        textwrap.dedent(
            """
            import time

            async def f():
                time.sleep(1)  # aio-lint: disable=blocking-call
            """
        )
    )
    assert lint.audit_suppressions([str(tmp_path)]) == []


def test_suppression_syntax_in_string_not_flagged(tmp_path):
    # Docstrings and message strings mention the waiver syntax without
    # being waivers; only genuine comment tokens are audited.
    (tmp_path / "m.py").write_text(
        'HELP = "waive with # aio-lint: disable=blocking-call"\n'
    )
    assert lint.audit_suppressions([str(tmp_path)]) == []


def test_stale_telemetry_allow_flagged(tmp_path):
    private = tmp_path / "_private"
    private.mkdir()
    (private / "m.py").write_text(
        "y = 2  # telemetry: allow-adhoc-stats\n"
    )
    findings = lint.audit_suppressions([str(tmp_path)])
    assert [f.rule for f in findings] == [lint.RULE_STALE]


# ---------------------------------------------------------------------------
# rpc_check: wire-native-drift
# ---------------------------------------------------------------------------


def _cc_fixture(tmp_path, markers):
    cc = tmp_path / "fastpath.cc"
    cc.write_text("// codec\n" + "\n".join(markers) + "\n")
    return str(cc)


def _native_markers():
    """The markers matching the live wire.NATIVE_WIRE_SCHEMAS registry."""
    from ray_tpu._private import wire

    return [
        f"// NATIVE_WIRE_SCHEMA: {m} v{v} fields={','.join(fields)}"
        for m, (v, fields) in sorted(wire.NATIVE_WIRE_SCHEMAS.items())
    ]


def test_native_drift_clean_registry_negative(tmp_path):
    cc = _cc_fixture(tmp_path, _native_markers())
    assert rpc_check._check_native_wire_drift(cc_path=cc) == []


def test_native_drift_field_mutation_positive(tmp_path):
    """Mutating a natively packed schema's field list without touching the
    C marker must fail lint — simulated by mutating the marker instead."""
    markers = [
        m.replace("dirty,lease_id", "dirty,lease_id,renamed_field")
        for m in _native_markers()
    ]
    findings = rpc_check._check_native_wire_drift(
        cc_path=_cc_fixture(tmp_path, markers)
    )
    assert any(
        f.rule == rpc_check.RULE_NATIVE and "ReturnWorker" in f.message
        for f in findings
    )


def test_native_drift_version_skew_positive(tmp_path):
    markers = [
        m.replace("RequestWorkerLease v1", "RequestWorkerLease v2")
        for m in _native_markers()
    ]
    findings = rpc_check._check_native_wire_drift(
        cc_path=_cc_fixture(tmp_path, markers)
    )
    assert any(
        f.rule == rpc_check.RULE_NATIVE and "version skew" in f.message
        for f in findings
    )


def test_native_drift_missing_marker_positive(tmp_path):
    markers = [m for m in _native_markers() if "PubBatch" not in m]
    findings = rpc_check._check_native_wire_drift(
        cc_path=_cc_fixture(tmp_path, markers)
    )
    assert any(
        f.rule == rpc_check.RULE_NATIVE and "PubBatch" in f.message
        for f in findings
    )


def test_native_drift_stale_marker_positive(tmp_path):
    markers = _native_markers() + [
        "// NATIVE_WIRE_SCHEMA: GhostMethod v1 fields=x"
    ]
    findings = rpc_check._check_native_wire_drift(
        cc_path=_cc_fixture(tmp_path, markers)
    )
    assert any(
        f.rule == rpc_check.RULE_NATIVE and "GhostMethod" in f.message
        for f in findings
    )


def test_native_drift_real_tree_is_clean():
    """The committed fastpath.cc markers must match wire.py exactly."""
    assert rpc_check._check_native_wire_drift() == []
