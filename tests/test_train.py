"""ray_tpu.train tests (reference model: python/ray/train/tests)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import Checkpoint, TrainingFailedError
from ray_tpu.train.base_trainer import _CheckpointManager, _shard_datasets
from ray_tpu.train.jax import JaxTrainer


def test_jax_trainer_reports_and_context(ray_start_regular, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        for i in range(config["steps"]):
            train.report(
                {
                    "step": i,
                    "rank": ctx.get_world_rank(),
                    "world": ctx.get_world_size(),
                }
            )

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_basic", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics["step"] == 2
    assert result.metrics["world"] == 2
    assert result.metrics["rank"] == 0  # driver keeps rank-0 metrics


def test_checkpoint_save_and_resume(ray_start_regular, tmp_path):
    def train_fn(config):
        import json
        import tempfile

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt:
            with ckpt.as_directory() as d:
                start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
        for i in range(start, start + 2):
            if train.get_context().get_world_rank() == 0:
                with tempfile.TemporaryDirectory() as d:
                    json.dump({"step": i}, open(os.path.join(d, "state.json"), "w"))
                    train.report({"step": i}, checkpoint=Checkpoint.from_directory(d))
            else:
                train.report({"step": i})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_ckpt", storage_path=str(tmp_path)),
    )
    r1 = trainer.fit()
    assert r1.checkpoint is not None
    assert os.path.exists(os.path.join(r1.checkpoint.path, "state.json"))

    trainer2 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_ckpt2", storage_path=str(tmp_path)),
        resume_from_checkpoint=r1.checkpoint,
    )
    r2 = trainer2.fit()
    # resumed from step 1 -> steps 2,3
    assert [m["step"] for m in r2.metrics_history] == [2, 3]


def test_collective_across_train_workers(ray_start_regular, tmp_path):
    def train_fn(config):
        from ray_tpu.util import collective

        ctx = train.get_context()
        group = ctx.get_collective_group()
        assert group is not None
        out = collective.allreduce(
            np.array([float(ctx.get_world_rank() + 1)]), group_name=group
        )
        train.report({"sum": float(np.asarray(out)[0])})

    result = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_coll", storage_path=str(tmp_path)),
    ).fit()
    assert result.metrics["sum"] == 3.0  # 1 + 2


def test_failure_raises_training_failed(ray_start_regular, tmp_path):
    def train_fn(config):
        raise ValueError("boom")

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t_fail",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    with pytest.raises(TrainingFailedError, match="boom"):
        trainer.fit()


def test_checkpoint_manager_topk(tmp_path):
    mgr = _CheckpointManager(
        CheckpointConfig(num_to_keep=2, checkpoint_score_attribute="acc")
    )
    paths = []
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        p = tmp_path / f"ckpt_{i}"
        p.mkdir()
        paths.append(str(p))
        mgr.register(str(p), {"acc": acc})
    kept = [c[0] for c in mgr.checkpoints]
    assert str(tmp_path / "ckpt_0") not in kept  # worst dropped
    assert not os.path.exists(paths[0])
    assert mgr.best() == str(tmp_path / "ckpt_1")


def test_shard_datasets_sequences():
    shards = _shard_datasets({"train": [1, 2, 3, 4, 5]}, 2)
    assert shards[0]["train"] == [1, 3, 5]
    assert shards[1]["train"] == [2, 4]


def test_dataset_shard_in_session(ray_start_regular, tmp_path):
    def train_fn(config):
        shard = train.get_dataset_shard("train")
        train.report({"n": len(shard), "total": sum(shard)})

    result = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_ds", storage_path=str(tmp_path)),
        datasets={"train": list(range(10))},
    ).fit()
    assert result.metrics["n"] == 5
