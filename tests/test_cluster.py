"""Multi-node tests via the in-process Cluster harness
(models reference python/ray/tests with ray_start_cluster)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


@pytest.fixture
def cluster_3():
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    yield cluster
    cluster.shutdown()


def test_cluster_sees_all_nodes(cluster_3):
    nodes = ray_tpu.nodes()
    assert len([n for n in nodes if n["state"] == "ALIVE"]) == 3
    assert ray_tpu.cluster_resources()["CPU"] == 5.0


def test_spillback_scheduling(cluster_3):
    """Head has 1 CPU; 2-CPU tasks must spill to the bigger nodes."""

    @ray_tpu.remote(num_cpus=2)
    def where():
        import os

        return os.environ["RAY_TPU_NODE_ID"]

    node_ids = ray_tpu.get([where.remote() for _ in range(4)])
    # The 1-CPU head can never host a 2-CPU task: all must have spilled to
    # the 2-CPU nodes.
    from ray_tpu._private.common import ResourceSet

    small_nodes = {
        n["node_id"] for n in ray_tpu.nodes()
        if ResourceSet.from_units(n["total"]).to_dict().get("CPU", 0) < 2
    }
    assert small_nodes and not (small_nodes & set(node_ids))


def test_cross_node_object_transfer(cluster_3):
    @ray_tpu.remote(num_cpus=2)
    def produce():
        return np.ones((600, 600))  # ~2.9 MB: plasma on producing node

    @ray_tpu.remote(num_cpus=2)
    def consume(x):
        return float(x.sum())

    # Force different nodes via node affinity.
    nodes = [n for n in ray_tpu.nodes() if n["total"].get("CPU", 0) >= 20000]
    n1, n2 = nodes[0]["node_id"], nodes[1]["node_id"]
    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(n1)
    ).remote()
    out = consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(n2)
    ).remote(ref)
    assert ray_tpu.get(out, timeout=60) == 360000.0


def test_broadcast_uses_push_manager(cluster_3):
    """One object consumed on every other node: transfers go through the
    source's push manager (bounded one-way chunk fan-out, reference
    push_manager.h) rather than per-chunk request/reply pulls."""
    from ray_tpu._private.common import config

    @ray_tpu.remote(num_cpus=2)
    def consume(x):
        return float(x[0] + x[-1])

    data = np.arange(3 * 1024 * 1024, dtype=np.float64)  # 24 MB -> 3 chunks
    ref = ray_tpu.put(data)  # lands in the head node's store
    nodes = [n for n in ray_tpu.nodes() if n["total"].get("CPU", 0) >= 20000]
    assert len(nodes) >= 2
    outs = [
        consume.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(n["node_id"])
        ).remote(ref)
        for n in nodes
        for _ in range(2)
    ]
    expected = float(data[0] + data[-1])
    assert all(v == expected for v in ray_tpu.get(outs, timeout=120))
    stats = cluster_3.head_node.raylet.push_manager.stats
    assert stats["pushes_completed"] >= 2, stats
    assert stats["chunks_sent"] >= 2 * 3, stats
    assert stats["peak_inflight_chunks"] <= config.push_manager_max_chunks, stats


def test_placement_group_spread(cluster_3):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import os

        return os.environ["RAY_TPU_NODE_ID"]

    a = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    b = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 1)
    ).remote()
    na, nb = ray_tpu.get([a, b], timeout=60)
    assert na != nb  # strict spread -> distinct nodes
    remove_placement_group(pg)


def test_placement_group_infeasible_times_out(cluster_3):
    pg = placement_group([{"CPU": 50}], strategy="PACK")
    assert pg.ready(timeout=2) is False


def test_placement_group_table(cluster_3):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)
    table = placement_group_table()
    states = {t["pg_id"]: t["state"] for t in table}
    assert states[pg.id_hex] == "CREATED"


def test_actor_on_specific_node(cluster_3):
    nodes = [n for n in ray_tpu.nodes() if n["total"].get("CPU", 0) >= 20000]
    target = nodes[0]["node_id"]

    @ray_tpu.remote(num_cpus=1)
    class A:
        def where(self):
            import os

            return os.environ["RAY_TPU_NODE_ID"]

    a = A.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(target)
    ).remote()
    assert ray_tpu.get(a.where.remote(), timeout=60) == target


def test_node_death_kills_actors(cluster_3):
    cluster = cluster_3
    extra = cluster.add_node(num_cpus=1, resources={"special": 1})

    @ray_tpu.remote(num_cpus=1, resources={"special": 1})
    class Pinned:
        def ping(self):
            return "pong"

    a = Pinned.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    cluster.remove_node(extra)
    time.sleep(1.0)
    with pytest.raises((ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError, ray_tpu.RayTpuError)):
        ray_tpu.get(a.ping.remote(), timeout=15)
