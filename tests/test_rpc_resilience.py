"""RPC resilience layer: retry policies, end-to-end deadlines, and the
retryable connection wrapper (reference analogs: retryable_grpc_client.h,
gcs_rpc_client.h failover call queues).

Covers: backoff/jitter schedule determinism, deadline shrinking across a
3-hop call chain, server-side shedding of expired frames, deadline
enforcement (handler cancelled at its deadline), reconnect-and-drain across
a server restart, and per-method retry safety incl. dedup-token gating.
"""

import asyncio
import itertools
import random

import pytest

from ray_tpu._private import rpc, wire


# ------------------------------------------------------------ retry policy


def test_backoff_schedule_deterministic_under_seeded_rng():
    policy = rpc.RetryPolicy(
        initial_backoff_s=0.1,
        max_backoff_s=1.0,
        multiplier=2.0,
        max_attempts=5,
        total_budget_s=10.0,
    )
    a = list(itertools.islice(policy.backoffs(random.Random(7)), 10))
    b = list(itertools.islice(policy.backoffs(random.Random(7)), 10))
    assert a == b, "same seed must reproduce the identical jitter schedule"
    c = list(itertools.islice(policy.backoffs(random.Random(8)), 10))
    assert a != c


def test_backoff_caps_grow_exponentially_then_clamp():
    policy = rpc.RetryPolicy(
        initial_backoff_s=0.1, max_backoff_s=1.0, multiplier=2.0
    )
    caps = [policy.backoff_cap(i) for i in range(8)]
    assert caps == [0.1, 0.2, 0.4, 0.8, 1.0, 1.0, 1.0, 1.0]
    # Full jitter: every sleep lands in [0, cap_i].
    sleeps = list(itertools.islice(policy.backoffs(random.Random(3)), 8))
    assert all(0.0 <= s <= cap for s, cap in zip(sleeps, caps))


def test_policy_allows_enforces_both_caps():
    policy = rpc.RetryPolicy(max_attempts=3, total_budget_s=5.0)
    assert policy.allows(1, 0.0)
    assert policy.allows(3, 4.9)
    assert not policy.allows(4, 0.0), "attempt cap"
    assert not policy.allows(2, 5.0), "total budget cap"
    unbounded = rpc.RetryPolicy(max_attempts=0, total_budget_s=0.0)
    assert unbounded.allows(10_000, 1e6)


def test_connect_backoff_dial_gives_up_within_budget():
    async def go():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        with pytest.raises(rpc.ConnectionLost):
            # Port 1 refuses instantly; legacy args map onto a policy with
            # total budget retry * retry_interval.
            await rpc.connect("127.0.0.1", 1, retry=3, retry_interval=0.05)
        assert loop.time() - t0 < 2.0

    asyncio.run(go())


# ------------------------------------------------------ deadline propagation


def test_deadline_shrinks_across_three_hop_chain():
    """driver -> A -> B -> C: every hop's remaining budget must be strictly
    smaller than its caller's, because the wire TTL is re-derived from the
    same absolute deadline at each hop."""

    async def go():
        budgets = {}
        servers = [rpc.Server("127.0.0.1", 0) for _ in range(3)]
        conns = {}

        async def handler_c(conn, p):
            budgets["c"] = rpc.remaining_budget()
            return "leaf"

        async def handler_b(conn, p):
            budgets["b"] = rpc.remaining_budget()
            # No explicit timeout: the ambient deadline alone must ride on.
            return await conns["bc"].call("Hop", None)

        async def handler_a(conn, p):
            budgets["a"] = rpc.remaining_budget()
            return await conns["ab"].call("Hop", None)

        servers[0].register("Hop", handler_a)
        servers[1].register("Hop", handler_b)
        servers[2].register("Hop", handler_c)
        addrs = [await s.start() for s in servers]
        conns["ab"] = await rpc.connect(*addrs[1])
        conns["bc"] = await rpc.connect(*addrs[2])
        driver = await rpc.connect(*addrs[0])
        try:
            assert await driver.call("Hop", None, timeout=1.0) == "leaf"
            assert 0 < budgets["c"] < budgets["b"] < budgets["a"] <= 1.0
            # No deadline at all: budget is unbounded end to end.
            budgets.clear()
            assert await driver.call("Hop", None) == "leaf"
            assert budgets == {"a": None, "b": None, "c": None}
        finally:
            await driver.close()
            await conns["ab"].close()
            await conns["bc"].close()
            for s in servers:
                await s.stop()

    asyncio.run(go())


def test_server_sheds_frames_that_arrive_past_deadline():
    """A frame delayed beyond its TTL (chaos-delay analog: held, then
    re-sent via _send_direct, which re-stamps the TTL at pack time) must be
    shed on arrival — the handler never runs."""

    async def go():
        loop = asyncio.get_running_loop()
        ran = []

        async def handler(conn, p):
            ran.append(p)
            return "late"

        server = rpc.Server("127.0.0.1", 0)
        server.register("Slow", handler)
        addr = await server.start()
        conn = await rpc.connect(*addr)

        def hold(c, msg):
            if msg[1] == 0 and msg[2] == "Slow":
                loop.call_later(0.25, c._send_direct, msg)
                return True
            return False

        rpc.set_send_interceptor(hold)
        rpc.deadline_stats.reset()
        try:
            with pytest.raises(asyncio.TimeoutError):
                await conn.call("Slow", None, timeout=0.1)
            # Give the held frame time to arrive and be shed.
            await asyncio.sleep(0.3)
            assert ran == [], "handler must not run for an expired frame"
            assert rpc.deadline_stats.shed == 1
        finally:
            rpc.set_send_interceptor(None)
            await conn.close()
            await server.stop()

    asyncio.run(go())


def test_handler_cancelled_at_deadline_and_error_reply_text():
    async def go():
        loop = asyncio.get_running_loop()
        unwound = []

        async def sleepy(conn, p):
            try:
                await asyncio.sleep(30)
            finally:
                unwound.append(True)
            return "never"

        server = rpc.Server("127.0.0.1", 0)
        server.register("Sleepy", sleepy)
        addr = await server.start()
        conn = await rpc.connect(*addr)
        rpc.deadline_stats.reset()
        try:
            # call_nowait + bare await (no local wait_for) so the error
            # reply itself is observable instead of the local timeout.
            fut = conn.call_nowait("Sleepy", None, deadline=loop.time() + 0.2)
            with pytest.raises(rpc.RpcError, match="DeadlineExceeded"):
                await fut
            assert rpc.deadline_stats.enforced == 1
            assert unwound == [True], "cancellation must unwind the handler"
            assert rpc.deadline_stats.overruns == []
        finally:
            await conn.close()
            await server.stop()

    asyncio.run(go())


# ------------------------------------------------- retryable connection


def _fast_policy():
    return rpc.RetryPolicy(
        initial_backoff_s=0.02,
        max_backoff_s=0.1,
        multiplier=2.0,
        max_attempts=0,
        total_budget_s=10.0,
    )


def test_reconnect_and_drain_across_server_restart():
    """Calls issued while the server is down queue behind the redial lock
    and drain once it is back — a restart is a latency blip, not an error."""

    async def go():
        async def echo(conn, p):
            return p

        server = rpc.Server("127.0.0.1", 0)
        server.register("Echo", echo)
        host, port = await server.start()

        async def dial():
            return await rpc.connect(host, port, policy=_fast_policy())

        rc = rpc.RetryableConnection(
            dial, conn=await dial(), policy=_fast_policy(),
            default_retry=wire.RETRY_SAFE, name="test",
        )
        try:
            assert await rc.call("Echo", 1) == 1
            await server.stop()
            # In-flight while down: all must block, then drain on restart.
            calls = [
                asyncio.ensure_future(rc.call("Echo", i)) for i in range(5)
            ]
            await asyncio.sleep(0.15)
            assert not any(c.done() for c in calls)
            server = rpc.Server("127.0.0.1", port)
            server.register("Echo", echo)
            await server.start()
            assert await asyncio.wait_for(asyncio.gather(*calls), 10) == [
                0, 1, 2, 3, 4,
            ]
            assert rc.stats["redials"] >= 1
            # Late arrivals park behind the redial lock rather than failing.
            assert rc.stats["queued"] >= 1
        finally:
            await rc.close()
            await server.stop()

    asyncio.run(go())


def test_closed_retryable_connection_stops_redialing():
    async def go():
        async def echo(conn, p):
            return p

        server = rpc.Server("127.0.0.1", 0)
        server.register("Echo", echo)
        host, port = await server.start()

        async def dial():
            return await rpc.connect(host, port, policy=_fast_policy())

        rc = rpc.RetryableConnection(
            dial, conn=await dial(), policy=_fast_policy(),
            default_retry=wire.RETRY_SAFE, name="test",
        )
        await rc.close()
        with pytest.raises(rpc.ConnectionLost):
            await rc.call("Echo", 1)
        await server.stop()

    asyncio.run(go())


def _lossy_lease_server(calls):
    """Server whose first reply per method is lost: the handler runs, then
    the connection dies before the reply frame ships."""

    async def lease(conn, p):
        calls.append(p.get("lease_id"))
        if len(calls) == 1:
            await conn.close()  # reply vanishes with the link
        return {"granted": True}

    server = rpc.Server("127.0.0.1", 0)
    server.register("RequestWorkerLease", lease)
    return server


def test_dedup_method_retries_only_with_token():
    """RequestWorkerLease is RETRY_DEDUP on lease_id: with the token the
    wrapper re-issues after a lost reply (the raylet's grant ledger dedupes
    server-side); without it the failure surfaces."""

    async def go():
        calls = []
        server = _lossy_lease_server(calls)
        host, port = await server.start()

        async def dial():
            return await rpc.connect(host, port, policy=_fast_policy())

        rc = rpc.RetryableConnection(
            dial, conn=await dial(), policy=_fast_policy(), name="test",
        )
        try:
            reply = await rc.call(
                "RequestWorkerLease", {"lease_id": "L1", "resources": {}}
            )
            assert reply == {"granted": True}
            assert calls == ["L1", "L1"], "retry must carry the same token"
        finally:
            await rc.close()
            await server.stop()

    asyncio.run(go())


def test_dedup_method_without_token_does_not_retry():
    async def go():
        calls = []
        server = _lossy_lease_server(calls)
        host, port = await server.start()

        async def dial():
            return await rpc.connect(host, port, policy=_fast_policy())

        rc = rpc.RetryableConnection(
            dial, conn=await dial(), policy=_fast_policy(), name="test",
        )
        try:
            with pytest.raises(rpc.ConnectionLost):
                await rc.call("RequestWorkerLease", {"resources": {}})
            assert calls == [None], "no token -> no transparent retry"
        finally:
            await rc.close()
            await server.stop()

    asyncio.run(go())


def test_retry_none_method_surfaces_first_failure():
    async def go():
        async def push_task(conn, p):
            await conn.close()
            return "lost"

        server = rpc.Server("127.0.0.1", 0)
        server.register("PushTask", push_task)
        host, port = await server.start()

        async def dial():
            return await rpc.connect(host, port, policy=_fast_policy())

        rc = rpc.RetryableConnection(
            dial, conn=await dial(), policy=_fast_policy(),
            default_retry=wire.RETRY_SAFE, name="test",
        )
        try:
            # PushTask is RETRY_NONE in wire.SCHEMAS: the channel default
            # ("safe") must not override the per-method declaration.
            with pytest.raises(rpc.ConnectionLost):
                await rc.call("PushTask", {"spec": {}})
        finally:
            await rc.close()
            await server.stop()

    asyncio.run(go())


def test_retry_class_registry():
    assert wire.retry_class("KVGet") == (wire.RETRY_SAFE, None)
    assert wire.retry_class("RequestWorkerLease") == (
        wire.RETRY_DEDUP, "lease_id",
    )
    assert wire.retry_class("PushChunk") == (wire.RETRY_NONE, None)
    assert wire.retry_class("NoSuchMethod") == (wire.RETRY_NONE, None)
    assert wire.retry_class("NoSuchMethod", wire.RETRY_SAFE) == (
        wire.RETRY_SAFE, None,
    )
