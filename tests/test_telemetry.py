"""Runtime telemetry plane: snapshot-and-reset flush semantics, the GCS
aggregate + Prometheus rendering, flush-on-exit from worker subprocesses,
the merged dashboard /metrics export, the chaos flight-recorder dump, and
the telemetry-unregistered-stat lint rule."""

import json
import time
import urllib.request

import pytest

from ray_tpu._private import telemetry

# The registry is process-global and other tests leave series behind
# (rpc frame counters, raylet gauges, ...). Every test here uses a unique
# component namespace and asserts on its own families only.


def _series(payload, comp, name):
    """The wire entry for (comp, name) in a flush payload, or None."""
    if payload is None:
        return None
    for m in payload["metrics"]:
        if m["c"] == comp and m["n"] == name:
            return m
    return None


# ------------------------------------------------------- flush semantics


def test_counter_flush_is_exactly_once():
    fam = telemetry.counter("t7flush", "reqs", "test counter")
    fam.cell(k="a").inc(2)
    fam.cell(k="b").inc(3)

    p1 = telemetry.flush_delta("src", "node1")
    m = _series(p1, "t7flush", "reqs")
    assert m is not None and m["k"] == "counter"
    assert sum(v for _, v in m["s"]) == 5.0

    # Drained: the same family contributes nothing to the next flush.
    p2 = telemetry.flush_delta("src", "node1")
    assert _series(p2, "t7flush", "reqs") is None

    # New increments after the flush land in the next delta, undoubled.
    fam.cell(k="a").inc()
    p3 = telemetry.flush_delta("src", "node1")
    m3 = _series(p3, "t7flush", "reqs")
    assert sum(v for _, v in m3["s"]) == 1.0


def test_gauge_reports_and_keeps():
    g = telemetry.gauge("t7flush", "depth", "test gauge").default
    g.set(7.0)
    for _ in range(2):  # gauges survive flushes: last value, every time
        p = telemetry.flush_delta("src", "node1")
        m = _series(p, "t7flush", "depth")
        assert m is not None and m["s"][0][1] == 7.0


def test_histogram_buckets_and_reset():
    h = telemetry.histogram(
        "t7flush", "lat_s", "test histogram", buckets=(0.1, 1.0)
    ).default
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    p = telemetry.flush_delta("src", "node1")
    m = _series(p, "t7flush", "lat_s")
    assert m["b"] == [0.1, 1.0]
    _, val = m["s"][0]
    assert val["counts"] == [1, 1, 1]  # one per bucket + one overflow
    assert val["total"] == 3 and abs(val["sum"] - 5.55) < 1e-9

    # Histograms drain like counters.
    assert _series(telemetry.flush_delta("s", "n"), "t7flush", "lat_s") is None


def test_restore_delta_roundtrips_an_undelivered_flush():
    fam = telemetry.counter("t7restore", "sent", "test")
    fam.cell(ch="x").inc(4)
    h = telemetry.histogram("t7restore", "d_s", "test", buckets=(1.0,)).default
    h.observe(0.5)
    telemetry.record_event("t7restore", "probe", n=1)

    p = telemetry.flush_delta("src", "node1")
    assert p is not None and p.get("events")
    telemetry.restore_delta(p)  # the send failed; fold it back

    p2 = telemetry.flush_delta("src", "node1")
    for name in ("sent", "d_s"):
        assert _series(p2, "t7restore", name) == _series(p, "t7restore", name)
    assert p2["events"] == p["events"]


def test_flight_recorder_drain_and_flush_payload():
    telemetry.flight().clear()
    telemetry.record_event("t7ring", "one", a=1)
    telemetry.record_event("t7ring", "two", b=2)
    assert len(telemetry.flight()) == 2

    p = telemetry.flush_delta("src", "node1")
    evs = [e for e in p["events"] if e[1] == "t7ring"]
    assert [e[2] for e in evs] == ["one", "two"]
    assert len(telemetry.flight()) == 0  # drained with the flush


# --------------------------------------------- aggregate + Prometheus text


def _payload(node, metrics):
    return {"source": node, "node": node, "metrics": metrics}


def test_ingest_and_render_runtime_prometheus():
    agg = telemetry.new_aggregate()
    ctr = {
        "c": "t7rend", "n": "reqs", "k": "counter", "h": "test reqs",
        "b": None, "s": [['{"dep": "x"}', 3.0]],
    }
    gau = {
        "c": "t7rend", "n": "depth", "k": "gauge", "h": "", "b": None,
        "s": [["{}", 9.0]],
    }
    hist = {
        "c": "t7rend", "n": "lat_s", "k": "histogram", "h": "", "b": [0.1, 1.0],
        "s": [["{}", {"counts": [1, 1, 1], "sum": 5.55, "total": 3}]],
    }
    telemetry.ingest(agg, _payload("n1", [ctr, gau, hist]), now=1000.0)
    telemetry.ingest(agg, _payload("n2", [ctr]), now=1000.0)
    telemetry.ingest(agg, _payload("n1", [ctr]), now=1000.0)  # delta folds

    wds = {"met": 5, "shed": 1, "enforced": 2, "overruns": [["w", "m", 1.0]]}
    text = telemetry.render_runtime_prometheus(
        agg, worker_deadline_stats=wds, now=1010.0, stale_after_s=30.0
    )
    # Counter: deltas accumulate per (node, labels); name gets _total.
    assert '# TYPE ray_tpu_t7rend_reqs_total counter' in text
    assert '# HELP ray_tpu_t7rend_reqs_total test reqs' in text
    assert 'ray_tpu_t7rend_reqs_total{dep="x",node="n1"} 6.0' in text
    assert 'ray_tpu_t7rend_reqs_total{dep="x",node="n2"} 3.0' in text
    # Gauge: last value with its node label.
    assert 'ray_tpu_t7rend_depth{node="n1"} 9.0' in text
    # Histogram: cumulative buckets, +Inf, sum/count.
    assert 'ray_tpu_t7rend_lat_s_bucket{node="n1",le="0.1"} 1' in text
    assert 'ray_tpu_t7rend_lat_s_bucket{node="n1",le="+Inf"} 3' in text
    assert 'ray_tpu_t7rend_lat_s_count{node="n1"} 3' in text
    # worker_deadline_stats appears as the deadline families under the
    # dedicated aggregate pseudo-node.
    assert 'ray_tpu_rpc_deadline_met_total{node="_worker_aggregate"} 5.0' in text
    assert (
        'ray_tpu_rpc_deadline_overruns_total{node="_worker_aggregate"} 1.0'
        in text
    )

    # A gauge whose source stopped flushing ages out; counters do not.
    stale = telemetry.render_runtime_prometheus(
        agg, now=1000.0 + 120.0, stale_after_s=30.0
    )
    assert 'ray_tpu_t7rend_depth{node="n1"}' not in stale
    assert 'ray_tpu_t7rend_reqs_total{dep="x",node="n1"} 6.0' in stale


def test_merged_timeline_orders_and_dumps_jsonl(tmp_path):
    a = [(3.0, "raylet", "lease_granted", {"lease": "l1"})]
    b = [
        (1.0, "object", "sealed", {"oid": "o1"}),
        (2.0, "rpc", "retry", {"channel": "gcs"}),
    ]
    timeline = telemetry.merged_timeline(a, b)
    assert [e["ts"] for e in timeline] == [1.0, 2.0, 3.0]
    assert timeline[0] == {"ts": 1.0, "component": "object", "event": "sealed",
                           "oid": "o1"}

    path = tmp_path / "flight.jsonl"
    assert telemetry.dump_timeline(str(path), a, b) == 3
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [e["event"] for e in lines] == ["sealed", "retry", "lease_granted"]


# ------------------------------------------------------------- lint rule


def test_telemetry_lint_flags_adhoc_stats_and_honors_waiver(tmp_path):
    from ray_tpu.devtools import telemetry_lint

    pkg = tmp_path / "_private"
    pkg.mkdir()
    bad = pkg / "mod.py"
    bad.write_text(
        "class C:\n"
        "    def __init__(self):\n"
        "        self.stats = {'a': 0}\n"
        "        self.push_stats = {'b': 0}  # telemetry: allow-adhoc-stats\n"
        "        # telemetry: allow-adhoc-stats\n"
        "        self.pull_stats = {'c': 0}\n"
        "        self.status = {'not': 'stats'}\n"
    )
    findings = telemetry_lint.lint_file(str(bad))
    assert len(findings) == 1 and findings[0].line == 3
    assert findings[0].rule == "telemetry-unregistered-stat"

    # Outside a _private package the rule does not apply.
    ok = tmp_path / "mod.py"
    ok.write_text("stats = {'a': 0}\n")
    assert telemetry_lint.lint_file(str(ok)) == []


# --------------------------------------------------------- cluster e2e


def test_worker_exit_flushes_telemetry_to_gcs(shutdown_only, monkeypatch):
    """Counters recorded inside a worker subprocess survive its managed
    exit: handle_exit's bounded final ReportTelemetry reaches the GCS
    aggregate even with periodic flushing disabled."""
    # Periodic flush off everywhere: delivery below can only be the
    # worker's flush-on-exit.
    monkeypatch.setenv("RAY_TPU_TELEMETRY_FLUSH_INTERVAL_S", "0")
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    telemetry.reset_flusher_for_test()
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote
    def bump():
        from ray_tpu._private import telemetry as t

        t.counter("t7exit", "worker_bump", "test").cell(tag="x").inc(3)
        t.record_event("t7exit", "bumped", tag="x")
        return 1

    assert ray_tpu.get(bump.remote()) == 1

    w = worker_mod.global_worker
    node = w.node
    gcs = node.gcs_server
    assert gcs is not None

    async def _exit_workers():
        # Graceful Exit: the reply only comes back after handle_exit has
        # awaited its final ReportTelemetry, so this is race-free.
        for wk in list(node.raylet.workers.values()):
            if wk.conn is not None and not wk.conn.closed:
                try:
                    await wk.conn.call("Exit", {}, timeout=10)
                except Exception:
                    pass

    w.run_async(_exit_workers(), timeout=30)

    tbl = gcs.telemetry["counters"].get("t7exit.worker_bump", {})
    assert sum(tbl.values()) == 3.0, gcs.telemetry["counters"].keys()
    assert any(
        comp == "t7exit" and ev == "bumped"
        for _, comp, ev, _f in gcs.flight_events
    )


def test_dashboard_metrics_merges_app_and_runtime_series(shutdown_only):
    """/metrics serves the app-metric export plus runtime series from all
    five instrumented components, including the deadline-stats family."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.dashboard.dashboard import Dashboard
    from ray_tpu.util import metrics as app_metrics

    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        # Object + rpc + raylet + gcs traffic (past the 100 KiB inline
        # threshold so the put goes through the shm store client).
        ref = ray_tpu.put(b"x" * (1 << 20))
        assert len(ray_tpu.get(ref)) == 1 << 20

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1

        # Serve traffic (the handle router records per-deployment series).
        @serve.deployment
        class Doubler:
            def __call__(self, x):
                return x * 2

        handle = serve.run(Doubler.bind(), route_prefix=None)
        assert handle.remote(21).result(timeout_s=30) == 42

        # An application metric, flushed to the GCS KV snapshot store.
        app_metrics.Counter("t7_app_requests", "app-side test counter").inc(5)
        app_metrics._flush_once()

        # Deterministic runtime flush: in-process cluster -> one shared
        # registry; a single explicit report carries every component.
        w = worker_mod.global_worker
        w.run_async(
            telemetry.flush_once(w.core.gcs.call, "driver", "drivernode"),
            timeout=10,
        )

        gcs_addr = w.node.gcs_addr
        dash = Dashboard(gcs_addr, port=0)
        host, port = w.run_async(dash.start())
        try:
            body = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10
            ).read().decode()
        finally:
            w.run_async(dash.stop())

        # App-metric pipeline present, bookkeeping stamp not rendered.
        assert "t7_app_requests" in body
        assert not any(l.startswith("_ts") for l in body.splitlines())
        # Runtime series from every instrumented component.
        for comp in ("rpc", "raylet", "object", "gcs", "serve"):
            assert f"ray_tpu_{comp}_" in body, f"missing {comp} series"
        # The deadline family, including the GCS worker aggregate.
        assert "ray_tpu_rpc_deadline_met_total" in body
        assert 'node="_worker_aggregate"' in body
    finally:
        serve.shutdown()


def test_chaos_violation_dumps_flight_timeline(shutdown_only, tmp_path,
                                               monkeypatch):
    """A failing chaos seed writes flight_<scenario>_<seed>.jsonl next to
    the corpus: a non-empty, time-ordered merged timeline."""
    from ray_tpu.chaos import invariants
    from ray_tpu.chaos.runner import SCENARIOS, run_scenario

    async def forced_violation(cluster):
        return ["forced: flight-dump test"]

    monkeypatch.setattr(invariants, "check", forced_violation)

    corpus = tmp_path / "chaos_corpus.jsonl"
    results = run_scenario(SCENARIOS["rpc_delay"], seeds=[0], corpus=str(corpus))
    assert [r.ok for r in results] == [False]

    dump = tmp_path / "flight_rpc_delay_0.jsonl"
    assert dump.exists(), list(tmp_path.iterdir())
    events = [json.loads(l) for l in dump.read_text().splitlines()]
    assert events, "flight dump must not be empty"
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    for e in events:
        assert {"ts", "component", "event"} <= set(e)
    # The workload's lifecycle edges made it into the timeline.
    assert any(e["component"] in ("raylet", "object", "gcs") for e in events)
