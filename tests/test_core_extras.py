"""Core API extras: cancel, dynamic generators, ActorPool, Queue,
TorchTrainer (analog of python/ray/tests/test_cancel.py, test_generators.py,
test_actor_pool.py, test_queue.py; train/tests/test_torch_trainer.py)."""

import time

import numpy as np
import pytest


def test_cancel_running_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def spin(seconds):
        # Pure-Python loop: interruptible by PyThreadState_SetAsyncExc.
        deadline = time.monotonic() + seconds
        x = 0
        while time.monotonic() < deadline:
            x += 1
        return x

    ref = spin.remote(60)
    time.sleep(2)  # let it start
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_cancel_queued_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(num_cpus=4)
    def hog():
        time.sleep(8)
        return "hog"

    @ray_tpu.remote(num_cpus=4)
    def queued():
        return "ran"

    h = hog.remote()
    time.sleep(0.5)
    q = queued.remote()  # cannot start: hog holds all CPUs
    ray_tpu.cancel(q)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(q, timeout=30)
    assert ray_tpu.get(h, timeout=30) == "hog"


def test_dynamic_generators(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    ref = gen.remote(5)
    dyn = ray_tpu.get(ref)
    assert isinstance(dyn, ray_tpu.ObjectRefGenerator)
    assert len(dyn) == 5
    assert [ray_tpu.get(r) for r in dyn] == [0, 1, 4, 9, 16]


def test_dynamic_generator_large_items(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(num_returns="dynamic")
    def gen():
        for i in range(3):
            yield np.full((256, 256), i)  # 0.5MB each -> plasma path

    refs = list(ray_tpu.get(gen.remote()))
    for i, r in enumerate(refs):
        np.testing.assert_array_equal(ray_tpu.get(r), np.full((256, 256), i))


def test_actor_pool(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import ActorPool

    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return x * 2

    pool = ActorPool([Worker.remote() for _ in range(2)])
    results = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert results == [0, 2, 4, 6, 8, 10, 12, 14]
    unordered = sorted(
        pool.map_unordered(lambda a, v: a.double.remote(v), range(8))
    )
    assert unordered == [0, 2, 4, 6, 8, 10, 12, 14]


def test_queue(ray_start_regular):
    import ray_tpu
    from ray_tpu.util.queue import Empty, Full, Queue

    q = Queue(maxsize=2)
    q.put("a")
    q.put("b")
    with pytest.raises(Full):
        q.put("c", block=False)
    assert q.qsize() == 2 and q.full()
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get(block=False)

    # Cross-process: a task puts, driver gets.
    @ray_tpu.remote
    def producer(queue):
        for i in range(3):
            queue.put(i)
        return True

    # Drain while the producer runs: the third put blocks until the driver
    # frees a slot, so waiting on the task before draining would deadlock.
    ref = producer.remote(q)
    assert [q.get(timeout=10) for _ in range(3)] == [0, 1, 2]
    assert ray_tpu.get(ref)
    q.shutdown()


def test_torch_trainer_ddp(ray_start_regular):
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def train_fn(config):
        import torch
        import torch.distributed as dist
        from torch import nn

        import ray_tpu.train as train
        from ray_tpu.train.torch import prepare_model

        assert dist.is_initialized() and dist.get_world_size() == 2
        rank = dist.get_rank()

        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        torch.manual_seed(0)
        X = torch.randn(64, 4)
        y = X.sum(dim=1, keepdim=True)
        for _ in range(config["epochs"]):
            opt.zero_grad()
            loss = ((model(X) - y) ** 2).mean()
            loss.backward()  # DDP allreduces grads here
            opt.step()
        # Gradient sync means identical weights on every rank.
        w = model.module.weight.detach().clone()
        gathered = [torch.zeros_like(w) for _ in range(2)]
        dist.all_gather(gathered, w)
        assert torch.allclose(gathered[0], gathered[1])
        train.report({"loss": float(loss), "rank": rank})

    trainer = TorchTrainer(
        train_fn,
        train_loop_config={"epochs": 20},
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.metrics["loss"] < 1.0


def test_streaming_generator_overlaps_producer(ray_start_regular):
    """Consumer receives early items while the producer is still yielding
    (reference: ReportGeneratorItemReturns streaming)."""
    import time as _time

    import ray_tpu

    @ray_tpu.remote
    def warm():
        return 1

    @ray_tpu.remote(num_returns="dynamic")
    def slow_gen():
        for i in range(4):
            yield i
            _time.sleep(0.8)

    ray_tpu.get(warm.remote())  # spawn the worker outside the timed window

    t0 = _time.monotonic()
    gen = ray_tpu.get(slow_gen.remote(), timeout=30)
    it = iter(gen)
    first = ray_tpu.get(next(it))
    first_latency = _time.monotonic() - t0
    assert first == 0
    # The full run takes >= 3*0.8s; getting item 0 must not wait for it.
    assert first_latency < 2.0, f"first item took {first_latency:.1f}s (not streamed)"
    rest = [ray_tpu.get(r) for r in it]
    assert rest == [1, 2, 3]


def test_streaming_generator_borrowed(ray_start_regular):
    """A generator handle passed to another process iterates via the owner
    (DynNext long-poll)."""
    import ray_tpu

    @ray_tpu.remote(num_returns="dynamic")
    def gen():
        for i in range(3):
            yield i * 10

    @ray_tpu.remote
    def consume(g):
        return [ray_tpu.get(r) for r in g]

    g = ray_tpu.get(gen.remote(), timeout=30)
    assert ray_tpu.get(consume.remote(g), timeout=60) == [0, 10, 20]


def test_streaming_generator_failure_propagates(ray_start_regular):
    """A generator that raises mid-stream terminates iteration with the
    task's error instead of hanging consumers."""
    import ray_tpu

    @ray_tpu.remote(num_returns="dynamic", max_retries=0)
    def bad_gen():
        yield 1
        raise ValueError("boom-mid-stream")

    gen = ray_tpu.get(bad_gen.remote(), timeout=30)
    it = iter(gen)
    assert ray_tpu.get(next(it), timeout=30) == 1
    with pytest.raises(Exception) as ei:
        ray_tpu.get(next(it), timeout=30)
    assert "boom-mid-stream" in str(ei.value)


def test_pull_manager_priority_and_quota():
    """Prioritized bandwidth-capped pull admission (reference:
    object_manager/pull_manager.h): quota bounds bytes in flight, a
    head-of-line oversized pull is never deadlocked, and gets outrank
    task-arg prefetches regardless of arrival order."""
    import asyncio

    from ray_tpu._private.pull_manager import PullManager

    async def scenario():
        pm = PullManager(100)
        order = []

        await pm.acquire(60, "get")       # admitted: 60 in flight
        await pm.acquire(30, "task_arg")  # admitted: 90 in flight

        async def queued(size, purpose, tag):
            await pm.acquire(size, purpose)
            order.append(tag)

        # Over quota now: these queue. task_arg arrives FIRST but the get
        # and wait must be admitted before it.
        t1 = asyncio.ensure_future(queued(50, "task_arg", "arg"))
        await asyncio.sleep(0.01)
        t2 = asyncio.ensure_future(queued(50, "get", "get"))
        t3 = asyncio.ensure_future(queued(50, "wait", "wait"))
        await asyncio.sleep(0.01)
        assert order == []
        assert pm.stats()["queued_pulls"] == 3

        pm.release(60)  # 30 in flight; head (get, 50) fits -> 80
        await asyncio.sleep(0.01)
        assert order == ["get"]
        pm.release(30)  # 50 in flight; wait (50) fits -> 100; arg must wait
        await asyncio.sleep(0.01)
        assert order == ["get", "wait"]
        pm.release(50)
        pm.release(50)
        await asyncio.sleep(0.01)
        assert order == ["get", "wait", "arg"]
        await asyncio.gather(t1, t2, t3)
        pm.release(50)  # the admitted task_arg pull finishes too

        # Oversized head-of-line pull: admitted alone rather than deadlocked.
        await pm.acquire(1000, "get")
        assert pm.stats()["bytes_in_flight"] == 1000
        pm.release(1000)
        assert pm.stats() == {
            "bytes_in_flight": 0, "active_pulls": 0, "queued_pulls": 0,
            "stalled_streams": 0, "rerequested_streams": 0,
            "restore_fallbacks": 0,
        }

    asyncio.run(scenario())


def test_fast_id_state_reseeds_after_fork():
    """Forked workers must not inherit the zygote's fast-id stream: shared
    prefix + counter makes two workers draw identical task ids, whose
    deterministic return-object ids then alias in the object store (the
    second task's output silently becomes the first task's bytes)."""
    import os

    from ray_tpu._private import ids

    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child
        try:
            os.write(w, ids.fast_unique_hex().encode())
        finally:
            os._exit(0)
    os.close(w)
    _, status = os.waitpid(pid, 0)
    assert status == 0
    child = os.read(r, 64).decode()
    os.close(r)
    parent = ids.fast_unique_hex()
    assert len(child) == 32 and len(parent) == 32
    # The 20-hex-char random prefix must differ post-fork (1 in 16^20
    # chance of a false pass by collision).
    assert child[:20] != parent[:20]
