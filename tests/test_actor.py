"""Actor tests (models reference python/ray/tests/test_actor.py coverage)."""

import time

import pytest

import ray_tpu


def test_basic_actor(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def value(self):
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote()) == 2
    assert ray_tpu.get(c.value.remote()) == 2


def test_actor_ordering(ray_start_regular):
    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)

        def items_(self):
            return self.items

    log = Log.remote()
    for i in range(30):
        log.add.remote(i)
    assert ray_tpu.get(log.items_.remote()) == list(range(30))


def test_actor_constructor_args(ray_start_regular):
    @ray_tpu.remote
    class A:
        def __init__(self, a, b=2):
            self.v = a + b

        def get(self):
            return self.v

    a = A.remote(1, b=10)
    assert ray_tpu.get(a.get.remote()) == 11


def test_actor_error(ray_start_regular):
    @ray_tpu.remote
    class A:
        def boom(self):
            raise RuntimeError("actor error")

        def ok(self):
            return "fine"

    a = A.remote()
    with pytest.raises(RuntimeError, match="actor error"):
        ray_tpu.get(a.boom.remote())
    # Actor survives method errors.
    assert ray_tpu.get(a.ok.remote()) == "fine"


def test_named_actor(ray_start_regular):
    @ray_tpu.remote
    class A:
        def hello(self):
            return "world"

    A.options(name="singleton").remote()
    h = ray_tpu.get_actor("singleton")
    assert ray_tpu.get(h.hello.remote()) == "world"


def test_get_if_exists(ray_start_regular):
    @ray_tpu.remote
    class A:
        def __init__(self):
            self.t = time.time()

        def created(self):
            return self.t

    a1 = A.options(name="shared", get_if_exists=True).remote()
    t1 = ray_tpu.get(a1.created.remote())
    a2 = A.options(name="shared", get_if_exists=True).remote()
    t2 = ray_tpu.get(a2.created.remote())
    assert t1 == t2  # same instance


def test_actor_handle_in_task(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.inc.remote())

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(c.inc.remote()) == 2


def test_kill_actor(ray_start_regular):
    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    ray_tpu.kill(a)
    time.sleep(0.5)
    with pytest.raises((ray_tpu.ActorDiedError, ray_tpu.RayTpuError)):
        ray_tpu.get(a.ping.remote(), timeout=10)


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os

            os._exit(1)

    f = Flaky.remote()
    assert ray_tpu.get(f.inc.remote()) == 1
    f.die.remote()
    time.sleep(1.0)
    # After restart, state is fresh (reconstructed from __init__).
    for _ in range(50):
        try:
            v = ray_tpu.get(f.inc.remote(), timeout=30)
            break
        except ray_tpu.RayTpuError:
            time.sleep(0.2)
    assert v == 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.05)
            return x * 2

    a = AsyncActor.remote()
    refs = [a.work.remote(i) for i in range(5)]
    assert ray_tpu.get(refs) == [0, 2, 4, 6, 8]


def test_max_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Slow:
        def work(self):
            time.sleep(0.5)
            return 1

    s = Slow.remote()
    ray_tpu.get(s.work.remote())  # warm up: actor spawn excluded from timing
    start = time.time()
    ray_tpu.get([s.work.remote() for _ in range(4)])
    elapsed = time.time() - start
    assert elapsed < 1.9, f"expected concurrent execution, took {elapsed:.2f}s"


def test_actor_large_state_roundtrip(ray_start_regular):
    import numpy as np

    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.data = None

        def set(self, x):
            self.data = x
            return x.nbytes

        def get(self):
            return self.data

    s = Store.remote()
    arr = np.random.rand(500, 500)  # 2 MB
    assert ray_tpu.get(s.set.remote(arr)) == arr.nbytes
    out = ray_tpu.get(s.get.remote())
    np.testing.assert_array_equal(arr, out)


def test_concurrency_groups(ray_start_regular):
    """Per-method concurrency groups: calls in different groups never block
    each other; a group's limit bounds its concurrency (reference:
    transport/concurrency_group_manager.cc)."""
    import time as _time

    import ray_tpu

    @ray_tpu.remote(concurrency_groups={"io": 1, "compute": 2})
    class Grouped:
        def __init__(self):
            self.active_compute = 0
            self.peak_compute = 0

        def block_io(self):
            _time.sleep(3.0)
            return "io-done"

        def compute(self):
            self.active_compute += 1
            self.peak_compute = max(self.peak_compute, self.active_compute)
            _time.sleep(0.3)
            self.active_compute -= 1
            return "c-done"

        def peak(self):
            return self.peak_compute

    g = Grouped.remote()
    ray_tpu.get(g.peak.remote())  # actor fully started before timing
    t0 = _time.monotonic()
    io_ref = g.block_io.options(concurrency_group="io").remote()
    # Compute calls must finish while the io group is still blocked.
    outs = ray_tpu.get(
        [g.compute.options(concurrency_group="compute").remote() for _ in range(4)],
        timeout=30,
    )
    compute_done = _time.monotonic() - t0
    assert outs == ["c-done"] * 4
    assert compute_done < 2.5, f"compute blocked behind io group ({compute_done:.1f}s)"
    assert ray_tpu.get(io_ref, timeout=30) == "io-done"
    # Group limit 2: never more than 2 compute calls in flight.
    assert ray_tpu.get(g.peak.remote(), timeout=30) <= 2
