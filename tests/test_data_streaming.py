"""Streaming ingest fast-path tests: metadata plumbing, bounded windows,
completion-order output, the zero-copy batcher, fused read->map stages and
fast teardown (reference model: python/ray/data/tests/test_streaming_*)."""

import pickle
import time

import numpy as np
import pyarrow as pa
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data import _execution as E
from ray_tpu.data import block as B
from ray_tpu.data.iterator import batches_from_blocks, iter_blocks_pipelined


class _SubmitSpy:
    """Record the function name of every TASK submitted through
    ray_tpu.remote (actors pass through untouched)."""

    def __init__(self, monkeypatch):
        self.names = []
        orig_remote = ray_tpu.remote
        spy = self

        class _Tracking:
            def __init__(self, fn_name, wrapped):
                self._fn_name = fn_name
                self._wrapped = wrapped

            def remote(self, *ta, **tk):
                spy.names.append(self._fn_name)
                return self._wrapped.remote(*ta, **tk)

            def options(self, **opts):
                return _Tracking(self._fn_name, self._wrapped.options(**opts))

        def tracking_remote(*a, **k):
            if a and callable(a[0]) and not isinstance(a[0], type):
                return _Tracking(a[0].__name__, orig_remote(*a, **k))
            if not a and k:
                # decorator-with-options form: remote(num_cpus=1)(fn_or_cls)
                def deco(obj):
                    wrapped = orig_remote(**k)(obj)
                    if isinstance(obj, type):
                        return wrapped
                    return _Tracking(obj.__name__, wrapped)

                return deco
            return orig_remote(*a, **k)

        monkeypatch.setattr(E.ray_tpu, "remote", tracking_remote)

    def count(self, name):
        return sum(1 for n in self.names if n == name)


def test_metadata_rides_with_refs(ray_start_regular):
    """Every stage task returns (block, meta) via num_returns=2; the meta
    matches the materialized block exactly, for map, repartition, sort and
    groupby stages alike."""
    ds = (
        rd.range(40, parallelism=4)
        .map_batches(lambda b: {"id": b["id"], "y": b["id"] * 2})
        .repartition(3)
    )
    bundles = list(ds.iter_bundles())
    assert len(bundles) == 3
    metas = E.resolve_metas(bundles)
    blocks = ray_tpu.get([b.block for b in bundles])
    for meta, blk in zip(metas, blocks):
        assert meta.num_rows == blk.num_rows
        assert meta.size_bytes == blk.nbytes
    assert sum(m.num_rows for m in metas) == 40

    for ds2 in (
        rd.range(20, parallelism=3).sort("id"),
        rd.range(20, parallelism=3).groupby("id").count(),
    ):
        bundles = list(ds2.iter_bundles())
        metas = E.resolve_metas(bundles)
        blocks = ray_tpu.get([b.block for b in bundles])
        for meta, blk in zip(metas, blocks):
            assert meta.num_rows == blk.num_rows


def test_resolve_metas_caches_and_batches(ray_start_regular):
    """resolve_metas resolves ref-typed metas with one batched get and
    caches the concrete BlockMeta on the bundle."""
    bundles = list(rd.range(30, parallelism=3).iter_bundles())
    assert all(isinstance(b.meta, ray_tpu.ObjectRef) for b in bundles)
    metas = E.resolve_metas(bundles)
    assert all(isinstance(b.meta, B.BlockMeta) for b in bundles)
    # Second resolve is a pure cache hit (no refs left to fetch).
    assert E.resolve_metas(bundles) == metas


def test_no_counter_round_trips(ray_start_regular, monkeypatch):
    """Limit / zip / repartition / count dispatch on bundled metadata: the
    only tasks submitted are the data-bearing stage kernels — no per-block
    row-counting task exists anywhere in the pipeline."""
    spy = _SubmitSpy(monkeypatch)
    ds = rd.range(40, parallelism=4)
    assert ds.count() == 40
    assert ds.limit(11).count() == 11
    assert ds.repartition(3).count() == 40
    z = rd.range(8, parallelism=2).zip(
        rd.range(8, parallelism=2).map_batches(lambda b: {"o": b["id"] + 1})
    )
    assert z.count() == 8
    data_kernels = {
        "_exec_read",
        "_exec_map",
        "_slice_concat",
        "_zip_tables",
        "_partition_block",
        "_merge_sort",
        "_merge_shuffle",
        "_merge_groupby",
        "_sample_block",
    }
    assert spy.names, "spy saw no submissions"
    assert set(spy.names) <= data_kernels, set(spy.names) - data_kernels


def test_bounded_in_flight_submissions(ray_start_regular, monkeypatch):
    """Pulling one block from a 64-task read submits O(parallelism) tasks,
    not the whole stage (backpressure reaches the submit window)."""
    spy = _SubmitSpy(monkeypatch)
    ds = rd.range(64, parallelism=64)
    ex = E.StreamingExecutor(4)
    it = ex.execute(ds._ops)
    next(it)
    assert 0 < spy.count("_exec_read") <= 2 * 4 + 1, spy.count("_exec_read")
    it.close()  # teardown; remaining tasks never submit
    assert spy.count("_exec_read") <= 2 * 4 + 2


def test_completion_order_yields_all_blocks(ray_start_regular):
    """preserve_order=False yields every block exactly once, a slow first
    task does not stall later blocks, and preserve_order=True keeps
    submission order."""

    def make_ops(sleep_first):
        def synth(b):
            if sleep_first and int(np.asarray(b["id"]).reshape(-1)[0]) == 0:
                time.sleep(2.0)
            return {"id": b["id"]}

        return rd.range(8, parallelism=8).map_batches(synth, batch_size=1)._ops

    # Warm the worker pool so spawn latency doesn't mask completion order.
    assert rd.range(8, parallelism=8).count() == 8

    ex = E.StreamingExecutor(8, preserve_order=False)
    t0 = time.perf_counter()
    got = []
    first_yield_at = None
    for bundle in ex.execute(make_ops(sleep_first=True)):
        if first_yield_at is None:
            first_yield_at = time.perf_counter() - t0
        got.extend(ray_tpu.get(bundle.block).column("id").to_pylist())
    assert sorted(got) == list(range(8))
    # The straggler (block 0, sleeping 2s) was NOT the first block out —
    # a finished block jumped the queue well before the straggler was done.
    assert got[0] != 0, got
    assert first_yield_at < 1.9, first_yield_at

    ex = E.StreamingExecutor(8, preserve_order=True)
    ordered = []
    for bundle in ex.execute(make_ops(sleep_first=False)):
        ordered.extend(ray_tpu.get(bundle.block).column("id").to_pylist())
    assert ordered == list(range(8))


def test_read_map_fusion():
    """A task-pool MapBlocks directly after Read folds INTO the read task:
    one fused stage, no intermediate block."""
    ds = rd.range(8, parallelism=2).map_batches(lambda b: {"y": b["id"] * 3})
    fused = E._fuse_maps(list(ds._ops))
    assert len(fused) == 1
    assert isinstance(fused[0], E.Read)
    assert "MapBatches" in fused[0].name
    out = fused[0].read_tasks[0]()
    assert out.column("y").to_pylist() == [0, 3, 6, 9]
    # Actor-pool stages must NOT fuse (they need the pool).
    ds2 = rd.range(8, parallelism=2).map_batches(
        type("U", (), {"__call__": lambda self, b: b}), concurrency=1
    )
    fused2 = E._fuse_maps(list(ds2._ops))
    assert len(fused2) == 2


def _tables(*row_counts):
    out = []
    base = 0
    for n in row_counts:
        out.append(pa.table({"v": list(range(base, base + n))}))
        base += n
    return out


def test_batcher_block_boundaries_and_drop_last():
    # Batch spans three blocks; remainder emitted when drop_last=False.
    blocks = _tables(3, 2, 4)  # 9 rows
    batches = list(batches_from_blocks(iter(blocks), 4, "pyarrow", False))
    assert [b.num_rows for b in batches] == [4, 4, 1]
    assert [v for b in batches for v in b.column("v").to_pylist()] == list(
        range(9)
    )
    # drop_last drops the short tail.
    batches = list(batches_from_blocks(iter(blocks), 4, "pyarrow", True))
    assert [b.num_rows for b in batches] == [4, 4]
    # Exact block boundary: no concat, batch IS a zero-copy slice.
    blocks = _tables(4, 4)
    batches = list(batches_from_blocks(iter(blocks), 4, "pyarrow", False))
    assert [b.num_rows for b in batches] == [4, 4]
    # Empty blocks are skipped, including a trailing one.
    blocks = [pa.table({"v": []}), *_tables(2, 3), pa.table({"v": []})]
    batches = list(batches_from_blocks(iter(blocks), 5, "pyarrow", False))
    assert [b.num_rows for b in batches] == [5]
    # batch_size=None passes blocks through unchanged.
    out = list(batches_from_blocks(iter(_tables(2, 3)), None, "pyarrow"))
    assert [b.num_rows for b in out] == [2, 3]


def test_batcher_slices_are_zero_copy():
    """A batch emitted from inside one block shares that block's buffers."""
    blk = pa.table({"v": np.arange(64, dtype=np.int64)})
    batches = list(batches_from_blocks(iter([blk]), 16, "pyarrow", False))
    assert len(batches) == 4
    src = blk.column("v").chunk(0).buffers()[1]
    for b in batches:
        bufs = b.column("v").chunk(0).buffers()
        assert bufs[1].address == src.address or (
            src.address <= bufs[1].address < src.address + src.size
        )


def test_iter_blocks_pipelined_order_and_close(ray_start_regular):
    refs = [ray_tpu.put(t) for t in _tables(2, 3, 4, 1)]
    closed = []

    def ref_gen():
        try:
            yield from refs
        finally:
            closed.append(True)

    got = list(iter_blocks_pipelined(ref_gen(), lookahead=3))
    assert [t.num_rows for t in got] == [2, 3, 4, 1]
    assert closed == [True]
    # Abandonment also closes the source generator.
    closed.clear()
    it = iter_blocks_pipelined(ref_gen(), lookahead=3)
    next(it)
    it.close()
    assert closed == [True]


def test_streaming_split_single_is_local_fast_path(ray_start_regular):
    """streaming_split(1) runs in-process (no coordinator actor); pickling
    ships the plan, so a remote consumer drives its own local execution."""
    ds = rd.range(24, parallelism=4)
    (it,) = ds.streaming_split(1)
    assert it._coord is None
    seen = []
    for b in it.iter_batches(batch_size=5):
        seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(24))
    # Second epoch works (fresh local execution per pass).
    assert it._coord is None
    seen2 = [v for b in it.iter_batches(batch_size=None) for v in b["id"]]
    assert sorted(seen2) == list(range(24))
    # Pickle round-trip carries the plan; the clone iterates independently
    # (one split == the whole dataset) and no actor is ever spawned.
    clone = pickle.loads(pickle.dumps(it))
    assert it._coord is None and clone._coord is None
    seen3 = [v for b in clone.iter_batches(batch_size=6) for v in b["id"]]
    assert sorted(seen3) == list(range(24))


def test_streaming_split_single_shipped_to_task(ray_start_regular):
    """A fast-path DataIterator survives ray serialization as a task arg:
    the receiving worker drives the execution itself."""
    (it,) = rd.range(12, parallelism=3).streaming_split(1)

    @ray_tpu.remote
    def consume(shard):
        return sorted(
            v for b in shard.iter_batches(batch_size=4) for v in b["id"]
        )

    assert ray_tpu.get(consume.remote(it), timeout=120) == list(range(12))


def test_streaming_split_completion_order_covers_rows(ray_start_regular):
    """Default split dispatch is completion-order; every row still arrives
    exactly once across splits."""
    ds = rd.range(36, parallelism=6)
    shards = ds.streaming_split(2)
    seen = []
    for s in shards:
        for b in s.iter_batches(batch_size=None):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(36))


def test_abandoned_actor_stage_teardown_is_fast(ray_start_regular):
    """Breaking out of iteration over an actor-pool stage cancels the
    undelivered window instead of riding it out: teardown completes far
    sooner than executing every remaining (slow) block would take."""

    class SlowUdf:
        def __call__(self, batch):
            time.sleep(0.5)
            return batch

    ds = rd.range(16, parallelism=16).map_batches(
        SlowUdf, concurrency=1, batch_size=None
    )
    it = ds.iter_batches(batch_size=None, prefetch_batches=0)
    next(it)
    t0 = time.perf_counter()
    it.close()  # abandon: 14+ blocks never delivered
    dt = time.perf_counter() - t0
    # Riding out the remaining blocks serially would cost >= 5s; the
    # cancel-or-seal teardown only waits for the in-flight window.
    assert dt < 4.0, f"teardown took {dt:.1f}s"


def _family_total(family):
    return sum(c.v for c in family._cells.values())


def test_ingest_telemetry_counters_move(ray_start_regular):
    before_blocks = _family_total(E._BLOCKS_PRODUCED)
    before_resolves = _family_total(E._META_RESOLVES)
    ds = rd.range(32, parallelism=4)
    assert ds.count() == 32
    list(ds.iter_batches(batch_size=8))
    assert _family_total(E._BLOCKS_PRODUCED) > before_blocks
    assert _family_total(E._META_RESOLVES) > before_resolves
