"""Accelerator manager registry + TPU pod detection + slice-aware scaling.

Reference analogs: python/ray/_private/accelerators/tpu.py (env + device +
GCE metadata probe order), autoscaler gcp/tpu pod handling."""

import http.server
import threading

import pytest

from ray_tpu._private.accelerators import (
    TPUAcceleratorManager,
    detect_accelerator_resources,
    get_accelerator_manager_for_resource,
)


@pytest.fixture
def fake_metadata_server():
    """A local GCE metadata server double (reference: tpu.py queries
    metadata.google.internal for accelerator-type / agent-worker-number)."""
    values = {
        "/computeMetadata/v1/instance/attributes/accelerator-type": "v5litepod-16",
        "/computeMetadata/v1/instance/attributes/agent-worker-number": "0",
    }

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            val = values.get(self.path)
            if val is None:
                self.send_response(404)
                self.end_headers()
                return
            body = val.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"127.0.0.1:{srv.server_port}", values
    srv.shutdown()


def test_tpu_detection_via_gce_metadata(fake_metadata_server, monkeypatch):
    host, _ = fake_metadata_server
    monkeypatch.setenv("GCE_METADATA_HOST", host)
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3")
    monkeypatch.delenv("TPU_POD_TYPE", raising=False)
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    res = detect_accelerator_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v5litepod-16-head"] == 1.0
    assert res["accelerator_type:TPU-V5LITEPOD"] == 1.0


def test_tpu_nonzero_worker_gets_no_head_resource(fake_metadata_server, monkeypatch):
    host, values = fake_metadata_server
    values["/computeMetadata/v1/instance/attributes/agent-worker-number"] = "2"
    monkeypatch.setenv("GCE_METADATA_HOST", host)
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3")
    monkeypatch.delenv("TPU_POD_TYPE", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    res = detect_accelerator_resources()
    assert res["TPU"] == 4.0
    assert "TPU-v5litepod-16-head" not in res


def test_manager_registry_lookup():
    assert get_accelerator_manager_for_resource("TPU") is TPUAcceleratorManager
    assert get_accelerator_manager_for_resource("GPU") is None


def test_pod_worker_count_heuristics():
    # v4 reports cores (2 per chip); 4 chips per host.
    assert TPUAcceleratorManager.get_num_workers_in_pod("v4-16") == 2
    assert TPUAcceleratorManager.get_num_workers_in_pod("v4-8") == 1
    # v5e reports chips directly.
    assert TPUAcceleratorManager.get_num_workers_in_pod("v5litepod-16") == 4
    assert TPUAcceleratorManager.get_num_workers_in_pod("bogus") == 1


def test_gce_provider_command_shapes():
    from ray_tpu.autoscaler.node_provider import GCETPUNodeProvider

    commands = []
    provider = GCETPUNodeProvider(
        project="proj-x",
        zone="us-central2-b",
        accelerator_type="v5litepod-8",
        runner=lambda cmd: commands.append(cmd) or "",
    )
    pid = provider.create_node("worker")
    assert provider.non_terminated_nodes() == [pid]
    create = commands[0]
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "--project=proj-x" in create and "--zone=us-central2-b" in create
    assert "--accelerator-type=v5litepod-8" in create
    provider.terminate_node(pid)
    assert commands[1][:5] == ["gcloud", "compute", "tpus", "tpu-vm", "delete"]
    assert provider.non_terminated_nodes() == []


def test_infeasible_task_does_not_block_feasible(shutdown_only):
    """A cluster-wide-infeasible demand parks on the side queue; feasible
    tasks behind it still schedule (no FIFO head-of-line blocking)."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 8})
    def needs_tpus():
        return 1

    @ray_tpu.remote
    def plain():
        return 42

    stuck = needs_tpus.remote()  # queues forever (no TPU node ever joins)
    assert ray_tpu.get(plain.remote(), timeout=60) == 42
    ready, pending = ray_tpu.wait([stuck], num_returns=1, timeout=1)
    assert not ready and pending


def test_autoscaler_launches_whole_pod_slice(shutdown_only):
    """A TPU pod-slice node type scales in whole slices: one demand unit
    launches every host of the slice as a gang, and idle scale-down removes
    the gang together (reference: TPU pod worker groups)."""
    import time

    import ray_tpu
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(
        cluster,
        node_types={
            "tpu-slice": {
                "resources": {"CPU": 1.0, "TPU": 4.0},
                "min_workers": 0,
                "max_workers": 2,
                "workers_per_slice": 2,
            }
        },
    )
    scaler = Autoscaler(
        provider, AutoscalerConfig(upscale_delay_s=0.1, idle_timeout_s=2.0)
    )

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 4})
    def tpu_task():
        time.sleep(3)
        return 1

    ref = tpu_task.remote()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not provider.non_terminated_nodes():
        scaler.update()
        time.sleep(0.2)
    # The whole 2-host slice came up at once.
    assert len(provider.non_terminated_nodes()) == 2
    assert ray_tpu.get(ref, timeout=60) == 1

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and provider.non_terminated_nodes():
        scaler.update()
        time.sleep(0.5)
    assert not provider.non_terminated_nodes(), "slice was not reclaimed"
    cluster.shutdown()
