"""Tests for the exhaustive interleaving explorer (devtools.explore).

Covers the virtualized event loop (determinism, virtual time, deadlock
detection), sleep-set DPOR pruning against naive enumeration on a toy
scenario with known footprints, trace save/replay round-trips, crash-point
enumeration over the WAL group-commit boundaries, the three control-plane
scenarios, and the double-grant mutation gate (explorer catches it; the
committed schedule in tests/schedules/ replays to the violation).
"""

import asyncio
import json
import os

import pytest

from ray_tpu.chaos import scenarios_explore
from ray_tpu.devtools import explore

SCHEDULES_DIR = os.path.join(os.path.dirname(__file__), "schedules")


# ---------------------------------------------------------------------------
# Toy scenario: three tasks, two conflicting, one independent
# ---------------------------------------------------------------------------


async def _toy_writer_a(shared):
    shared["a"] = shared.get("a", 0) + 1


async def _toy_writer_b(shared):
    shared["b"] = shared.get("b", 0) + 1


TOY_FOOTPRINTS = {
    "_toy_writer_a": {"reads": set(), "writes": {"self.a"}},
    "_toy_writer_b": {"reads": set(), "writes": {"self.b"}},
}


class _ToyScenario:
    def __init__(self, mutations=()):
        self.shared = {}

    async def run(self):
        await asyncio.gather(
            _toy_writer_a(self.shared),
            _toy_writer_b(self.shared),
            _toy_writer_a(self.shared),
        )
        return []

    def cleanup(self):
        pass


def _toy_explorer(dpor):
    return explore.Explorer(
        _ToyScenario,
        oracle=explore.IndependenceOracle(TOY_FOOTPRINTS),
        dpor=dpor,
    )


def test_toy_exhausts_clean():
    report = _toy_explorer(dpor=True).explore("toy", budget=10000)
    assert report.complete
    assert report.violations == 0
    assert report.schedules >= 1


def test_dpor_prunes_vs_naive_same_verdict():
    dpor = _toy_explorer(dpor=True).explore("toy", budget=10000)
    naive = _toy_explorer(dpor=False).explore("toy", budget=10000)
    assert dpor.complete and naive.complete
    # Sleep sets must cut the enumeration without changing the verdict.
    # (The savings surface as branches never tried — `pruned` only counts
    # runs abandoned mid-flight, which this tiny space may not produce.)
    assert dpor.schedules < naive.schedules
    assert naive.pruned == 0
    assert dpor.violations == naive.violations == 0


def test_enumeration_deterministic():
    first = _toy_explorer(dpor=True).explore("toy", budget=10000)
    second = _toy_explorer(dpor=True).explore("toy", budget=10000)
    assert first.digest == second.digest
    assert first.schedules == second.schedules


# ---------------------------------------------------------------------------
# Independence oracle
# ---------------------------------------------------------------------------


def test_oracle_rules():
    oracle = explore.IndependenceOracle(TOY_FOOTPRINTS)
    # Disjoint write sets commute.
    assert oracle.independent("task:_toy_writer_a#0", "task:_toy_writer_b#0")
    # Same qualname: conservatively dependent (same instance state).
    assert not oracle.independent("task:_toy_writer_a#0", "task:_toy_writer_a#1")
    # Unknown qualnames: conservatively dependent.
    assert not oracle.independent("task:_toy_writer_a#0", "task:mystery#0")


def test_oracle_repo_footprints_capture_writes():
    fp = explore.repo_footprints()
    # Spot-check: the store flush path writes its pending buffer.
    ent = fp.get("ReplicatedStoreClient.put")
    assert ent is not None
    assert "self._pending" in ent["writes"]


# ---------------------------------------------------------------------------
# VirtualLoop semantics
# ---------------------------------------------------------------------------


def test_virtual_time_ordering():
    loop = explore.VirtualLoop()
    order = []

    async def main():
        async def late():
            await asyncio.sleep(5.0)
            order.append("late")

        async def early():
            await asyncio.sleep(1.0)
            order.append("early")

        await asyncio.gather(late(), early())
        return []

    try:
        loop.drive(main(), lambda enabled: enabled[0], 1000)
    finally:
        loop.close()
    assert order == ["early", "late"]
    # Virtual clock jumped to the furthest deadline without real sleeping.
    assert loop.time() >= 5.0


def test_deadlock_detected():
    loop = explore.VirtualLoop()

    async def main():
        await asyncio.get_running_loop().create_future()  # never resolved

    try:
        with pytest.raises(explore.DeadlockError):
            loop.drive(main(), lambda enabled: enabled[0], 1000)
    finally:
        loop.close()


def test_max_steps_guard():
    loop = explore.VirtualLoop()

    async def main():
        while True:
            await asyncio.sleep(0)

    try:
        with pytest.raises(explore.ExploreError):
            loop.drive(main(), lambda enabled: enabled[0], 50)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# Trace save / load / replay
# ---------------------------------------------------------------------------


def test_trace_round_trip(tmp_path):
    report = _toy_explorer(dpor=True).explore("toy", budget=10000)
    assert report.schedules > 0
    # Re-run one schedule by replaying the first run's recorded choices:
    # enumerate once more and take the first record via a fresh explorer.
    ex = _toy_explorer(dpor=True)
    rec = ex._run_once()
    path = tmp_path / "trace.json"
    explore.save_trace(str(path), "toy", rec, mutations=[])
    data = explore.load_trace(str(path))
    assert data["scenario"] == "toy"
    assert data["trace"] == rec.choices
    replayed = explore.replay(_ToyScenario, data["trace"])
    assert replayed.status == rec.status == "ok"
    assert replayed.choices == rec.choices


def test_replay_divergence_detected():
    ex = _toy_explorer(dpor=True)
    rec = ex._run_once()
    bogus = ["task:not_a_real_event#0"] + rec.choices
    with pytest.raises(explore.NondeterminismError):
        explore.replay(_ToyScenario, bogus)


# ---------------------------------------------------------------------------
# Crash-point enumeration
# ---------------------------------------------------------------------------


def test_crash_scan_wal(tmp_path):
    report = explore.crash_scan_wal(str(tmp_path))
    assert report.commits > 0
    # Every commit boundary is probed twice: clean truncation + torn tail.
    assert report.cases == 2 * report.commits
    assert report.failures == []


def test_crash_scan_replicated(tmp_path):
    report = explore.crash_scan_replicated(str(tmp_path))
    assert report.commits > 0
    # Per commit image of the 3-member group: lose each single member,
    # each with a clean and a torn-tail survivor variant.
    assert report.cases == 6 * report.commits
    assert report.failures == []


# ---------------------------------------------------------------------------
# Control-plane scenarios
# ---------------------------------------------------------------------------


def _explore_scenario(name, budget, mutations=(), stop_on_violation=False):
    spec = scenarios_explore.SCENARIOS[name]
    ex = explore.Explorer(
        lambda: spec.factory(mutations=list(mutations)),
        oracle=explore.IndependenceOracle(explore.repo_footprints()),
        dpor=True,
    )
    return ex.explore(name, budget=budget, stop_on_violation=stop_on_violation)


def test_lease_exactly_once_exhausts_clean():
    report = _explore_scenario("lease_exactly_once", budget=6000)
    assert report.complete, report.summary()
    assert report.violations == 0, report.first_violation
    assert report.schedules > 100  # a real space, not a degenerate one


def test_ha_promotion_bounded_clean():
    report = _explore_scenario("ha_promotion", budget=400)
    assert report.violations == 0, report.first_violation
    assert report.schedules + report.pruned > 100


def test_resubscribe_gap_bounded_clean():
    report = _explore_scenario("resubscribe_gap", budget=300)
    assert report.violations == 0, report.first_violation
    # Measured space: ~99 interleavings. The publisher's inline fan-out
    # (no drain task when a subscriber has no backlog and a writable
    # transport) removed one task-spawn choice point per delivery, so the
    # space is smaller than the pre-batching ~150 — still far from
    # degenerate.
    assert report.schedules + report.pruned > 60


def test_quorum_election_exhausts_clean():
    # Measured space: 591 schedules — small enough to exhaust inline.
    report = _explore_scenario("quorum_election", budget=2000)
    assert report.complete, report.summary()
    assert report.violations == 0, report.first_violation
    assert report.schedules > 100


@pytest.mark.slow
def test_ha_promotion_exhausts_clean():
    # Measured space: 29369 schedules (~1 min); budget leaves headroom so
    # the assert fails loudly if the scenario ever grows past exhaustibility.
    report = _explore_scenario("ha_promotion", budget=40000)
    assert report.complete, report.summary()
    assert report.violations == 0, report.first_violation


# ---------------------------------------------------------------------------
# Mutation gate: the seeded double-grant bug must be caught
# ---------------------------------------------------------------------------


def test_mutation_double_grant_caught_in_budget():
    report = _explore_scenario(
        "lease_exactly_once",
        budget=2000,
        mutations=("double_grant",),
        stop_on_violation=True,
    )
    assert report.violations > 0
    assert any(
        "resource-ledger" in v for v in report.first_violation.violations
    )


def test_committed_double_grant_trace_replays_to_violation():
    path = os.path.join(SCHEDULES_DIR, "lease_double_grant.json")
    data = explore.load_trace(path)
    assert data["scenario"] == "lease_exactly_once"
    assert data["mutations"] == ["double_grant"]
    spec = scenarios_explore.SCENARIOS["lease_exactly_once"]
    rec = explore.replay(
        lambda: spec.factory(mutations=data["mutations"]), data["trace"]
    )
    assert rec.status == "violation"
    assert any("resource-ledger" in v for v in rec.violations)


def test_unmutated_scenario_on_violation_schedule_is_clean():
    """The schedule that kills the mutant must be survivable by the fix.

    The fixed code takes a different branch (duplicate detection), so the
    trace diverges — either a clean completion or a NondeterminismError at
    the divergence point is acceptable; a violation is not.
    """
    path = os.path.join(SCHEDULES_DIR, "lease_double_grant.json")
    data = explore.load_trace(path)
    spec = scenarios_explore.SCENARIOS["lease_exactly_once"]
    try:
        rec = explore.replay(lambda: spec.factory(), data["trace"])
    except explore.NondeterminismError:
        return
    assert rec.status == "ok", rec.violations


def test_unknown_mutation_rejected():
    spec = scenarios_explore.SCENARIOS["lease_exactly_once"]
    with pytest.raises(ValueError):
        spec.factory(mutations=["not_a_mutation"])


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_list(capsys):
    assert explore.main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in scenarios_explore.SCENARIOS:
        assert name in out


def test_cli_replay_committed_trace(capsys):
    path = os.path.join(SCHEDULES_DIR, "lease_double_grant.json")
    assert explore.main(["--replay", path, "--expect-violation"]) == 0


def test_trace_file_is_valid_json():
    path = os.path.join(SCHEDULES_DIR, "lease_double_grant.json")
    with open(path) as fh:
        data = json.load(fh)
    assert data["format"] == explore.TRACE_FORMAT
    assert isinstance(data["trace"], list) and data["trace"]
