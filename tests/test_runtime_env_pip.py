"""runtime_env pip support: per-spec cached venvs, offline wheel install.

Reference analog: python/ray/_private/runtime_env/pip.py (PipProcessor).
The test builds a local wheel and installs it with --no-index so no network
is needed."""

import os
import zipfile

import pytest

import ray_tpu

WHEEL_NAME = "rtpu_testpkg-0.1.0-py3-none-any.whl"


def _build_wheel(dirpath: str) -> str:
    """A minimal spec-compliant wheel for a one-module package."""
    path = os.path.join(dirpath, WHEEL_NAME)
    meta = (
        "Metadata-Version: 2.1\nName: rtpu-testpkg\nVersion: 0.1.0\n"
    )
    wheel = (
        "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
        "Tag: py3-none-any\n"
    )
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("rtpu_testpkg/__init__.py", "MAGIC = 12345\n")
        zf.writestr("rtpu_testpkg-0.1.0.dist-info/METADATA", meta)
        zf.writestr("rtpu_testpkg-0.1.0.dist-info/WHEEL", wheel)
        zf.writestr(
            "rtpu_testpkg-0.1.0.dist-info/RECORD",
            "rtpu_testpkg/__init__.py,,\n"
            "rtpu_testpkg-0.1.0.dist-info/METADATA,,\n"
            "rtpu_testpkg-0.1.0.dist-info/WHEEL,,\n"
            "rtpu_testpkg-0.1.0.dist-info/RECORD,,\n",
        )
    return path


def test_pip_env_installs_and_imports(shutdown_only, tmp_path):
    _build_wheel(str(tmp_path))
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote(
        runtime_env={
            "pip": {
                "packages": ["rtpu-testpkg"],
                "pip_install_options": [
                    "--no-index", "--find-links", str(tmp_path),
                ],
            }
        }
    )
    def use_pkg():
        import rtpu_testpkg

        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(use_pkg.remote(), timeout=180) == 12345


def test_pip_install_failure_is_loud(shutdown_only, tmp_path):
    """A missing package must FAIL the task (previously pip was silently
    ignored and the task ran without its dependencies)."""
    ray_tpu.init(num_cpus=2, num_tpus=0)

    @ray_tpu.remote(
        max_retries=0,
        runtime_env={
            "pip": {
                "packages": ["definitely-not-a-real-pkg-xyz"],
                "pip_install_options": [
                    "--no-index", "--find-links", str(tmp_path),
                ],
            }
        },
    )
    def f():
        return 1

    with pytest.raises(Exception, match="pip install"):
        ray_tpu.get(f.remote(), timeout=180)


def test_conda_rejected_at_submission(shutdown_only):
    ray_tpu.init(num_cpus=1, num_tpus=0)

    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["x"]}})
    def f():
        return 1

    with pytest.raises(Exception, match="conda"):
        ray_tpu.get(f.remote(), timeout=60)
