"""End-to-end tests of the `xla` collective backend — the framework's
flagship path (SURVEY.md §7 step 5): ranks are SEPARATE worker processes,
rendezvous through the GCS KV, `jax.distributed.initialize` forms the
multi-process cluster, and collectives run as jitted programs over the
GLOBAL device mesh.

Reference analog: NCCL group bootstrap + allreduce in
python/ray/util/collective/collective_group/nccl_collective_group.py:127 and
Train's process-group setup in python/ray/train/torch/config.py:65-147.

Each worker process sees 4 virtual CPU devices (JAX_NUM_CPU_DEVICES), so a
2-process group spans a real 2x4 global mesh: cross-process collectives
exercise the same make_array_from_single_device_arrays + jit machinery that
carries ICI traffic on TPU pods.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.testing import cpu_mesh_worker_env
from ray_tpu.train.jax import JaxConfig, JaxTrainer

WORLD = 2
DEVICES_PER_PROC = 4


@pytest.fixture
def ray_xla_cluster(shutdown_only):
    """Cluster whose worker processes each see 4 virtual CPU devices, so a
    2-rank xla group forms an 8-device global mesh across 2 OS processes."""
    ray_tpu.init(
        num_cpus=8,
        num_tpus=0,
        worker_env=cpu_mesh_worker_env(DEVICES_PER_PROC),
    )
    yield


def _rank_cls():
    @ray_tpu.remote(num_cpus=1)
    class XlaRank:
        """One rank = one worker process = one jax.distributed process."""

        def __init__(self, rank: int, world: int, group: str):
            from ray_tpu.util import collective as col

            col.init_collective_group(
                world, rank, backend="xla", group_name=group
            )
            self.rank = rank
            self.group = group

        def mesh_shape(self):
            import jax

            from ray_tpu.util import collective as col

            mesh = col.get_group_mesh(self.group)
            return {
                "local": jax.local_device_count(),
                "global": jax.device_count(),
                "mesh_shape": dict(mesh.shape),
            }

        def do_allreduce(self, value):
            from ray_tpu.util import collective as col

            return col.allreduce(
                np.full((3,), value, dtype=np.float32), group_name=self.group
            )

        def do_allgather(self):
            from ray_tpu.util import collective as col

            return col.allgather(
                np.full((2,), self.rank, dtype=np.float32),
                group_name=self.group,
            )

        def do_broadcast(self):
            from ray_tpu.util import collective as col

            val = (
                np.arange(4, dtype=np.float32)
                if self.rank == 0
                else np.zeros(4, dtype=np.float32)
            )
            return col.broadcast(val, src_rank=0, group_name=self.group)

        def do_reducescatter(self):
            from ray_tpu.util import collective as col

            return col.reducescatter(
                np.arange(8, dtype=np.float32), group_name=self.group
            )

        def do_barrier(self):
            from ray_tpu.util import collective as col

            col.barrier(group_name=self.group)
            return True

        def shutdown_group(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(self.group)

    return XlaRank


def test_xla_backend_collectives_across_processes(ray_xla_cluster):
    """allreduce/allgather/broadcast/reducescatter/barrier on the xla
    backend with 2 ranks in 2 separate worker processes."""
    XlaRank = _rank_cls()
    actors = [XlaRank.remote(i, WORLD, "xg") for i in range(WORLD)]

    # The group IS a mesh: 2 processes x 4 local devices.
    shapes = ray_tpu.get([a.mesh_shape.remote() for a in actors], timeout=180)
    for s in shapes:
        assert s["local"] == DEVICES_PER_PROC
        assert s["global"] == WORLD * DEVICES_PER_PROC
        assert s["mesh_shape"] == {"world": WORLD, "local": DEVICES_PER_PROC}

    outs = ray_tpu.get(
        [a.do_allreduce.remote(float(i + 1)) for i, a in enumerate(actors)],
        timeout=180,
    )
    for out in outs:
        np.testing.assert_allclose(out, np.full((3,), 3.0, dtype=np.float32))

    outs = ray_tpu.get([a.do_allgather.remote() for a in actors], timeout=180)
    for out in outs:
        assert [int(piece[0]) for piece in out] == [0, 1]

    outs = ray_tpu.get([a.do_broadcast.remote() for a in actors], timeout=180)
    for out in outs:
        np.testing.assert_allclose(out, np.arange(4, dtype=np.float32))

    outs = ray_tpu.get(
        [a.do_reducescatter.remote() for a in actors], timeout=180
    )
    np.testing.assert_allclose(outs[0], np.arange(4, dtype=np.float32) * 2)
    np.testing.assert_allclose(outs[1], np.arange(4, 8, dtype=np.float32) * 2)

    assert ray_tpu.get(
        [a.do_barrier.remote() for a in actors], timeout=180
    ) == [True, True]

    ray_tpu.get([a.shutdown_group.remote() for a in actors], timeout=60)


def _make_spmd_train_fn():
    """Returns the train fn as a closure so cloudpickle ships it by value
    (worker processes cannot import this test module).

    One shard_map-style SPMD step over the GLOBAL mesh: every rank feeds
    its process-local shard of the batch, the jitted loss computation runs
    over all 8 devices spanning both processes, and the scalar loss comes
    back identical (and equal to the single-process numpy value) on every
    rank."""

    def _spmd_train_fn(config):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu import parallel

        ctx = train.get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        assert jax.device_count() == config["global_devices"], (
            "xla backend did not form the global multi-process device cluster"
        )

        # Deterministic dataset; every rank can reconstruct the whole thing.
        n, d = config["rows"], config["feat"]
        rng = np.random.RandomState(0)
        x_all = rng.rand(n, d).astype(np.float32)
        w = rng.rand(d, 1).astype(np.float32)
        y_all = rng.rand(n, 1).astype(np.float32)

        mesh = parallel.make_mesh({"data": -1})  # global: all 8 devices
        sharding = NamedSharding(mesh, P("data"))

        # Each process donates its local rows as per-device shards.
        local_rows = n // world
        x_local = x_all[rank * local_rows : (rank + 1) * local_rows]
        per_dev = np.split(x_local, len(mesh.local_devices))
        x_global = jax.make_array_from_single_device_arrays(
            (n, d),
            sharding,
            [jax.device_put(s, dev) for s, dev in zip(per_dev, mesh.local_devices)],
        )

        @jax.jit
        def loss_fn(x):
            pred = x @ jnp.asarray(w)
            return jnp.mean((pred - jnp.asarray(y_all)) ** 2)

        for step in range(config["steps"]):
            loss = float(jax.device_get(loss_fn(x_global)))
            train.report({"loss": loss, "step": step, "rank": rank})

    return _spmd_train_fn


def test_jax_trainer_xla_backend_spmd_parity(ray_xla_cluster, tmp_path):
    """JaxTrainer with collective_backend='xla': the full runtime path — PG
    gang, worker actors, GCS-KV rendezvous, jax.distributed, one SPMD
    program over the 2-process global mesh — with loss parity against the
    single-process numpy computation."""
    rows, feat, steps = 64, 8, 2
    trainer = JaxTrainer(
        _make_spmd_train_fn(),
        train_loop_config={
            "rows": rows,
            "feat": feat,
            "steps": steps,
            "global_devices": WORLD * DEVICES_PER_PROC,
        },
        backend_config=JaxConfig(collective_backend="xla"),
        scaling_config=ScalingConfig(num_workers=WORLD),
        run_config=RunConfig(name="t_xla_spmd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == steps

    # Parity: the sharded-global-mesh loss must equal plain numpy.
    rng = np.random.RandomState(0)
    x_all = rng.rand(rows, feat).astype(np.float32)
    w = rng.rand(feat, 1).astype(np.float32)
    y_all = rng.rand(rows, 1).astype(np.float32)
    expected = float(np.mean((x_all @ w - y_all) ** 2))
    assert result.metrics["loss"] == pytest.approx(expected, rel=1e-4)


# -- single-process engine tests over the 8-device forced CPU mesh ------------
#
# The MeshCollectives engine (mesh_ops.py) is the compiled core of the xla
# backend: every group op is one cached shard_map program. These tests drive
# all `world` ranks from one process via stage_parts — the same programs the
# multi-controller path runs, minus jax.distributed (which the CPU backend
# does not implement across processes).

ENGINE_WORLD = 8


@pytest.fixture(scope="module")
def engine():
    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(ENGINE_WORLD)
    import jax
    from jax.sharding import Mesh

    from ray_tpu.util.collective.mesh_ops import MeshCollectives

    mesh = Mesh(np.asarray(jax.devices()[:ENGINE_WORLD]), ("world",))
    return MeshCollectives(mesh, axis="world", group_name="t_engine")


def _rank_parts(shape=(4, 6), seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randn(*shape).astype(np.float32) for _ in range(ENGINE_WORLD)]


def test_engine_allreduce_ops(engine):
    from ray_tpu.util.collective import mesh_ops as mo

    parts = _rank_parts()
    g = engine.stage_parts(parts)
    np.testing.assert_allclose(
        np.asarray(engine.allreduce(g, mo.SUM)), np.sum(parts, axis=0),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(engine.allreduce(g, mo.MAX)), np.max(parts, axis=0)
    )
    np.testing.assert_allclose(
        np.asarray(engine.allreduce(g, mo.MIN)), np.min(parts, axis=0)
    )
    pos = [np.abs(p) + 0.1 for p in parts]
    np.testing.assert_allclose(
        np.asarray(engine.allreduce(engine.stage_parts(pos), mo.PRODUCT)),
        np.prod(pos, axis=0),
        rtol=1e-3,
    )


def test_engine_allgather(engine):
    parts = _rank_parts(seed=1)
    out = np.asarray(engine.allgather(engine.stage_parts(parts)))
    assert out.shape == (ENGINE_WORLD, 4, 6)
    np.testing.assert_allclose(out, np.stack(parts), rtol=1e-5)


def test_engine_reducescatter(engine):
    from ray_tpu.util.collective import mesh_ops as mo

    parts = _rank_parts(shape=(16, 3), seed=2)
    g = engine.stage_parts(parts)
    block = 16 // ENGINE_WORLD
    red = np.sum(parts, axis=0)
    out = engine.reducescatter(g, mo.SUM)
    for r in range(ENGINE_WORLD):
        np.testing.assert_allclose(
            engine.rank_shard(out, r), red[r * block : (r + 1) * block],
            rtol=1e-4, atol=1e-4,
        )
    # Non-SUM ops lower to reduce + per-rank dynamic slice.
    redm = np.max(parts, axis=0)
    outm = engine.reducescatter(g, mo.MAX)
    for r in range(ENGINE_WORLD):
        np.testing.assert_allclose(
            engine.rank_shard(outm, r), redm[r * block : (r + 1) * block]
        )


@pytest.mark.parametrize("src", [0, 3, 7])
def test_engine_broadcast_ppermute_tree(engine, src):
    parts = _rank_parts(seed=3 + src)
    out = engine.broadcast(engine.stage_parts(parts), src)
    for r in range(ENGINE_WORLD):
        np.testing.assert_allclose(engine.rank_shard(out, r)[0], parts[src])


def test_engine_permute_send_recv(engine):
    """ppermute [(src, dst)] is the compiled send/recv hop: dst's row takes
    src's shard, every non-destination row reads zeros."""
    parts = _rank_parts(seed=11)
    out = engine.permute(engine.stage_parts(parts), [(2, 5)])
    np.testing.assert_allclose(engine.rank_shard(out, 5)[0], parts[2])
    np.testing.assert_allclose(
        engine.rank_shard(out, 0)[0], np.zeros_like(parts[0])
    )
    # ring shift: every rank passes to its right neighbor
    ring = [(i, (i + 1) % ENGINE_WORLD) for i in range(ENGINE_WORLD)]
    out = engine.permute(engine.stage_parts(parts), ring)
    for r in range(ENGINE_WORLD):
        np.testing.assert_allclose(
            engine.rank_shard(out, r)[0], parts[(r - 1) % ENGINE_WORLD]
        )


def test_engine_barrier(engine):
    engine.barrier()
    engine.barrier()  # second call reuses the cached staged input + program


def test_engine_program_cache_and_staging_cache(engine):
    parts = _rank_parts(seed=4)
    token = parts[0]
    g1 = engine.stage_parts(parts, cache_token=token)
    engine.allreduce(g1)
    n_prog = len(engine._programs)
    hits = engine.stats["stage_hits"]
    g2 = engine.stage_parts(parts, cache_token=token)
    assert g2 is g1, "identity-keyed staging cache must hit"
    assert engine.stats["stage_hits"] == hits + 1
    engine.allreduce(g2)
    assert len(engine._programs) == n_prog, (
        "repeat allreduce of the same (op, shape, dtype) must reuse the "
        "compiled program"
    )
    # stage_local identity cache + invalidation
    local = parts[1]
    s1 = engine.stage_local(local, 0)
    s2 = engine.stage_local(local, 0)
    assert s2 is s1
    engine.invalidate(local)
    assert engine.stage_local(local, 0) is not s1


def test_allgather_no_worldx_host_staging(engine):
    """Regression for the retired one-hot allgather: staging a 1 MiB shard
    must copy ~1 MiB to devices, not world x 1 MiB (the old path allocated
    and all-reduced a world-sized zero-padded host buffer per call)."""
    shard = np.ones((1 << 18,), dtype=np.float32)  # 1 MiB
    before = engine.stats["staged_bytes"]
    staged = engine.stage_local(shard, 0, cache=False)
    copied = engine.stats["staged_bytes"] - before
    assert copied == shard.nbytes, (
        f"staging copied {copied} bytes for a {shard.nbytes}-byte shard "
        f"(world x blowup would be {ENGINE_WORLD * shard.nbytes})"
    )
    out = np.asarray(engine.allgather(staged))
    assert out.shape == (ENGINE_WORLD,) + shard.shape
    np.testing.assert_allclose(out[0], shard)


def test_xla_group_zero_store_roundtrips(engine, monkeypatch):
    """Acceptance: on the xla backend, allreduce/allgather/reducescatter/
    broadcast run zero _CollectiveStore actor round trips. The spy wraps
    ActorMethod.remote (every actor task submission funnels through it) and
    the store-actor factory; neither may fire."""
    from ray_tpu import actor as actor_mod
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import collective as col_impl

    submits = []
    orig = actor_mod.ActorMethod.remote

    def spy(self, *a, **kw):
        submits.append(self._name)
        return orig(self, *a, **kw)

    monkeypatch.setattr(actor_mod.ActorMethod, "remote", spy)
    monkeypatch.setattr(
        col_impl,
        "_store_actor_cls",
        lambda: (_ for _ in ()).throw(
            AssertionError("xla backend must not build a store actor")
        ),
    )

    col.init_collective_group(1, 0, backend="xla", group_name="t_spy")
    try:
        group = col_impl._manager.get("t_spy")
        assert group.store is None
        assert group.engine is not None
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(col.allreduce(x, "t_spy"), x)
        got = col.allgather(x, "t_spy")
        assert len(got) == 1
        np.testing.assert_allclose(got[0], x)
        np.testing.assert_allclose(col.reducescatter(x, "t_spy"), x)
        np.testing.assert_allclose(col.broadcast(x, 0, "t_spy"), x)
        col.barrier("t_spy")
    finally:
        col.destroy_collective_group("t_spy")
    assert submits == [], f"xla collectives submitted actor tasks: {submits}"


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_on_mesh_parity(engine, causal):
    """Engine ring attention vs the generic sharded path AND the dense
    reference: same inputs, allclose."""
    from ray_tpu.parallel import full_attention, ring_attention_sharded

    B, T, H, D = 2, 32, 4, 16
    rng = np.random.RandomState(7)
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)

    mesh_out = np.asarray(engine.ring_attention(q, k, v, causal=causal))
    generic = np.asarray(
        ring_attention_sharded(
            q, k, v, engine.mesh, causal=causal, seq_axis="world"
        )
    )
    dense = np.asarray(full_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(mesh_out, generic, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(mesh_out, dense, rtol=2e-4, atol=2e-4)


def test_ulysses_on_mesh_parity(engine):
    from ray_tpu.parallel import full_attention

    B, T, H, D = 2, 32, 8, 16
    rng = np.random.RandomState(8)
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)
    out = np.asarray(engine.ulysses_attention(q, k, v))
    ref = np.asarray(full_attention(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_collective_telemetry_families(engine):
    """Group ops must feed the collective_op_latency_s histogram and the
    collective_bytes counter (rendered with _total; docs/observability.md)."""
    import json

    from ray_tpu._private import telemetry

    telemetry.flush_delta("t", "n")  # drain prior tests' observations
    parts = _rank_parts(seed=9)
    engine.allreduce(engine.stage_parts(parts))
    payload = telemetry.flush_delta("t", "n")
    series = {
        (m["c"], m["n"]): m for m in (payload or {"metrics": []})["metrics"]
    }
    lat = series.get(("collective", "op_latency_s"))
    assert lat is not None and lat["k"] == "histogram"
    byt = series.get(("collective", "bytes"))
    assert byt is not None and byt["k"] == "counter"
    labels = [dict(json.loads(k)) for k, _ in byt["s"]]
    assert {"op": "allreduce", "group": "t_engine"} in labels
    contributed = sum(
        v for k, v in byt["s"]
        if dict(json.loads(k)) == {"op": "allreduce", "group": "t_engine"}
    )
    assert contributed == parts[0].nbytes


def test_store_backend_participant_death_raises_typed_error(
    ray_start_regular,
):
    """Satellite: a rank dying mid-collective fails the group op with
    CollectiveGroupDiedError well inside the op deadline — never a hang."""
    import time

    from ray_tpu.util.collective import CollectiveGroupDiedError

    @ray_tpu.remote(num_cpus=1)
    class Rank:
        def __init__(self, rank):
            from ray_tpu.util import collective as col

            col.init_collective_group(
                2, rank, backend="store", group_name="t_death"
            )

        def ready(self):
            return True

        def reduce(self):
            from ray_tpu.util import collective as col

            col.allreduce(np.ones(4, dtype=np.float32), "t_death")
            return "completed"

    a, b = Rank.remote(0), Rank.remote(1)
    assert ray_tpu.get([a.ready.remote(), b.ready.remote()], timeout=60)
    # Rank 0 blocks in the rendezvous (rank 1 never contributes)...
    ref = a.reduce.remote()
    time.sleep(1.0)
    # ...then rank 1 dies mid-collective.
    ray_tpu.kill(b)
    t0 = time.monotonic()
    with pytest.raises(CollectiveGroupDiedError):
        ray_tpu.get(ref, timeout=60)
    assert time.monotonic() - t0 < 30, (
        "death detection must beat the op deadline by a wide margin"
    )
