"""End-to-end tests of the `xla` collective backend — the framework's
flagship path (SURVEY.md §7 step 5): ranks are SEPARATE worker processes,
rendezvous through the GCS KV, `jax.distributed.initialize` forms the
multi-process cluster, and collectives run as jitted programs over the
GLOBAL device mesh.

Reference analog: NCCL group bootstrap + allreduce in
python/ray/util/collective/collective_group/nccl_collective_group.py:127 and
Train's process-group setup in python/ray/train/torch/config.py:65-147.

Each worker process sees 4 virtual CPU devices (JAX_NUM_CPU_DEVICES), so a
2-process group spans a real 2x4 global mesh: cross-process collectives
exercise the same make_array_from_single_device_arrays + jit machinery that
carries ICI traffic on TPU pods.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.testing import cpu_mesh_worker_env
from ray_tpu.train.jax import JaxConfig, JaxTrainer

WORLD = 2
DEVICES_PER_PROC = 4


@pytest.fixture
def ray_xla_cluster(shutdown_only):
    """Cluster whose worker processes each see 4 virtual CPU devices, so a
    2-rank xla group forms an 8-device global mesh across 2 OS processes."""
    ray_tpu.init(
        num_cpus=8,
        num_tpus=0,
        worker_env=cpu_mesh_worker_env(DEVICES_PER_PROC),
    )
    yield


def _rank_cls():
    @ray_tpu.remote(num_cpus=1)
    class XlaRank:
        """One rank = one worker process = one jax.distributed process."""

        def __init__(self, rank: int, world: int, group: str):
            from ray_tpu.util import collective as col

            col.init_collective_group(
                world, rank, backend="xla", group_name=group
            )
            self.rank = rank
            self.group = group

        def mesh_shape(self):
            import jax

            from ray_tpu.util import collective as col

            mesh = col.get_group_mesh(self.group)
            return {
                "local": jax.local_device_count(),
                "global": jax.device_count(),
                "mesh_shape": dict(mesh.shape),
            }

        def do_allreduce(self, value):
            from ray_tpu.util import collective as col

            return col.allreduce(
                np.full((3,), value, dtype=np.float32), group_name=self.group
            )

        def do_allgather(self):
            from ray_tpu.util import collective as col

            return col.allgather(
                np.full((2,), self.rank, dtype=np.float32),
                group_name=self.group,
            )

        def do_broadcast(self):
            from ray_tpu.util import collective as col

            val = (
                np.arange(4, dtype=np.float32)
                if self.rank == 0
                else np.zeros(4, dtype=np.float32)
            )
            return col.broadcast(val, src_rank=0, group_name=self.group)

        def do_reducescatter(self):
            from ray_tpu.util import collective as col

            return col.reducescatter(
                np.arange(8, dtype=np.float32), group_name=self.group
            )

        def do_barrier(self):
            from ray_tpu.util import collective as col

            col.barrier(group_name=self.group)
            return True

        def shutdown_group(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(self.group)

    return XlaRank


def test_xla_backend_collectives_across_processes(ray_xla_cluster):
    """allreduce/allgather/broadcast/reducescatter/barrier on the xla
    backend with 2 ranks in 2 separate worker processes."""
    XlaRank = _rank_cls()
    actors = [XlaRank.remote(i, WORLD, "xg") for i in range(WORLD)]

    # The group IS a mesh: 2 processes x 4 local devices.
    shapes = ray_tpu.get([a.mesh_shape.remote() for a in actors], timeout=180)
    for s in shapes:
        assert s["local"] == DEVICES_PER_PROC
        assert s["global"] == WORLD * DEVICES_PER_PROC
        assert s["mesh_shape"] == {"world": WORLD, "local": DEVICES_PER_PROC}

    outs = ray_tpu.get(
        [a.do_allreduce.remote(float(i + 1)) for i, a in enumerate(actors)],
        timeout=180,
    )
    for out in outs:
        np.testing.assert_allclose(out, np.full((3,), 3.0, dtype=np.float32))

    outs = ray_tpu.get([a.do_allgather.remote() for a in actors], timeout=180)
    for out in outs:
        assert [int(piece[0]) for piece in out] == [0, 1]

    outs = ray_tpu.get([a.do_broadcast.remote() for a in actors], timeout=180)
    for out in outs:
        np.testing.assert_allclose(out, np.arange(4, dtype=np.float32))

    outs = ray_tpu.get(
        [a.do_reducescatter.remote() for a in actors], timeout=180
    )
    np.testing.assert_allclose(outs[0], np.arange(4, dtype=np.float32) * 2)
    np.testing.assert_allclose(outs[1], np.arange(4, 8, dtype=np.float32) * 2)

    assert ray_tpu.get(
        [a.do_barrier.remote() for a in actors], timeout=180
    ) == [True, True]

    ray_tpu.get([a.shutdown_group.remote() for a in actors], timeout=60)


def _make_spmd_train_fn():
    """Returns the train fn as a closure so cloudpickle ships it by value
    (worker processes cannot import this test module).

    One shard_map-style SPMD step over the GLOBAL mesh: every rank feeds
    its process-local shard of the batch, the jitted loss computation runs
    over all 8 devices spanning both processes, and the scalar loss comes
    back identical (and equal to the single-process numpy value) on every
    rank."""

    def _spmd_train_fn(config):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu import parallel

        ctx = train.get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        assert jax.device_count() == config["global_devices"], (
            "xla backend did not form the global multi-process device cluster"
        )

        # Deterministic dataset; every rank can reconstruct the whole thing.
        n, d = config["rows"], config["feat"]
        rng = np.random.RandomState(0)
        x_all = rng.rand(n, d).astype(np.float32)
        w = rng.rand(d, 1).astype(np.float32)
        y_all = rng.rand(n, 1).astype(np.float32)

        mesh = parallel.make_mesh({"data": -1})  # global: all 8 devices
        sharding = NamedSharding(mesh, P("data"))

        # Each process donates its local rows as per-device shards.
        local_rows = n // world
        x_local = x_all[rank * local_rows : (rank + 1) * local_rows]
        per_dev = np.split(x_local, len(mesh.local_devices))
        x_global = jax.make_array_from_single_device_arrays(
            (n, d),
            sharding,
            [jax.device_put(s, dev) for s, dev in zip(per_dev, mesh.local_devices)],
        )

        @jax.jit
        def loss_fn(x):
            pred = x @ jnp.asarray(w)
            return jnp.mean((pred - jnp.asarray(y_all)) ** 2)

        for step in range(config["steps"]):
            loss = float(jax.device_get(loss_fn(x_global)))
            train.report({"loss": loss, "step": step, "rank": rank})

    return _spmd_train_fn


def test_jax_trainer_xla_backend_spmd_parity(ray_xla_cluster, tmp_path):
    """JaxTrainer with collective_backend='xla': the full runtime path — PG
    gang, worker actors, GCS-KV rendezvous, jax.distributed, one SPMD
    program over the 2-process global mesh — with loss parity against the
    single-process numpy computation."""
    rows, feat, steps = 64, 8, 2
    trainer = JaxTrainer(
        _make_spmd_train_fn(),
        train_loop_config={
            "rows": rows,
            "feat": feat,
            "steps": steps,
            "global_devices": WORLD * DEVICES_PER_PROC,
        },
        backend_config=JaxConfig(collective_backend="xla"),
        scaling_config=ScalingConfig(num_workers=WORLD),
        run_config=RunConfig(name="t_xla_spmd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == steps

    # Parity: the sharded-global-mesh loss must equal plain numpy.
    rng = np.random.RandomState(0)
    x_all = rng.rand(rows, feat).astype(np.float32)
    w = rng.rand(feat, 1).astype(np.float32)
    y_all = rng.rand(rows, 1).astype(np.float32)
    expected = float(np.mean((x_all @ w - y_all) ** 2))
    assert result.metrics["loss"] == pytest.approx(expected, rel=1e-4)
