"""Test fixtures (analog of python/ray/tests/conftest.py).

JAX-facing tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware. The image's TPU plugin self-registers and
overrides JAX_PLATFORMS, so forcing happens via ray_tpu.testing helpers:
XLA_FLAGS before any jax import here, jax.config.update in-process, and a
worker_env for spawned workers.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Persistent XLA compilation cache: jax-heavy tests (models/parallel/train)
# recompile identical programs every run; caching them is worth minutes.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ray_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import signal

# Workers in the test suite never touch the TPU: dropping the axon
# sitecustomize trigger from worker envs skips its ~2s jax import per
# worker-process spawn (the single biggest suite-time cost).
os.environ.setdefault("RAY_TPU_WORKER_ENV_DROP", "PALLAS_AXON_POOL_IPS")
import threading

import pytest


def pytest_addoption(parser):
    parser.addini(
        "timeout",
        "per-test timeout in seconds, enforced by the built-in SIGALRM "
        "watchdog below (pytest-timeout is not available in this image)",
        default="180",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "timeout(seconds): override the per-test watchdog timeout"
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """Watchdog so one wedged test cannot hang the whole suite."""
    timeout = float(item.config.getini("timeout"))
    marker = item.get_closest_marker("timeout")
    if marker and marker.args:
        timeout = float(marker.args[0])
    if timeout <= 0 or threading.current_thread() is not threading.main_thread():
        yield
        return

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded watchdog timeout of {timeout:.0f}s"
        )

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, timeout)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def ray_start_regular():
    """Fresh single-node cluster per test (reference: conftest.py:419)."""
    import ray_tpu

    info = ray_tpu.init(num_cpus=4, num_tpus=0)
    yield info
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cpu_mesh_workers():
    """Cluster whose workers see 8 virtual CPU 'TPU' devices — used by
    train/collective tests to emulate an 8-chip host."""
    import ray_tpu
    from ray_tpu.testing import cpu_mesh_worker_env

    info = ray_tpu.init(
        num_cpus=8, num_tpus=8, worker_env=cpu_mesh_worker_env(8)
    )
    yield info
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    """Test calls init itself; fixture guarantees teardown (conftest.py:336)."""
    import ray_tpu

    yield None
    ray_tpu.shutdown()
