"""Test fixtures (analog of python/ray/tests/conftest.py).

JAX-facing tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware; set before any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
)

import pytest


@pytest.fixture
def ray_start_regular():
    """Fresh single-node cluster per test (reference: conftest.py:419)."""
    import ray_tpu

    info = ray_tpu.init(num_cpus=4, num_tpus=0)
    yield info
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    """Test calls init itself; fixture guarantees teardown (conftest.py:336)."""
    import ray_tpu

    yield None
    ray_tpu.shutdown()
