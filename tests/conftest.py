"""Test fixtures (analog of python/ray/tests/conftest.py).

JAX-facing tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware. The image's TPU plugin self-registers and
overrides JAX_PLATFORMS, so forcing happens via ray_tpu.testing helpers:
XLA_FLAGS before any jax import here, jax.config.update in-process, and a
worker_env for spawned workers.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import pytest


@pytest.fixture
def ray_start_regular():
    """Fresh single-node cluster per test (reference: conftest.py:419)."""
    import ray_tpu

    info = ray_tpu.init(num_cpus=4, num_tpus=0)
    yield info
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cpu_mesh_workers():
    """Cluster whose workers see 8 virtual CPU 'TPU' devices — used by
    train/collective tests to emulate an 8-chip host."""
    import ray_tpu
    from ray_tpu.testing import cpu_mesh_worker_env

    info = ray_tpu.init(
        num_cpus=8, num_tpus=8, worker_env=cpu_mesh_worker_env(8)
    )
    yield info
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    """Test calls init itself; fixture guarantees teardown (conftest.py:336)."""
    import ray_tpu

    yield None
    ray_tpu.shutdown()
