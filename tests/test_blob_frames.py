"""Blob sidecar frames (the zero-copy data plane, rpc.py kinds 4/5):
framing boundaries under adversarial chunking, sink selection and delivery,
interleaving with ordinary control frames, mid-blob connection loss, and
chaos-interceptor atomicity (a blob frame drops/delays/dups as ONE unit
with its data materialized)."""

import asyncio

import msgpack
import pytest

from ray_tpu._private import rpc
from ray_tpu.chaos import interceptors
from ray_tpu.chaos.schedule import FaultSchedule, FaultSpec

_packb = msgpack.Packer(use_bin_type=True, autoreset=True).pack


class _RecordingSink:
    """Sink that records every write and the final done(ok) verdict."""

    def __init__(self):
        self.chunks = []
        self.oks = []

    def write(self, view):
        self.chunks.append(bytes(view))  # views are transient: copy

    def done(self, ok):
        self.oks.append(ok)

    def data(self):
        return b"".join(self.chunks)


async def _start_pair(server_handlers=None, blob_factories=None):
    """A Server with the given handlers/factories plus a dialed client."""
    server = rpc.Server("127.0.0.1", 0)
    for name, fn in (server_handlers or {}).items():
        server.register(name, fn)
    for name, factory in (blob_factories or {}).items():
        server.register_blob(name, factory)
    host, port = await server.start()
    client = await rpc.connect(host, port)
    return server, client


# ------------------------------------------------- byte-level framing


def _feed_sizes(total):
    # Adversarial chunkings: byte-by-byte, tiny, and near-boundary splits.
    yield [1] * total
    yield [7] * (total // 7) + [total % 7]
    yield [total - 1, 1]
    yield [total]


def test_blob_frame_survives_any_chunking():
    """A blob control frame + sidecar + trailing ordinary frame must decode
    identically no matter how the byte stream is sliced: the protocol's
    unpacker-tail recovery and blob-mode switch cannot depend on frames
    arriving whole."""

    async def go():
        blob = bytes(range(256)) * 13  # 3328 bytes, non-repeating-ish
        wire_bytes = (
            _packb([0, 3, "Before", {"seq": 1}])
            + _packb([0, 4, "Blobbed", {"oid": "o1"}, len(blob)])
            + blob
            + _packb([0, 3, "After", {"seq": 2}])
        )
        for sizes in _feed_sizes(len(wire_bytes)):
            got = {"pushes": [], "sink": _RecordingSink()}

            async def push(conn, p, got=got):
                got["pushes"].append(p)

            conn = rpc.Connection(
                {"Before": push, "After": push},
                blob_factories={
                    "Blobbed": lambda c, p, size, got=got: got["sink"]
                },
            )
            pos = 0
            for n in sizes:
                conn._protocol.data_received(wire_bytes[pos : pos + n])
                pos += n
            for _ in range(4):
                await asyncio.sleep(0)  # run the spawned push dispatches
            assert got["sink"].data() == blob, sizes
            assert got["sink"].oks == [True]
            assert [p["seq"] for p in got["pushes"]] == [1, 2], sizes

    asyncio.run(go())


def test_back_to_back_blobs_one_chunk():
    """Two blob frames delivered in a single data_received call: the tail
    recovery after the first blob must hand the second control frame (and
    its sidecar) back through the framing loop."""

    async def go():
        a, b = b"A" * 1000, b"B" * 2000
        sinks = []

        def factory(conn, p, size):
            sinks.append(_RecordingSink())
            return sinks[-1]

        conn = rpc.Connection({}, blob_factories={"Chunk": factory})
        conn._protocol.data_received(
            _packb([0, 4, "Chunk", {"i": 0}, len(a)])
            + a
            + _packb([0, 4, "Chunk", {"i": 1}, len(b)])
            + b
        )
        assert [s.data() for s in sinks] == [a, b]
        assert all(s.oks == [True] for s in sinks)

    asyncio.run(go())


def test_zero_length_blob_completes_inline():
    async def go():
        sink = _RecordingSink()
        conn = rpc.Connection(
            {}, blob_factories={"Empty": lambda c, p, size: sink}
        )
        conn._protocol.data_received(
            _packb([0, 4, "Empty", {"oid": "z"}, 0])
            + _packb([0, 4, "Empty", {"oid": "z2"}, 0])
        )
        assert sink.data() == b"" and sink.oks == [True, True]

    asyncio.run(go())


def test_oversized_blob_length_drops_connection():
    """A corrupt/hostile length field must kill the link, not allocate."""

    async def go():
        closed = []
        conn = rpc.Connection({}, on_close=lambda c: closed.append(True))

        class _T:
            def close(self):
                conn._teardown()

            def get_extra_info(self, *_):
                return None

        conn._protocol.transport = _T()
        conn._protocol.data_received(
            _packb([0, 4, "Huge", {}, rpc._MAX_FRAME + 1])
        )
        assert closed == [True]

    asyncio.run(go())


# ------------------------------------------------- end-to-end over sockets


def test_blob_push_streams_into_factory_sink():
    async def go():
        landed = asyncio.Event()
        sink = _RecordingSink()
        seen = {}

        def factory(conn, payload, size):
            seen["payload"], seen["size"] = payload, size
            return sink

        real_done = sink.done

        def done(ok):
            real_done(ok)
            landed.set()

        sink.done = done
        server, client = await _start_pair(blob_factories={"Push": factory})
        try:
            blob = memoryview(bytearray(b"\xab" * (256 * 1024)))
            client.blob_push_nowait("Push", {"oid": "x", "offset": 0}, blob)
            await asyncio.wait_for(landed.wait(), 5)
            assert seen["payload"] == {"oid": "x", "offset": 0}
            assert seen["size"] == blob.nbytes
            assert sink.data() == bytes(blob)
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_call_with_blob_default_sink_injects_data():
    """No factory registered: the blob lands in a BufferSink and reaches the
    ordinary handler as payload['data']; the reply round-trips."""

    async def go():
        async def put(conn, p):
            return {"n": len(p["data"]), "meta": p["meta"]}

        server, client = await _start_pair(server_handlers={"CPut": put})
        try:
            blob = b"z" * 123_457
            reply = await asyncio.wait_for(
                client.call_with_blob("CPut", {"meta": 7}, blob), 5
            )
            assert reply == {"n": len(blob), "meta": 7}
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_call_into_span_sink_receives_blob_reply():
    async def go():
        payload = bytes(range(256)) * 4096  # 1 MiB

        async def fetch(conn, p):
            lo, hi = p["lo"], p["hi"]
            return rpc.Blob({"size": hi - lo}, memoryview(payload)[lo:hi])

        server, client = await _start_pair(server_handlers={"Fetch": fetch})
        try:
            dest = memoryview(bytearray(len(payload)))
            sink = rpc.SpanSink(dest, pos=4096)
            meta = await asyncio.wait_for(
                client.call_into(
                    "Fetch", {"lo": 0, "hi": 65536}, sink, timeout=5
                ),
                10,
            )
            assert meta == {"size": 65536}
            assert sink.written == 65536
            assert bytes(dest[4096 : 4096 + 65536]) == payload[:65536]
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_multi_buffer_blob_concatenates():
    async def go():
        async def put(conn, p):
            return {"data": bytes(p["data"])}

        server, client = await _start_pair(server_handlers={"Put": put})
        try:
            parts = [b"a" * 10, memoryview(b"b" * 20), bytearray(b"c" * 30)]
            reply = await asyncio.wait_for(
                client.call_with_blob("Put", {}, parts), 5
            )
            assert reply["data"] == b"a" * 10 + b"b" * 20 + b"c" * 30
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_declined_factory_drains_and_stream_stays_framed():
    """A factory returning None discards the blob; the very next request on
    the same connection must still parse (the stream stayed framed)."""

    async def go():
        async def ping(conn, p):
            return {"pong": True}

        server, client = await _start_pair(
            server_handlers={"Ping": ping},
            blob_factories={"Unwanted": lambda c, p, size: None},
        )
        try:
            client.blob_push_nowait("Unwanted", {}, b"x" * 50_000)
            reply = await asyncio.wait_for(client.call("Ping", {}), 5)
            assert reply == {"pong": True}
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_blob_interleaves_with_pipelined_calls():
    """Blob frames and ordinary request/reply traffic share one connection;
    ordering per direction is preserved and nothing corrupts."""

    async def go():
        order = []

        async def mark(conn, p):
            order.append(("call", p["i"]))
            return p["i"]

        async def putb(conn, p):
            order.append(("blob", len(p["data"])))
            return len(p["data"])

        server, client = await _start_pair(
            server_handlers={"Mark": mark, "PutB": putb}
        )
        try:
            results = await asyncio.wait_for(
                asyncio.gather(
                    client.call("Mark", {"i": 0}),
                    client.call_with_blob("PutB", {}, b"q" * 70_000),
                    client.call("Mark", {"i": 1}),
                    client.call_with_blob("PutB", {}, b"r" * 10),
                    client.call("Mark", {"i": 2}),
                ),
                10,
            )
            assert results == [0, 70_000, 1, 10, 2]
            assert order == [
                ("call", 0),
                ("blob", 70_000),
                ("call", 1),
                ("blob", 10),
                ("call", 2),
            ]
        finally:
            await client.close()
            await server.stop()

    asyncio.run(go())


def test_connection_loss_mid_blob_fails_sink():
    """Teardown with a half-received blob: the sink must see done(False) so
    an arena span being filled can be aborted/quarantined."""

    async def go():
        sink = _RecordingSink()
        started = asyncio.Event()
        real_write = sink.write

        def write(view):
            real_write(view)
            started.set()

        sink.write = write
        server, client = await _start_pair(
            blob_factories={"Part": lambda c, p, size: sink}
        )
        try:
            total = 64 * 1024 * 1024  # far more than one socket buffer
            # Half-frame by hand: control message promises `total` bytes but
            # the client only ever writes a fragment, then dies.
            client._protocol.transport.write(
                _packb([0, 4, "Part", {"oid": "p"}, total]) + b"x" * 4096
            )
            await asyncio.wait_for(started.wait(), 5)
            await client.close()
            for _ in range(100):
                if sink.oks:
                    break
                await asyncio.sleep(0.02)
            assert sink.oks == [False]
            assert len(sink.data()) < total
        finally:
            await server.stop()

    asyncio.run(go())


def test_call_with_blob_fails_fast_on_connection_loss():
    async def go():
        server, client = await _start_pair()
        try:
            fut = asyncio.ensure_future(
                client.call_with_blob("Never", {}, b"x" * 1024)
            )
            await asyncio.sleep(0)
            await client.close()
            with pytest.raises(rpc.RpcError):
                await asyncio.wait_for(fut, 5)
        finally:
            await server.stop()

    asyncio.run(go())


# ------------------------------------------------- chaos x blob atomicity


def _install(spec, seed=0):
    return interceptors.install(FaultSchedule(seed, [spec]))


def test_chaos_sees_blob_frame_as_one_materialized_unit():
    """The interceptor must be offered [msgid, kind, method, payload, BYTES]
    — a stable copy, not a live arena view — so drop/delay/dup act on the
    whole frame (control + data) atomically."""

    async def go():
        offered = []

        def interceptor(conn, msg):
            offered.append(msg)
            return True  # drop

        rpc.set_send_interceptor(interceptor)
        try:
            server, client = await _start_pair()
            try:
                arena = bytearray(b"\x11" * 2048)
                client.blob_push_nowait(
                    "PushChunk", {"oid": "o"}, memoryview(arena)
                )
                (msg,) = offered
                assert msg[1] == rpc._KIND_BLOB
                assert isinstance(msg[4], bytes)  # materialized, not a view
                arena[:] = b"\x99" * 2048  # arena reuse must not corrupt it
                assert msg[4] == b"\x11" * 2048
            finally:
                await client.close()
                await server.stop()
        finally:
            rpc.set_send_interceptor(None)

    asyncio.run(go())


def test_chaos_dropped_then_redelivered_blob_arrives_intact():
    """Drop a blob push via the chaos interceptor, then redeliver the
    captured frame with _send_direct (the delay/dup delivery path): the
    receiver must get the full blob exactly once."""

    async def go():
        held = []

        def interceptor(conn, msg):
            if msg[1] == rpc._KIND_BLOB:
                held.append((conn, msg))
                return True
            return False

        landed = asyncio.Event()
        sink = _RecordingSink()
        real_done = sink.done

        def done(ok):
            real_done(ok)
            landed.set()

        sink.done = done
        rpc.set_send_interceptor(interceptor)
        try:
            server, client = await _start_pair(
                blob_factories={"PushChunk": lambda c, p, size: sink}
            )
            try:
                blob = b"\x42" * 100_000
                client.blob_push_nowait("PushChunk", {"oid": "o"}, blob)
                assert not sink.chunks  # consumed by the fault
                (conn, msg), = held
                conn._send_direct(msg)  # the delayed delivery half
                await asyncio.wait_for(landed.wait(), 5)
                assert sink.data() == blob and sink.oks == [True]
            finally:
                await client.close()
                await server.stop()
        finally:
            rpc.set_send_interceptor(None)

    asyncio.run(go())


def test_chaos_interceptor_classifies_blob_frames():
    """Frame-class matching for the new kinds: a kind-4 with msgid 0 is a
    push, with a msgid it is a request; kind-5 is a reply."""
    chaos = interceptors.ChaosInterceptor(
        FaultSchedule(
            0, [FaultSpec("d", "drop", "PushChunk", frame="push", p=1.0)]
        )
    )

    class _C:
        sent = []

        def _send_direct(self, m):
            self.sent.append(m)

    # push-classed blob: dropped.
    assert chaos(_C(), [0, rpc._KIND_BLOB, "PushChunk", {}, b"x"]) is True
    # request-classed blob (msgid != 0): not a "push", flows.
    assert chaos(_C(), [9, rpc._KIND_BLOB, "PushChunk", {}, b"x"]) is False
    # blob replies class as replies.
    rep = interceptors.ChaosInterceptor(
        FaultSchedule(
            0, [FaultSpec("d", "drop", "FetchChunk", frame="reply", p=1.0)]
        )
    )
    assert rep(_C(), [3, rpc._KIND_BLOB_REP, "FetchChunk", {}, b"x"]) is True


def test_chaos_dup_of_blob_push_is_idempotent_for_arena_sink():
    """Duplicate a PushChunk blob: both copies carry the same offset, so an
    arena sink just writes the same bytes twice — content converges."""

    async def go():
        arena = bytearray(8192)
        dones = []

        class _ArenaSink:
            def __init__(self, off):
                self.off = off

            def write(self, view):
                n = view.nbytes
                arena[self.off : self.off + n] = view
                self.off += n

            def done(self, ok):
                dones.append(ok)

        chaos = _install(
            FaultSpec("2x", "dup", "PushChunk", frame="push", p=1.0)
        )
        try:
            server, client = await _start_pair(
                blob_factories={
                    "PushChunk": lambda c, p, size: _ArenaSink(p["offset"])
                }
            )
            try:
                blob = bytes(range(256)) * 16  # 4096 bytes
                client.blob_push_nowait(
                    "PushChunk", {"oid": "o", "offset": 512}, blob
                )
                for _ in range(100):
                    if len(dones) >= 2:
                        break
                    await asyncio.sleep(0.02)
                assert dones == [True, True]  # original + duplicate
                assert bytes(arena[512 : 512 + len(blob)]) == blob
                assert chaos.log.count("2x") == 1
            finally:
                await client.close()
                await server.stop()
        finally:
            interceptors.uninstall()

    asyncio.run(go())
