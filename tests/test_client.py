"""Remote-driver (Ray Client analog) tests.

Reference: python/ray/util/client + ray_client.proto:326. The proxy session
owns all objects; the client holds opaque handles and moves only serialized
payloads."""

import subprocess
import sys

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod
from ray_tpu.util.client import ClientContext
from ray_tpu.util.client.server import ClientServer


@pytest.fixture
def client_setup():
    """Cluster + proxy in this process; a ClientContext on its own loop."""
    info = ray_tpu.init(num_cpus=4, num_tpus=0)
    w = worker_mod.global_worker
    gcs_addr = w.node.gcs_addr

    async def _start():
        srv = ClientServer(gcs_addr, host="127.0.0.1")
        await srv.start()
        return srv

    srv = w.run_async(_start(), timeout=30)
    ctx = ClientContext("127.0.0.1", srv.addr[1])
    yield ctx, srv
    ctx.disconnect()

    async def _stop():
        await srv.stop()

    w.run_async(_stop(), timeout=30)
    ray_tpu.shutdown()


def test_client_put_get_roundtrip(client_setup):
    ctx, _ = client_setup
    ref = ctx.put({"a": 1, "b": [1, 2, 3]})
    assert ctx.get(ref) == {"a": 1, "b": [1, 2, 3]}
    big = np.arange(1 << 20, dtype=np.float32)  # 4 MB -> plasma path
    bref = ctx.put(big)
    out = ctx.get(bref)
    assert out.shape == big.shape and out[-1] == big[-1]


def test_client_task_submission(client_setup):
    ctx, _ = client_setup

    @ray_tpu.remote
    def add(a, b):
        return a + b

    refs = ctx.submit_remote_function(add, (2, 3), {})
    assert ctx.get(refs[0]) == 5
    # Ref args: a client ref passed as a task arg resolves cluster-side.
    xref = ctx.put(10)
    refs2 = ctx.submit_remote_function(add, (xref, 5), {})
    assert ctx.get(refs2[0]) == 15


def test_client_task_error_propagates(client_setup):
    ctx, _ = client_setup

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaboom")

    refs = ctx.submit_remote_function(boom, (), {})
    with pytest.raises(Exception, match="kaboom"):
        ctx.get(refs[0], timeout=60)


def test_client_wait(client_setup):
    ctx, _ = client_setup

    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        import time

        time.sleep(30)
        return 2

    r1 = ctx.submit_remote_function(fast, (), {})[0]
    r2 = ctx.submit_remote_function(slow, (), {})[0]
    ready, not_ready = ctx.wait([r1, r2], num_returns=1, timeout=30)
    assert [r.hex() for r in ready] == [r1.hex()]
    assert [r.hex() for r in not_ready] == [r2.hex()]


def test_client_actor_lifecycle(client_setup):
    ctx, _ = client_setup

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    handle = ctx.create_actor(Counter, (100,), {})
    r1 = ctx.call_actor_method(handle._actor_id, "inc", (), {})[0]
    assert ctx.get(r1, timeout=60) == 101
    r2 = ctx.call_actor_method(handle._actor_id, "inc", (5,), {})[0]
    assert ctx.get(r2, timeout=60) == 106
    ctx.kill(handle._actor_id)


def test_client_mode_via_public_api():
    """Full path: a subprocess driver uses ray_tpu.init("ray-tpu://...") and
    the plain public API (remote/put/get/actors) end to end."""
    info = ray_tpu.init(num_cpus=4, num_tpus=0)
    w = worker_mod.global_worker
    gcs_addr = w.node.gcs_addr

    async def _start():
        srv = ClientServer(gcs_addr, host="127.0.0.1")
        await srv.start()
        return srv

    srv = w.run_async(_start(), timeout=30)
    port = srv.addr[1]
    script = f"""
import ray_tpu
ray_tpu.init(address="ray-tpu://127.0.0.1:{port}")

@ray_tpu.remote
def sq(x):
    return x * x

assert ray_tpu.get(sq.remote(7)) == 49
ref = ray_tpu.put(21)
assert ray_tpu.get(sq.remote(ref)) == 441

@ray_tpu.remote
class Acc:
    def __init__(self):
        self.total = 0
    def add(self, v):
        self.total += v
        return self.total

a = Acc.remote()
assert ray_tpu.get(a.add.remote(3)) == 3
assert ray_tpu.get(a.add.remote(4)) == 7
ready, pending = ray_tpu.wait([sq.remote(2)], num_returns=1, timeout=30)
assert len(ready) == 1 and not pending
assert any(n["state"] == "ALIVE" for n in ray_tpu.nodes())
ray_tpu.shutdown()
print("CLIENT_OK")
"""
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert "CLIENT_OK" in out.stdout, f"stdout={out.stdout}\nstderr={out.stderr}"
    finally:
        async def _stop():
            await srv.stop()

        w.run_async(_stop(), timeout=30)
        ray_tpu.shutdown()
