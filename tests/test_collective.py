"""Collective group tests over actors (models reference
python/ray/util/collective tests) using the store backend."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray4(shutdown_only):
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield


def _worker_cls():
    @ray_tpu.remote(num_cpus=1)
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend="store", group_name="g")
            self.rank = rank
            self.world = world

        def do_allreduce(self, value):
            from ray_tpu.util import collective as col

            return col.allreduce(np.full((4,), value, dtype=np.float32), group_name="g")

        def do_allgather(self):
            from ray_tpu.util import collective as col

            return col.allgather(np.full((2,), self.rank, dtype=np.int64), group_name="g")

        def do_broadcast(self):
            from ray_tpu.util import collective as col

            val = np.arange(3) if self.rank == 0 else np.zeros(3, dtype=np.int64)
            return col.broadcast(val, src_rank=0, group_name="g")

        def do_reducescatter(self):
            from ray_tpu.util import collective as col

            return col.reducescatter(
                np.arange(8, dtype=np.float32), group_name="g"
            )

        def do_sendrecv(self):
            from ray_tpu.util import collective as col

            if self.rank == 0:
                col.send(np.array([42.0]), dst_rank=1, group_name="g")
                return None
            return col.recv(src_rank=0, group_name="g")

        def rank_info(self):
            from ray_tpu.util import collective as col

            return col.get_rank("g"), col.get_collective_group_size("g")

    return Rank


def test_allreduce(ray4):
    Rank = _worker_cls()
    actors = [Rank.remote(i, 4) for i in range(4)]
    outs = ray_tpu.get([a.do_allreduce.remote(float(i)) for i, a in enumerate(actors)], timeout=120)
    expect = np.full((4,), 0.0 + 1 + 2 + 3, dtype=np.float32)
    for out in outs:
        np.testing.assert_array_equal(out, expect)


def test_allgather_and_rank(ray4):
    Rank = _worker_cls()
    actors = [Rank.remote(i, 2) for i in range(2)]
    outs = ray_tpu.get([a.do_allgather.remote() for a in actors], timeout=120)
    for out in outs:
        assert [int(x[0]) for x in out] == [0, 1]
    infos = ray_tpu.get([a.rank_info.remote() for a in actors], timeout=60)
    assert infos == [(0, 2), (1, 2)]


def test_broadcast(ray4):
    Rank = _worker_cls()
    actors = [Rank.remote(i, 3) for i in range(3)]
    outs = ray_tpu.get([a.do_broadcast.remote() for a in actors], timeout=120)
    for out in outs:
        np.testing.assert_array_equal(out, np.arange(3))


def test_reducescatter(ray4):
    Rank = _worker_cls()
    actors = [Rank.remote(i, 2) for i in range(2)]
    outs = ray_tpu.get([a.do_reducescatter.remote() for a in actors], timeout=120)
    np.testing.assert_array_equal(outs[0], np.arange(4, dtype=np.float32) * 2)
    np.testing.assert_array_equal(outs[1], np.arange(4, 8, dtype=np.float32) * 2)


def test_send_recv(ray4):
    Rank = _worker_cls()
    actors = [Rank.remote(i, 2) for i in range(2)]
    outs = ray_tpu.get([a.do_sendrecv.remote() for a in actors], timeout=120)
    assert outs[0] is None
    np.testing.assert_array_equal(outs[1], np.array([42.0]))
