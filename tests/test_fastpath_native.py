"""Native direct-call channel protocol tests (src/fastpath.cc) — the C++
unit-test tier for the fastpath extension (reference: the per-component
*_test.cc files under src/ray/**; here driven through the Python binding,
and runnable under RAY_TPU_SANITIZE=address builds, see setup.py)."""

import os
import select
import sys
import time

import pytest

fp = pytest.importorskip("ray_tpu._native._fastpath")


def _drain_until(n, timeout=10.0):
    out = []
    deadline = time.time() + timeout
    nfd = fp.notify_fd()
    while len(out) < n and time.time() < deadline:
        select.select([nfd], [], [], 0.5)
        out.extend(fp.drain())
    return out


@pytest.fixture
def server():
    calls = []

    def cb(tid, fid, name, blob):
        calls.append((tid, fid, name, blob))
        if fid == b"boom":
            return (1, b"ERRPAYLOAD")
        if fid == b"nofn":
            return (4, b"")
        if fid == b"big":
            return (6, b"PLASMA_DESC")
        return (0, b"R:" + blob)

    sid, port = fp.serve("127.0.0.1", 0, cb)
    yield port, calls
    fp.stop_server(sid)


def test_round_trip_statuses(server):
    """Every reply status survives the 10+status wire encoding."""
    port, calls = server
    ch = fp.client_connect("127.0.0.1", port)
    assert ch > 0
    fp.submit(ch, b"t-ok", b"f1", b"n", b"payload")
    fp.submit(ch, b"t-err", b"boom", b"n", b"x")
    fp.submit(ch, b"t-nofn", b"nofn", b"n", b"x")
    fp.submit(ch, b"t-big", b"big", b"n", b"x")
    got = {tid: (status, payload) for tid, status, payload in _drain_until(4)}
    assert got[b"t-ok"] == (0, b"R:payload")
    assert got[b"t-err"] == (1, b"ERRPAYLOAD")
    assert got[b"t-nofn"] == (4, b"")
    assert got[b"t-big"] == (6, b"PLASMA_DESC")
    assert [c[0] for c in calls] == [b"t-ok", b"t-err", b"t-nofn", b"t-big"]
    fp.client_close(ch)


def test_large_args_round_trip(server):
    """Multi-megabyte args cross the frame reader's 64KB recv buffer."""
    port, _calls = server
    ch = fp.client_connect("127.0.0.1", port)
    blob = os.urandom(3 * 1024 * 1024)
    fp.submit(ch, b"t-large", b"f", b"n", blob)
    ((tid, status, payload),) = _drain_until(1, timeout=30)
    assert tid == b"t-large" and status == 0
    assert payload == b"R:" + blob
    fp.client_close(ch)


def test_channel_loss_no_tid_vanishes(server):
    """The driver-side invariant its retry machinery depends on: every
    submitted tid produces EXACTLY ONE completion — finished work arrives
    as status 0/1, anything cut off by the connection dropping arrives as
    status 2 (lost). Nothing is silently dropped."""
    def slow_cb(tid, fid, name, blob):
        time.sleep(1.0)
        return (0, b"late")

    sid, port = fp.serve("127.0.0.1", 0, slow_cb)
    ch = fp.client_connect("127.0.0.1", port)
    fp.submit(ch, b"t-cut-1", b"f", b"n", b"x")
    fp.submit(ch, b"t-cut-2", b"f", b"n", b"x")
    time.sleep(0.2)
    fp.stop_server(sid)  # server torn down with work in flight
    got = _drain_until(2, timeout=15)
    assert sorted(tid for tid, _s, _p in got) == [b"t-cut-1", b"t-cut-2"]
    assert all(status in (0, 2) for _t, status, _p in got), got
    fp.client_close(ch)


def test_submit_to_closed_channel_returns_false(server):
    port, _calls = server
    ch = fp.client_connect("127.0.0.1", port)
    fp.client_close(ch)
    assert fp.submit(ch, b"t", b"f", b"n", b"x") is False


def test_connect_failure_returns_negative():
    assert fp.client_connect("127.0.0.1", 1) < 0


# ---------------------------------------------------- wire codec parity

msgpack = pytest.importorskip("msgpack")

_PACKB = msgpack.Packer(use_bin_type=True, autoreset=True).pack
_FUZZ_ROUNDS = 500


def _lease_id(rng):
    return "".join(rng.choices("0123456789abcdef", k=24))


def _resources(rng):
    names = ["CPU", "TPU", "memory", "node:10.0.0.%d" % rng.randrange(256),
             "custom/res-%d" % rng.randrange(8)]
    return {
        rng.choice(names): rng.choice([1, 0.5, 4.0, rng.random() * 64])
        for _ in range(rng.randrange(0, 4))
    }


def _payload_for(method, rng):
    """Schema-shaped randomized payloads (field lists mirror
    wire.NATIVE_WIRE_SCHEMAS; the drift lint keeps the two in sync)."""
    if method == "RequestWorkerLease":
        return {
            "lease_id": _lease_id(rng),
            "resources": _resources(rng),
            "pg_id": rng.choice([None, _lease_id(rng)]),
            "bundle_index": rng.choice([-1, 0, rng.randrange(64)]),
            "strategy": rng.choice(
                [None, {"spread": True}, {"node_affinity": {"node_id": _lease_id(rng), "soft": rng.random() < 0.5}}]
            ),
            "spilled_from": rng.random() < 0.3,
            "locality": rng.choice(
                [None, {"10.0.0.%d:%d" % (rng.randrange(256), rng.randrange(1024, 65536)): rng.random() * 8}]
            ),
            "job_id": rng.choice([None, "job-%04d" % rng.randrange(10000)]),
        }
    if method == "ReturnWorker":
        return {"lease_id": _lease_id(rng), "dirty": rng.random() < 0.5}
    if method == "CancelWorkerLease":
        return {"lease_id": _lease_id(rng)}
    if method == "LeaseBatch":
        inner = ["RequestWorkerLease", "ReturnWorker", "CancelWorkerLease"]
        return {
            "entries": [
                [
                    rng.randrange(1, 1 << 30),
                    m,
                    _payload_for(m, rng),
                    rng.choice([None, rng.random() * 30]),
                    rng.choice([None, [_lease_id(rng), _lease_id(rng)[:16]]]),
                ]
                for m in (rng.choice(inner) for _ in range(rng.randrange(1, 9)))
            ]
        }
    if method == "PubBatch":
        return {
            "items": [
                [
                    rng.choice(["NODE", "ACTOR", "WORKER", "health"]),
                    rng.choice(
                        [
                            {"node_id": _lease_id(rng), "state": rng.choice(["ALIVE", "DEAD"])},
                            {"actor_id": _lease_id(rng), "addr": ["10.0.0.1", rng.randrange(65536)]},
                            b"\x00binary blob\xff" * rng.randrange(1, 4),
                        ]
                    ),
                    rng.randrange(1, 1 << 40),
                ]
                for _ in range(rng.randrange(1, 9))
            ]
        }
    raise AssertionError(method)


def _frame_variants(method, payload, rng):
    """The frame shapes rpc._pack_frame actually emits: bare request, with
    TTL slot, and with TTL + trace-context slot (PR 4 / PR 13 survive
    byte-for-byte)."""
    msgid = rng.randrange(1, 1 << 31)
    kind = 3 if method in ("LeaseBatch", "PubBatch") else 0
    yield [msgid, kind, method, payload]
    yield [msgid, kind, method, payload, rng.random() * 30]
    yield [msgid, kind, method, payload, rng.random() * 30,
           [_lease_id(rng), _lease_id(rng)[:16]]]


def _norm(v):
    if isinstance(v, (list, tuple)):
        return [_norm(x) for x in v]
    if isinstance(v, dict):
        return {k: _norm(val) for k, val in v.items()}
    return v


@pytest.mark.parametrize(
    "method",
    ["RequestWorkerLease", "ReturnWorker", "CancelWorkerLease", "LeaseBatch", "PubBatch"],
)
def test_native_pack_parity_fuzz(method):
    """Per native schema: randomized frames pack byte-identically to the
    Python packer, Python-unpack the native bytes losslessly, and
    native-unpack the Python bytes losslessly — both directions of the
    fallback boundary are interchangeable on the wire."""
    import random

    if not hasattr(fp, "pack_frame"):
        pytest.skip("extension built without the wire codec")
    rng = random.Random(hash(method) & 0xFFFF)
    for _ in range(_FUZZ_ROUNDS):
        payload = _payload_for(method, rng)
        for frame in _frame_variants(method, payload, rng):
            native = fp.pack_frame(frame)
            pure = _PACKB(frame)
            assert native == pure, f"{method}: byte divergence"
            # native-pack -> Python-unpack
            back = msgpack.unpackb(native, raw=False, strict_map_key=False)
            assert back == _norm(frame)
            # Python-pack -> native-unpack
            dec = fp.Decoder()
            dec.feed(pure)
            got = list(dec)
            assert got == [_norm(frame)]
            assert dec.tell() == len(pure)


def test_native_decoder_incremental_and_tell():
    """Chunked feeds: frames split at arbitrary byte boundaries decode
    exactly once each, and tell() counts total consumed bytes (the blob-
    mode switch in rpc.data_received depends on that)."""
    import random

    if not hasattr(fp, "pack_frame"):
        pytest.skip("extension built without the wire codec")
    rng = random.Random(99)
    frames = []
    for _ in range(200):
        m = rng.choice(["RequestWorkerLease", "ReturnWorker", "LeaseBatch"])
        frames.append(next(_frame_variants(m, _payload_for(m, rng), rng)))
    stream = b"".join(_PACKB(f) for f in frames)
    dec = fp.Decoder()
    got = []
    i = 0
    while i < len(stream):
        n = rng.randrange(1, 4096)
        dec.feed(stream[i : i + n])
        i += n
        got.extend(dec)
    assert got == [_norm(f) for f in frames]
    assert dec.tell() == len(stream)


def test_native_decoder_rejects_malformed_bytes():
    """Ext/reserved leaders are not part of the wire protocol: the decoder
    must raise (the rpc layer drops the connection) instead of guessing."""
    if not hasattr(fp, "pack_frame"):
        pytest.skip("extension built without the wire codec")
    for bad in (b"\xc1", b"\xc7\x01\x05x", b"\xd4\x05x", b"\xc8\x00\x01\x05x"):
        dec = fp.Decoder()
        dec.feed(bad)
        with pytest.raises(Exception):
            list(dec)


def test_packed_payload_grant_reply_byte_identity():
    """The pre-packed grant skeleton (raylet._grant_reply) must splice into
    frames byte-identically to packing the equivalent plain-dict reply —
    the wire format is unchanged, only who pays the encode."""
    import asyncio

    from ray_tpu._private import rpc as _rpc

    mapping = {
        "granted": True,
        "worker_id": "w-00042",
        "worker_addr": ["10.0.0.7", 45123],
        "lease_id": "a1b2c3d4e5f60718293a4b5c",
        "fp_port": 7011,
    }
    packed = _rpc.PackedPayload(mapping, _rpc._packb(mapping))

    async def go():
        server = _rpc.Server("127.0.0.1", 0)
        addr = await server.start()
        conn = await _rpc.connect(*addr)
        try:
            flats = []
            for frame_payload in (mapping, packed):
                bufs = conn._pack_frame([771, 1, "RequestWorkerLease", frame_payload])
                flats.append(b"".join(bytes(b) for b in bufs))
        finally:
            await conn.close()
            await server.stop()
        return flats

    plain, spliced = asyncio.run(go())
    assert spliced == plain
    assert msgpack.unpackb(plain, raw=False, strict_map_key=False) == [
        771, 1, "RequestWorkerLease", mapping,
    ]


def test_python_fallback_when_native_masked():
    """With the compiled module masked (import error) the rpc layer must
    boot on the pure-Python packer and complete a lease-shaped round trip;
    native is an accelerator, never a dependency."""
    import subprocess

    code = r"""
import asyncio, sys

class _Mask:
    def find_module(self, name, path=None):
        if name == "ray_tpu._native._fastpath":
            return self
    def load_module(self, name):
        raise ImportError("masked for fallback test")

sys.meta_path.insert(0, _Mask())

from ray_tpu._private import rpc

assert rpc._NATIVE_WIRE is None, "mask failed"
assert not rpc.native_wire_active()

async def go():
    server = rpc.Server("127.0.0.1", 0)

    async def lease(conn, p):
        return {"granted": True, "lease_id": p["lease_id"]}

    server.register("RequestWorkerLease", lease)
    addr = await server.start()
    conn = await rpc.connect(*addr)
    try:
        replies = await asyncio.gather(
            *(conn.call_batched("RequestWorkerLease", {"lease_id": "L%d" % i})
              for i in range(8))
        )
        assert [r["lease_id"] for r in replies] == ["L%d" % i for i in range(8)]
    finally:
        await conn.close()
        await server.stop()

asyncio.run(go())
print("FALLBACK_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert "FALLBACK_OK" in out.stdout


def test_env_gate_disables_native_wire():
    """RAY_TPU_NATIVE_WIRE=0 must force the pure-Python path even with the
    extension importable."""
    import subprocess

    code = (
        "from ray_tpu._private import rpc; "
        "assert rpc._NATIVE_WIRE is None; "
        "assert not rpc.native_wire_active(); "
        "print('GATE_OK')"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "RAY_TPU_NATIVE_WIRE": "0", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert "GATE_OK" in out.stdout
