"""Native direct-call channel protocol tests (src/fastpath.cc) — the C++
unit-test tier for the fastpath extension (reference: the per-component
*_test.cc files under src/ray/**; here driven through the Python binding,
and runnable under RAY_TPU_SANITIZE=address builds, see setup.py)."""

import os
import select
import sys
import time

import pytest

fp = pytest.importorskip("ray_tpu._native._fastpath")


def _drain_until(n, timeout=10.0):
    out = []
    deadline = time.time() + timeout
    nfd = fp.notify_fd()
    while len(out) < n and time.time() < deadline:
        select.select([nfd], [], [], 0.5)
        out.extend(fp.drain())
    return out


@pytest.fixture
def server():
    calls = []

    def cb(tid, fid, name, blob):
        calls.append((tid, fid, name, blob))
        if fid == b"boom":
            return (1, b"ERRPAYLOAD")
        if fid == b"nofn":
            return (4, b"")
        if fid == b"big":
            return (6, b"PLASMA_DESC")
        return (0, b"R:" + blob)

    sid, port = fp.serve("127.0.0.1", 0, cb)
    yield port, calls
    fp.stop_server(sid)


def test_round_trip_statuses(server):
    """Every reply status survives the 10+status wire encoding."""
    port, calls = server
    ch = fp.client_connect("127.0.0.1", port)
    assert ch > 0
    fp.submit(ch, b"t-ok", b"f1", b"n", b"payload")
    fp.submit(ch, b"t-err", b"boom", b"n", b"x")
    fp.submit(ch, b"t-nofn", b"nofn", b"n", b"x")
    fp.submit(ch, b"t-big", b"big", b"n", b"x")
    got = {tid: (status, payload) for tid, status, payload in _drain_until(4)}
    assert got[b"t-ok"] == (0, b"R:payload")
    assert got[b"t-err"] == (1, b"ERRPAYLOAD")
    assert got[b"t-nofn"] == (4, b"")
    assert got[b"t-big"] == (6, b"PLASMA_DESC")
    assert [c[0] for c in calls] == [b"t-ok", b"t-err", b"t-nofn", b"t-big"]
    fp.client_close(ch)


def test_large_args_round_trip(server):
    """Multi-megabyte args cross the frame reader's 64KB recv buffer."""
    port, _calls = server
    ch = fp.client_connect("127.0.0.1", port)
    blob = os.urandom(3 * 1024 * 1024)
    fp.submit(ch, b"t-large", b"f", b"n", blob)
    ((tid, status, payload),) = _drain_until(1, timeout=30)
    assert tid == b"t-large" and status == 0
    assert payload == b"R:" + blob
    fp.client_close(ch)


def test_channel_loss_no_tid_vanishes(server):
    """The driver-side invariant its retry machinery depends on: every
    submitted tid produces EXACTLY ONE completion — finished work arrives
    as status 0/1, anything cut off by the connection dropping arrives as
    status 2 (lost). Nothing is silently dropped."""
    def slow_cb(tid, fid, name, blob):
        time.sleep(1.0)
        return (0, b"late")

    sid, port = fp.serve("127.0.0.1", 0, slow_cb)
    ch = fp.client_connect("127.0.0.1", port)
    fp.submit(ch, b"t-cut-1", b"f", b"n", b"x")
    fp.submit(ch, b"t-cut-2", b"f", b"n", b"x")
    time.sleep(0.2)
    fp.stop_server(sid)  # server torn down with work in flight
    got = _drain_until(2, timeout=15)
    assert sorted(tid for tid, _s, _p in got) == [b"t-cut-1", b"t-cut-2"]
    assert all(status in (0, 2) for _t, status, _p in got), got
    fp.client_close(ch)


def test_submit_to_closed_channel_returns_false(server):
    port, _calls = server
    ch = fp.client_connect("127.0.0.1", port)
    fp.client_close(ch)
    assert fp.submit(ch, b"t", b"f", b"n", b"x") is False


def test_connect_failure_returns_negative():
    assert fp.client_connect("127.0.0.1", 1) < 0
