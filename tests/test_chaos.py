"""ray_tpu.chaos: schedule determinism, interceptor fault units, the
double-grant lease guard, pull stall recovery, and a fixed-seed convergence
smoke (reference fault-injection shape: Jepsen nemeses + deterministic
schedule replay)."""

import asyncio

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private import worker as worker_mod
from ray_tpu.chaos import interceptors, invariants
from ray_tpu.chaos.runner import SCENARIOS, SUITES, run_scenario
from ray_tpu.chaos.schedule import (
    FaultLog,
    FaultSchedule,
    FaultSpec,
    NemesisPlan,
    stable_u64,
)


# ---------------------------------------------------------------- schedules


def test_stable_u64_is_process_stable():
    # sha256-derived, not the salted builtin hash: value is a constant.
    assert stable_u64("42:lose-chunks") == stable_u64("42:lose-chunks")
    assert stable_u64("a") != stable_u64("b")


def test_schedule_same_seed_byte_identical():
    specs = [
        FaultSpec("d", "drop", "PushChunk", frame="push", p=0.3),
        FaultSpec("l", "delay", "Request*", p=0.5, delay_s=(0.001, 0.2)),
    ]
    a = FaultSchedule(123, specs)
    b = FaultSchedule(123, specs)
    assert a.to_bytes() == b.to_bytes()
    assert a.digest() == b.digest()


def test_schedule_different_seed_differs():
    specs = [FaultSpec("d", "drop", "PushChunk", frame="push", p=0.3)]
    assert FaultSchedule(1, specs).to_bytes() != FaultSchedule(2, specs).to_bytes()


def test_schedule_respects_start_after_and_max_fires():
    spec = FaultSpec("d", "drop", "*", p=1.0, start_after=3, max_fires=2)
    plan = FaultSchedule(7, [spec]).decisions["d"]
    assert plan[:3] == [None, None, None]
    assert [d for d in plan if d is not None] == [("drop",), ("drop",)]


def test_schedule_rejects_bad_specs():
    with pytest.raises(ValueError):
        FaultSpec("x", "explode", "*")
    with pytest.raises(ValueError):
        FaultSpec("x", "drop", "*", frame="sideways")
    with pytest.raises(ValueError):
        FaultSchedule(1, [FaultSpec("x", "drop", "*"), FaultSpec("x", "dup", "*")])


def test_scenario_catalog_schedules_deterministic():
    # The exact property the CI gate replays: every cataloged scenario's
    # schedule and nemesis plan is a pure function of the seed.
    for scenario in SCENARIOS.values():
        for seed in (0, 1, 99):
            assert (
                FaultSchedule(seed, scenario.specs).to_bytes()
                == FaultSchedule(seed, scenario.specs).to_bytes()
            )
            assert (
                NemesisPlan(seed, scenario.nemesis, scenario.steps).to_wire()
                == NemesisPlan(seed, scenario.nemesis, scenario.steps).to_wire()
            )
    assert set(SUITES["smoke"]) <= set(SCENARIOS)


def test_nemesis_plan_never_fires_at_step_zero():
    plan = NemesisPlan(5, ["kill_worker", "restart_gcs"], steps=4)
    assert all(step >= 1 for step, _, _ in plan.points)
    assert plan.at_step(0) == []


# ------------------------------------------------------------- interceptors


class _FakeLoop:
    def __init__(self):
        self.later = []

    def call_later(self, t, fn, *args):
        self.later.append((t, fn, args))
        return _FakeTimer()


class _FakeTimer:
    def cancelled(self):
        return False


class _FakeConn:
    """Quacks like rpc.Connection for the interceptor's send paths."""

    def __init__(self):
        self.sent = []
        self._loop = _FakeLoop()

    def _send_direct(self, msg):
        self.sent.append(msg)


def _interceptor(spec, seed=0):
    return interceptors.ChaosInterceptor(FaultSchedule(seed, [spec]))


def test_interceptor_drop_consumes_frame():
    chaos = _interceptor(FaultSpec("d", "drop", "PushChunk", frame="push", p=1.0))
    conn = _FakeConn()
    msg = [0, 3, "PushChunk", {"oid": "x"}]
    assert chaos(conn, msg) is True  # consumed: never sent
    assert conn.sent == []
    assert chaos.log.count("d") == 1


def test_interceptor_delay_schedules_send_direct():
    chaos = _interceptor(
        FaultSpec("l", "delay", "ObjGet", frame="request", p=1.0,
                  delay_s=(0.01, 0.02))
    )
    conn = _FakeConn()
    msg = [1, 0, "ObjGet", {}]
    assert chaos(conn, msg) is True
    (t, fn, args), = conn._loop.later
    assert 0.01 <= t <= 0.02 and fn == conn._send_direct and args == (msg,)


def test_interceptor_dup_sends_extra_copy():
    chaos = _interceptor(FaultSpec("2x", "dup", "RequestWorkerLease", p=1.0))
    conn = _FakeConn()
    msg = [2, 0, "RequestWorkerLease", {"lease_id": "abc"}]
    # Returns False: the original still flows; one extra copy went direct.
    assert chaos(conn, msg) is False
    assert conn.sent == [msg]


def test_interceptor_reorder_swaps_adjacent_frames():
    # Fire on match 0, pass match 1: frame B must be sent before held frame A.
    chaos = _interceptor(
        FaultSpec("r", "reorder", "PushChunk", frame="push", p=1.0, max_fires=1)
    )
    conn = _FakeConn()
    a = [3, 3, "PushChunk", {"seq": 0}]
    b = [4, 3, "PushChunk", {"seq": 1}]
    assert chaos(conn, a) is True and conn.sent == []  # held
    assert chaos(conn, b) is True
    assert conn.sent == [b, a]  # swapped


def test_interceptor_flush_held_releases_frames():
    chaos = _interceptor(
        FaultSpec("r", "reorder", "PushChunk", frame="push", p=1.0)
    )
    conn = _FakeConn()
    msg = [5, 3, "PushChunk", {}]
    assert chaos(conn, msg) is True
    chaos.flush_held()
    assert conn.sent == [msg]


def test_interceptor_ignores_unmatched_frames():
    chaos = _interceptor(FaultSpec("d", "drop", "PushChunk", frame="push", p=1.0))
    conn = _FakeConn()
    assert chaos(conn, [0, 0, "PushChunk", {}]) is False  # request, not push
    assert chaos(conn, [0, 3, "PushStart", {}]) is False  # different method
    assert chaos.log.count() == 0


def test_fault_log_digest_tracks_events():
    chaos = _interceptor(FaultSpec("d", "drop", "*", p=1.0))
    empty = FaultLog().digest()
    assert chaos.log.digest() == empty
    chaos(_FakeConn(), [0, 0, "Anything", {}])
    assert chaos.log.digest() != empty


def test_install_uninstall_roundtrip():
    schedule = FaultSchedule(0, [FaultSpec("d", "drop", "NoSuchMethod", p=1.0)])
    chaos = interceptors.install(schedule)
    try:
        assert rpc.get_send_interceptor() is chaos
        with pytest.raises(RuntimeError):
            interceptors.install(schedule)
    finally:
        assert interceptors.uninstall() is chaos
    assert rpc.get_send_interceptor() is None
    assert interceptors.uninstall() is None


# ------------------------------------------- double-grant guard (regression)


@pytest.fixture
def ray_two_cpus(shutdown_only):
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield worker_mod.global_worker


def _head_raylet(w):
    return w.node.raylet


def test_duplicated_lease_request_grants_once(ray_two_cpus):
    """Regression for the raylet.leases write-write: a wire-duplicated
    RequestWorkerLease must grant exactly one worker, keep the resource
    ledger balanced, and leak nothing (ROADMAP AIOCHECK open item)."""
    w = ray_two_cpus
    schedule = FaultSchedule(
        0, [FaultSpec("2x", "dup", "RequestWorkerLease", frame="request", p=1.0)]
    )

    async def _install():
        return interceptors.install(schedule)

    async def _uninstall():
        return interceptors.uninstall()

    w.run_async(_install())
    try:

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(4)], timeout=60) == [
            0, 2, 4, 6,
        ]
    finally:
        w.run_async(_uninstall())

    raylet = _head_raylet(w)
    assert raylet.duplicate_lease_grants_avoided >= 1

    async def _settle():
        # Leases drain after worker_lease_idle_keep_s; then the ledger must
        # balance exactly and no worker may sit leaked outside the idle pool.
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            task_leases = [
                lid for lid, h in raylet.leases.items() if h.actor_id is None
            ]
            if not task_leases and raylet.available == raylet.total:
                return
            await asyncio.sleep(0.05)
        raise AssertionError(
            f"leases={list(raylet.leases)} "
            f"available={raylet.available.to_dict()} "
            f"total={raylet.total.to_dict()}"
        )

    w.run_async(_settle(), timeout=15)
    assert invariants.check_leases(raylet) == []


def test_duplicate_grant_ledger_allows_actor_restart_reuse():
    """Actor lease ids ARE legitimately re-requested after release (actor
    restart); task lease ids never are."""
    from ray_tpu._private.raylet import Raylet

    r = object.__new__(Raylet)  # ledger methods only touch these attrs
    from collections import OrderedDict

    r.granted_lease_ids = OrderedDict()
    r.duplicate_lease_grants_avoided = 0
    r._record_granted("task-lease-1")
    r._record_granted("actor:abc")
    assert r._is_duplicate_grant("task-lease-1")
    assert r._is_duplicate_grant("actor:abc")
    r._mark_lease_released("task-lease-1")
    r._mark_lease_released("actor:abc")
    # Released task ids stay duplicates (late wire-dup must not re-grant);
    # released actor ids may be granted again (restart path).
    assert r._is_duplicate_grant("task-lease-1")
    assert not r._is_duplicate_grant("actor:abc")
    assert not r._is_duplicate_grant("never-seen")


# ------------------------------------------------------ pull stall recovery


def test_watch_stream_detects_stall():
    from ray_tpu._private.pull_manager import PullManager, PullStalled

    async def go():
        pm = PullManager(1 << 20, stall_timeout_s=0.2)
        with pytest.raises(PullStalled):
            await pm.watch_stream(lambda: 0, lambda: False, timeout=5.0)
        assert pm.stalled_streams == 1

    asyncio.run(go())


def test_watch_stream_returns_on_completion():
    from ray_tpu._private.pull_manager import PullManager

    async def go():
        pm = PullManager(1 << 20, stall_timeout_s=0.2)
        state = {"n": 0, "done": False}

        async def producer():
            for _ in range(4):
                await asyncio.sleep(0.05)
                state["n"] += 1
            state["done"] = True

        task = asyncio.ensure_future(producer())
        await pm.watch_stream(
            lambda: state["n"], lambda: state["done"], timeout=5.0
        )
        await task
        assert pm.stalled_streams == 0

    asyncio.run(go())


@pytest.mark.slow
def test_chunk_loss_pull_rerequests(shutdown_only, monkeypatch):
    """Drop every early PushChunk of the first transfer: the pull must
    stall-detect, abort the half assembly, and converge via re-request or
    the fetch fallback instead of hanging. Marked slow (two-node boot);
    CI's chaos-smoke job runs the chunk_loss scenario over 20 seeds, and
    the stall-detection units above stay in tier-1."""
    monkeypatch.setenv("RAY_TPU_OBJECT_CHUNK_SIZE", "32768")
    monkeypatch.setenv("RAY_TPU_PULL_STALL_TIMEOUT_S", "0.5")
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    try:
        cluster.add_node(num_cpus=1, resources={"victim": 1})
        cluster.connect()
        w = worker_mod.global_worker
        schedule = FaultSchedule(
            3,
            [FaultSpec("lose", "drop", "PushChunk", frame="push", p=1.0,
                       max_fires=6)],
        )

        async def _install():
            return interceptors.install(schedule)

        async def _uninstall():
            return interceptors.uninstall()

        chaos = w.run_async(_install())

        @ray_tpu.remote(resources={"victim": 1})
        def blob():
            return b"\xab" * 300_000

        try:
            data = ray_tpu.get(blob.remote(), timeout=90)
        finally:
            w.run_async(_uninstall())
        assert data == b"\xab" * 300_000
        assert chaos.log.count("lose") >= 1
        stalled = sum(
            r.pull_manager.stalled_streams for r in cluster.raylets.values()
        )
        assert stalled >= 1
    finally:
        cluster.shutdown()


# -------------------------------------------------------- convergence smoke


@pytest.mark.parametrize("name", ["rpc_delay", "dup_lease"])
def test_chaos_smoke_fixed_seeds(shutdown_only, name):
    """Tier-1 smoke: two interceptor scenarios over a fixed seed must
    converge with every invariant intact (CI's chaos-smoke job runs the
    full suite over 20 seeds)."""
    results = run_scenario(SCENARIOS[name], seeds=[0], corpus=None)
    assert [r.ok for r in results] == [True], [
        v for r in results for v in r.violations
    ]
    # Replay determinism: the recorded schedule digest is reproducible.
    for r in results:
        assert (
            FaultSchedule(r.seed, SCENARIOS[name].specs).digest()
            == r.schedule_digest
        )
