"""Scheduling policy fidelity (reference:
src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc +
scheduling_options.h SPREAD/NODE_AFFINITY; test shapes mirror
cluster_task_manager_test.cc scenarios)."""

import time
from collections import Counter

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def three_nodes():
    cluster = Cluster(head_node_args={"num_cpus": 2, "num_tpus": 0})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    yield cluster
    cluster.shutdown()


@ray_tpu.remote
def where():
    import os

    return os.environ["RAY_TPU_NODE_ID"]


def test_spread_strategy_uses_all_nodes(three_nodes):
    refs = [
        where.options(scheduling_strategy="SPREAD").remote() for _ in range(12)
    ]
    nodes = Counter(ray_tpu.get(refs, timeout=120))
    # SPREAD must land tasks on every node, not pile onto the head.
    assert len(nodes) == 3, f"SPREAD used only {dict(nodes)}"


def test_hybrid_spills_past_threshold(three_nodes):
    """Hybrid packs locally while below the spread threshold, then moves
    excess load to other nodes — a burst larger than the head node's CPUs
    must not all run on the head."""

    @ray_tpu.remote
    def hold():
        import os
        import time as _t

        _t.sleep(1.5)
        return os.environ["RAY_TPU_NODE_ID"]

    refs = [hold.remote() for _ in range(6)]
    nodes = Counter(ray_tpu.get(refs, timeout=120))
    assert len(nodes) >= 2, f"hybrid never spilled: {dict(nodes)}"


def test_node_affinity_task(three_nodes):
    target = [n["node_id"] for n in ray_tpu.nodes()][-1]
    refs = [
        where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=target, soft=False
            )
        ).remote()
        for _ in range(4)
    ]
    assert set(ray_tpu.get(refs, timeout=120)) == {target}


def test_node_affinity_hard_missing_node_fails(three_nodes):
    with pytest.raises(Exception, match="affinity target"):
        ray_tpu.get(
            where.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id="f" * 32, soft=False
                )
            ).remote(),
            timeout=60,
        )


# -- locality-aware leasing (reference: locality-aware lease policy) ---------
#
# White-box regressions on the simulated cluster: hints name the raylet
# holding a task's args; the deciding raylet must honor them when the
# holder has room (telemetry hit) and fall back to the normal policy when
# it is saturated (telemetry miss).


def _addr_key(addr):
    return f"{addr[0]}:{addr[1]}"


def test_locality_hint_places_on_arg_holder():
    """Args on node X -> the lease is granted on X when X is feasible, and
    the entry raylet counts a locality hit."""
    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    cluster = SimCluster(8).start()
    try:
        client = SimLeaseClient(cluster)
        nids = sorted(cluster.raylets)
        entry = cluster.raylets[nids[0]]
        target = cluster.raylets[nids[-1]]
        hits0 = entry._tel_locality_hits.v
        grant = cluster.run(
            client.lease(
                {"CPU": 1.0},
                entry_addr=tuple(entry.addr),
                locality={_addr_key(target.addr): 2.0},
            ),
            timeout=30,
        )
        assert tuple(grant["addr"]) == tuple(target.addr), grant
        assert entry._tel_locality_hits.v == hits0 + 1
        cluster.run(client.release(grant), timeout=10)
        cluster.run(client.close(), timeout=10)
    finally:
        cluster.shutdown()


def test_locality_miss_when_holder_saturated():
    """Args on a node with no room -> the hint is a counted miss and the
    regular policy places the lease elsewhere."""
    import asyncio

    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    cluster = SimCluster(4, resources={"CPU": 1.0}).start()
    try:
        client = SimLeaseClient(cluster)
        nids = sorted(cluster.raylets)
        entry = cluster.raylets[nids[0]]
        target = cluster.raylets[nids[-1]]
        tkey = _addr_key(target.addr)

        # Exhaust the arg holder: its single CPU is pinned under a lease.
        pin = cluster.run(
            client.lease({"CPU": 1.0}, entry_addr=tuple(target.addr)),
            timeout=30,
        )
        assert tuple(pin["addr"]) == tuple(target.addr)

        async def holder_seen_saturated():
            # The entry raylet decides from its synced view; wait for the
            # holder's drained availability to reach it (resource report ->
            # GCS -> head broadcast / pulled view) before leasing.
            for _ in range(100):
                entry._view_time = 0.0  # force a fresh GetAllNodes pull
                await entry._cluster_view()
                n = entry._view_map.get(target.node_id)
                head = entry._head_by_addr(tkey)
                # A fully drained resource is omitted from ``available``.
                if (
                    n is not None
                    and n["available"].get("CPU", 0) == 0
                    and (head is None or head["available"].get("CPU", 0) == 0)
                ):
                    return True
                await asyncio.sleep(0.05)
            return False

        assert cluster.run(holder_seen_saturated(), timeout=30), (
            "holder saturation never reached the entry raylet's view"
        )

        misses0 = entry._tel_locality_misses.v
        grant = cluster.run(
            client.lease(
                {"CPU": 1.0},
                entry_addr=tuple(entry.addr),
                locality={tkey: 5.0},
            ),
            timeout=30,
        )
        assert tuple(grant["addr"]) != tuple(target.addr), (
            "lease landed on the saturated arg holder"
        )
        assert entry._tel_locality_misses.v == misses0 + 1
        cluster.run(client.release(grant), timeout=10)
        cluster.run(client.release(pin), timeout=10)
        cluster.run(client.close(), timeout=10)
    finally:
        cluster.shutdown()


# --------------------------------------------- batched lease cancel race


def test_cancel_before_batch_flush_withdraws_locally():
    """A surplus cancel landing between enqueue-into-batch and the flush
    tick must withdraw the entry from the pending LeaseBatch
    (``try_cancel_batched``) instead of sending a CancelWorkerLease for a
    request frame that never went out — the raylet would see a cancel for
    a phantom lease_id."""
    import asyncio

    from ray_tpu._private import rpc
    from ray_tpu._private.core_worker import LeasePool

    async def go():
        seen = []
        server = rpc.Server("127.0.0.1", 0)

        async def req(conn, p):
            seen.append(("RequestWorkerLease", p["lease_id"]))
            return {"cancelled": True}

        async def cancel(conn, p):
            seen.append(("CancelWorkerLease", p["lease_id"]))

        server.register("RequestWorkerLease", req)
        server.register("CancelWorkerLease", cancel)
        addr = await server.start()
        conn = await rpc.connect(*addr)

        class _Core:
            raylet_conn = conn
            job_id = "job-test"

        lp = LeasePool(_Core())
        key = lp.shape_key({"CPU": 1}, None, -1, None)
        pool = lp._pool(key, {"CPU": 1}, None, -1, None)
        waiter = asyncio.get_running_loop().create_future()
        pool.pending.append(("waiter", waiter, None))
        try:
            lp._pump(key, pool)  # spawns one _request_lease
            assert pool.inflight == 1
            # One tick: the request coroutine runs up to its reply await,
            # queueing its entry into this tick's (still unsent) batch.
            await asyncio.sleep(0)
            assert len(conn._batch_entries) == 1, "request must sit in the unsent batch"
            # The work disappears in the same tick (acquire cancelled).
            waiter.cancel()
            pool.pending.clear()
            lp._pump(key, pool)  # surplus trim races the flush
            assert pool.inflight == 0
            assert conn._batch_entries == [], "entry must be withdrawn from the batch"
            assert pool.inflight_ids == set()
            assert pool.inflight_reqs == {}
            # Let the flush tick and any stray frames land.
            await asyncio.sleep(0.2)
            assert seen == [], f"nothing may reach the wire, saw {seen}"
            # The withdrawn coroutine must not double-decrement the slot.
            assert pool.inflight == 0
        finally:
            await conn.close()
            await server.stop()

    asyncio.run(go())


def test_cancel_after_batch_flush_sends_wire_cancel():
    """Contrast case: once the batch has flushed, the surplus trim must
    fall back to a wire CancelWorkerLease — the raylet holds the queued
    request and must be told to drop it."""
    import asyncio

    from ray_tpu._private import rpc
    from ray_tpu._private.core_worker import LeasePool

    async def go():
        seen = []
        cancelled = asyncio.Event()
        server = rpc.Server("127.0.0.1", 0)

        async def req(conn, p):
            seen.append(("RequestWorkerLease", p["lease_id"]))
            await cancelled.wait()  # park until the cancel lands
            return {"cancelled": True}

        async def cancel(conn, p):
            seen.append(("CancelWorkerLease", p["lease_id"]))
            cancelled.set()

        server.register("RequestWorkerLease", req)
        server.register("CancelWorkerLease", cancel)
        addr = await server.start()
        conn = await rpc.connect(*addr)

        class _Core:
            raylet_conn = conn
            job_id = "job-test"

        lp = LeasePool(_Core())
        key = lp.shape_key({"CPU": 1}, None, -1, None)
        pool = lp._pool(key, {"CPU": 1}, None, -1, None)
        waiter = asyncio.get_running_loop().create_future()
        pool.pending.append(("waiter", waiter, None))
        try:
            lp._pump(key, pool)
            await asyncio.sleep(0.1)  # batch flushes; request reaches server
            assert ("RequestWorkerLease", next(iter(pool.inflight_ids))) in seen
            waiter.cancel()
            pool.pending.clear()
            lp._pump(key, pool)
            await asyncio.wait_for(cancelled.wait(), 5)
            await asyncio.sleep(0.1)  # cancelled reply drains
            assert [m for m, _ in seen] == ["RequestWorkerLease", "CancelWorkerLease"]
            assert pool.inflight == 0
            assert pool.inflight_ids == set()
            assert pool.inflight_reqs == {}
        finally:
            await conn.close()
            await server.stop()

    asyncio.run(go())
