"""Scheduling policy fidelity (reference:
src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc +
scheduling_options.h SPREAD/NODE_AFFINITY; test shapes mirror
cluster_task_manager_test.cc scenarios)."""

import time
from collections import Counter

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def three_nodes():
    cluster = Cluster(head_node_args={"num_cpus": 2, "num_tpus": 0})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    yield cluster
    cluster.shutdown()


@ray_tpu.remote
def where():
    import os

    return os.environ["RAY_TPU_NODE_ID"]


def test_spread_strategy_uses_all_nodes(three_nodes):
    refs = [
        where.options(scheduling_strategy="SPREAD").remote() for _ in range(12)
    ]
    nodes = Counter(ray_tpu.get(refs, timeout=120))
    # SPREAD must land tasks on every node, not pile onto the head.
    assert len(nodes) == 3, f"SPREAD used only {dict(nodes)}"


def test_hybrid_spills_past_threshold(three_nodes):
    """Hybrid packs locally while below the spread threshold, then moves
    excess load to other nodes — a burst larger than the head node's CPUs
    must not all run on the head."""

    @ray_tpu.remote
    def hold():
        import os
        import time as _t

        _t.sleep(1.5)
        return os.environ["RAY_TPU_NODE_ID"]

    refs = [hold.remote() for _ in range(6)]
    nodes = Counter(ray_tpu.get(refs, timeout=120))
    assert len(nodes) >= 2, f"hybrid never spilled: {dict(nodes)}"


def test_node_affinity_task(three_nodes):
    target = [n["node_id"] for n in ray_tpu.nodes()][-1]
    refs = [
        where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=target, soft=False
            )
        ).remote()
        for _ in range(4)
    ]
    assert set(ray_tpu.get(refs, timeout=120)) == {target}


def test_node_affinity_hard_missing_node_fails(three_nodes):
    with pytest.raises(Exception, match="affinity target"):
        ray_tpu.get(
            where.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id="f" * 32, soft=False
                )
            ).remote(),
            timeout=60,
        )
