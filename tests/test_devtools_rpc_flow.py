"""Fixtures for the whole-program blocking-graph pass (rpc_flow).

Each rule gets a positive fixture (must flag) and a negative fixture (the
clean idiom must stay quiet) over throwaway trees whose file layout maps
onto the service topology (``_private/gcs.py`` -> gcs, ...); the mutation
gate is exercised from both sides (seeded cycle detected, unmutated tree
clean); and the stale-suppression audit is pinned to cover the
``# rpc-flow:`` waiver family.
"""

import os
import textwrap

import pytest

from ray_tpu.devtools import aio_lint, lint, rpc_check, rpc_flow


def _rules(findings):
    return {f.rule for f in findings}


def _tree(tmp_path, sources):
    """Write {relpath: source} under tmp_path; returns check() paths."""
    for name, src in sources.items():
        dest = tmp_path / name
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_text(textwrap.dedent(src))
    return [str(tmp_path)]


# ---------------------------------------------------------------------------
# wait-cycle
# ---------------------------------------------------------------------------

_CYCLE_GCS = """
class Gcs:
    def setup(self, s):
        s.register("RemoveThing", self._remove_thing)

    async def _remove_thing(self, conn, p):
        return await self.raylet.call("ReleaseThing", {})
"""


def test_wait_cycle_positive(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": _CYCLE_GCS,
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ReleaseThing", self._release_thing)

                async def _release_thing(self, conn, p):
                    return await self.gcs.call("RemoveThing", {})
            """,
        },
    )
    findings = rpc_flow.check(paths)
    assert rpc_flow.RULE_CYCLE in _rules(findings)
    [f] = [f for f in findings if f.rule == rpc_flow.RULE_CYCLE]
    assert "gcs:RemoveThing" in f.message and "raylet:ReleaseThing" in f.message


def test_wait_cycle_negative_async_via(tmp_path):
    # Breaking one edge with a non-blocking via dissolves the cycle: the
    # raylet replies before the GCS round-trip resolves.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": _CYCLE_GCS,
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ReleaseThing", self._release_thing)

                async def _release_thing(self, conn, p):
                    self.gcs.call_nowait("RemoveThing", {})
                    return {}
            """,
        },
    )
    assert rpc_flow.RULE_CYCLE not in _rules(rpc_flow.check(paths))


def test_wait_cycle_negative_spawn_boundary(tmp_path):
    # Work reached across rpc.spawn is on the causal path but does not
    # block the issuing handler — no cycle over it.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": _CYCLE_GCS,
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("ReleaseThing", self._release_thing)

                async def _release_thing(self, conn, p):
                    task = rpc.spawn(self._notify(p))
                    return {}

                async def _notify(self, p):
                    await self.gcs.call("RemoveThing", {})
            """,
        },
    )
    assert rpc_flow.RULE_CYCLE not in _rules(rpc_flow.check(paths))


# ---------------------------------------------------------------------------
# deadline-drop
# ---------------------------------------------------------------------------

_DROP_CALLER = """
async def go(conn):
    await conn.call("DoWork", {}, timeout=5.0)
"""


def test_deadline_drop_positive(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "client.py": _DROP_CALLER,
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("DoWork", self._do_work)

                async def _do_work(self, conn, p):
                    self.worker.call_cb("Notify", {}, self._on_reply)
                    return {}
            """,
        },
    )
    findings = rpc_flow.check(paths)
    assert rpc_flow.RULE_DROP in _rules(findings)


def test_deadline_drop_negative_deadline_kwarg(tmp_path):
    # call_nowait with deadline= re-arms the budget downstream: no drop.
    paths = _tree(
        tmp_path,
        {
            "client.py": _DROP_CALLER,
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("DoWork", self._do_work)

                async def _do_work(self, conn, p):
                    self.worker.call_nowait(
                        "Notify", {}, deadline=rpc.current_deadline()
                    )
                    return {}
            """,
        },
    )
    assert rpc_flow.RULE_DROP not in _rules(rpc_flow.check(paths))


def test_deadline_drop_negative_never_deadlined(tmp_path):
    # No caller ever sends DoWork a budget — there is nothing to drop.
    paths = _tree(
        tmp_path,
        {
            "client.py": """
            async def go(conn):
                conn.call_nowait("DoWork", {})
            """,
            "_private/gcs.py": """
            class Gcs:
                def setup(self, s):
                    s.register("DoWork", self._do_work)

                async def _do_work(self, conn, p):
                    self.worker.call_cb("Notify", {}, self._on_reply)
                    return {}
            """,
        },
    )
    assert rpc_flow.RULE_DROP not in _rules(rpc_flow.check(paths))


# ---------------------------------------------------------------------------
# unbounded-await
# ---------------------------------------------------------------------------

_WAIT_HANDLER = """
import asyncio

class Gcs:
    def setup(self, s):
        s.register("WaitThing", self._wait_thing)

    async def _wait_thing(self, conn, p):
        fut = asyncio.get_running_loop().create_future()
        self.waiters.append(fut)
        return await fut
"""


def test_unbounded_await_positive(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": _WAIT_HANDLER,
            "client.py": """
            async def go(conn):
                await conn.call("WaitThing", {})
            """,
        },
    )
    findings = rpc_flow.check(paths)
    assert rpc_flow.RULE_UNBOUNDED in _rules(findings)


def test_unbounded_await_negative_guaranteed_deadline(tmp_path):
    # Every caller pins a budget, so _run_deadlined cancels the parked
    # handler at the deadline: the await is bounded from outside.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": _WAIT_HANDLER,
            "client.py": """
            async def go(conn, config):
                await conn.call("WaitThing", {}, timeout=config.wait_s)
            """,
        },
    )
    assert rpc_flow.RULE_UNBOUNDED not in _rules(rpc_flow.check(paths))


def test_unbounded_await_negative_spawned_path(tmp_path):
    # A spawned background task parking on a future is its job, not the
    # handler's — only the synchronous closure counts.
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            import asyncio

            class Gcs:
                def setup(self, s):
                    s.register("WaitThing", self._wait_thing)

                async def _wait_thing(self, conn, p):
                    task = rpc.spawn(self._background())
                    return {}

                async def _background(self):
                    fut = asyncio.get_running_loop().create_future()
                    await fut
            """,
            "client.py": """
            async def go(conn):
                await conn.call("WaitThing", {})
            """,
        },
    )
    assert rpc_flow.RULE_UNBOUNDED not in _rules(rpc_flow.check(paths))


# ---------------------------------------------------------------------------
# unsupervised-spawn
# ---------------------------------------------------------------------------

_SPAWN_TREE = {
    "_private/raylet.py": """
    class Raylet:
        def setup(self, s):
            s.register("GrantThing", self._grant_thing)

        async def _grant_thing(self, conn, p):
            self._record_granted(p["id"])
            rpc.spawn(self._finish(p))
            return {}

        async def _finish(self, p):
            pass
    """,
}


def test_unsupervised_spawn_positive(tmp_path):
    findings = rpc_flow.check(_tree(tmp_path, _SPAWN_TREE))
    assert rpc_flow.RULE_SPAWN in _rules(findings)


def test_unsupervised_spawn_negative_bound_task(tmp_path):
    # Binding the task means the caller can observe its failure.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("GrantThing", self._grant_thing)

                async def _grant_thing(self, conn, p):
                    self._record_granted(p["id"])
                    task = rpc.spawn(self._finish(p))
                    task.add_done_callback(self._finish_done)
                    return {}

                async def _finish(self, p):
                    pass
            """,
        },
    )
    assert rpc_flow.RULE_SPAWN not in _rules(rpc_flow.check(paths))


def test_unsupervised_spawn_negative_no_critical_state(tmp_path):
    # Bare spawns are only findings on paths touching ledgered pairs or
    # the PG 2PC protocol; fire-and-forget elsewhere is idiomatic.
    paths = _tree(
        tmp_path,
        {
            "_private/raylet.py": """
            class Raylet:
                def setup(self, s):
                    s.register("PokeThing", self._poke_thing)

                async def _poke_thing(self, conn, p):
                    rpc.spawn(self._finish(p))
                    return {}

                async def _finish(self, p):
                    pass
            """,
        },
    )
    assert rpc_flow.RULE_SPAWN not in _rules(rpc_flow.check(paths))


# ---------------------------------------------------------------------------
# deadline provenance (shared with the wire-protocol Deadline column)
# ---------------------------------------------------------------------------


def test_deadline_sources_pinned_vs_conditional(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "client.py": """
            async def a(conn, config, timeout):
                await conn.call("Pinned", {}, timeout=config.rpc_s)
                await conn.call(
                    "Conditional",
                    {},
                    timeout=None if timeout is None else timeout + 5,
                )
                await conn.call("Ambient", {})
                conn.push_nowait("Never", {})
            """,
        },
    )
    analysis = rpc_flow.build(paths)
    assert rpc_flow.deadline_sources(analysis, "Pinned") == (
        True,
        True,
        ["config.rpc_s"],
    )
    maybe, guaranteed, _ = rpc_flow.deadline_sources(analysis, "Conditional")
    assert maybe and not guaranteed
    assert rpc_flow.deadline_sources(analysis, "Ambient") == (True, False, [])
    assert rpc_flow.deadline_sources(analysis, "Never") == (False, False, [])


# ---------------------------------------------------------------------------
# suppressions + the stale-suppression audit for the rpc-flow family
# ---------------------------------------------------------------------------


def test_suppression_masks_finding(tmp_path):
    paths = _tree(
        tmp_path,
        {
            "_private/gcs.py": """
            import asyncio

            class Gcs:
                def setup(self, s):
                    s.register("WaitThing", self._wait_thing)

                async def _wait_thing(self, conn, p):
                    fut = asyncio.get_running_loop().create_future()
                    self.waiters.append(fut)
                    return await fut  # rpc-flow: disable=unbounded-await
            """,
            "client.py": """
            async def go(conn):
                await conn.call("WaitThing", {})
            """,
        },
    )
    assert rpc_flow.RULE_UNBOUNDED not in _rules(rpc_flow.check(paths))
    raw = rpc_flow.check(paths, apply_suppressions=False)
    assert rpc_flow.RULE_UNBOUNDED in _rules(raw)
    # ...and the audit sees the waiver as live, not stale.
    audit = lint.audit_suppressions(paths)
    assert [f for f in audit if f.rule == lint.RULE_STALE] == []


def test_stale_rpc_flow_suppression_flagged(tmp_path):
    (tmp_path / "m.py").write_text("x = 1  # rpc-flow: disable=wait-cycle\n")
    findings = lint.audit_suppressions([str(tmp_path)])
    assert [f.rule for f in findings] == [lint.RULE_STALE]


# ---------------------------------------------------------------------------
# mutation gate, both sides
# ---------------------------------------------------------------------------


def test_mutation_seeds_detectable_cycle():
    findings = rpc_flow.check(mutate="back_call")
    cycles = [f for f in findings if f.rule == rpc_flow.RULE_CYCLE]
    assert cycles, "seeded back-call cycle must be detected"
    assert any("ReleasePGBundles" in f.message for f in cycles)


def test_mutation_gate_cli_passes_on_mutant(capsys):
    assert rpc_flow.main(["--mutate", "back_call", "--expect-violation"]) == 0
    assert "mutation detected" in capsys.readouterr().out


def test_expect_violation_fails_on_clean_tree(capsys):
    # The other side of the gate: with no seeded defect the clean tree
    # must NOT satisfy --expect-violation (a toothless pass would).
    assert rpc_flow.main(["--expect-violation"]) == 1


# ---------------------------------------------------------------------------
# acceptance: the shipped tree and its committed graph doc (the full-repo
# walk is the expensive part — share one result across the pins)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_markdown():
    return rpc_flow.markdown()


def test_repo_is_rpc_flow_clean():
    assert [str(f) for f in rpc_flow.check()] == []


def test_repo_doc_is_current(repo_markdown):
    root = os.path.dirname(aio_lint._default_root())
    doc = os.path.join(root, "docs", "rpc_flow.md")
    with open(doc, "r", encoding="utf-8") as fh:
        assert fh.read() == repo_markdown + "\n"


def test_markdown_shape(repo_markdown):
    assert "```mermaid" in repo_markdown
    assert "## Blocking edges" in repo_markdown
    assert "## Handler-reachable local waits" in repo_markdown
    assert "## Spawn points on handler paths" in repo_markdown


def test_wire_protocol_doc_has_deadline_column():
    text = rpc_check.markdown_table()
    header = [l for l in text.splitlines() if l.startswith("| Method ")][0]
    assert "| Deadline |" in header
