"""Cluster launcher e2e: YAML -> up -> job -> scale -> down (reference:
`ray up`/`ray down` in python/ray/scripts/scripts.py:1279,1355 against the
fake_multi_node provider, schema ray-schema.json)."""

import subprocess
import sys

import pytest

from ray_tpu.autoscaler.launcher import (
    ClusterConfig,
    ClusterConfigError,
    ClusterLauncher,
    read_cluster_state,
)


FAKE_YAML = """
cluster_name: lctest
max_workers: 4
idle_timeout_minutes: 0.01
provider:
  type: fake
head_node_type: head
available_node_types:
  head:
    resources: {CPU: 2}
    min_workers: 0
    max_workers: 0
  worker:
    resources: {CPU: 2}
    min_workers: 2
    max_workers: 4
"""


def _write_yaml(tmp_path, text=FAKE_YAML):
    p = tmp_path / "cluster.yaml"
    p.write_text(text)
    return str(p)


def test_config_validation(tmp_path):
    cfg = ClusterConfig.from_yaml(_write_yaml(tmp_path))
    assert cfg.cluster_name == "lctest"
    assert set(cfg.worker_types()) == {"worker"}
    with pytest.raises(ClusterConfigError):
        ClusterConfig.from_dict({"cluster_name": "x"})
    with pytest.raises(ClusterConfigError):
        ClusterConfig.from_dict(
            {
                "cluster_name": "x",
                "provider": {"type": "nope"},
                "head_node_type": "h",
                "available_node_types": {"h": {"resources": {}}},
            }
        )
    with pytest.raises(ClusterConfigError):
        ClusterConfig.from_dict(
            {
                "cluster_name": "x",
                "provider": {"type": "fake"},
                "head_node_type": "missing",
                "available_node_types": {"h": {"resources": {}}},
            }
        )


def test_up_job_scale_down(tmp_path, shutdown_only):
    """A YAML boots head + min_workers in-process, runs a job through the
    job manager, the autoscaler can scale, and down() tears it all away."""
    import ray_tpu

    launcher = ClusterLauncher(ClusterConfig.from_yaml(_write_yaml(tmp_path)))
    addr = launcher.up()
    assert addr and ":" in addr
    assert len(launcher._worker_pids) == 2  # min_workers honored
    assert read_cluster_state("lctest")["head_address"] == addr

    # The cluster is usable: run a job end-to-end via the job manager.
    marker = tmp_path / "job_ran.txt"
    entry = (
        f"{sys.executable} -c \"open(r'{marker}', 'w').write('done')\""
    )
    sid, info = launcher.submit(entry, wait=True, timeout=120.0)
    assert info.status == "SUCCEEDED", info
    assert marker.read_text() == "done"

    # Autoscaler round runs against the provider (no demand -> idle nodes
    # past the tiny idle timeout get reclaimed down to min_workers=2,
    # i.e. nothing is terminated below the floor).
    result = launcher.update()
    assert set(result) == {"launched", "terminated"}
    assert len(launcher.provider.non_terminated_nodes()) >= 2

    launcher.down()
    assert launcher.provider.non_terminated_nodes() == []
    assert read_cluster_state("lctest") is None


def test_gce_bootstrap_over_fake_gcloud(tmp_path):
    """GCE path: head TPU-VM is created, polled to READY, and bootstrapped
    over ssh; workers reach READY before up() returns."""
    gce_yaml = """
cluster_name: lcgce
provider:
  type: gce
  project: proj
  zone: us-central2-b
  poll_interval_s: 0.0
head_node_type: head
available_node_types:
  head:
    resources: {CPU: 8}
    min_workers: 0
    max_workers: 0
  worker:
    resources: {CPU: 8, TPU: 4}
    min_workers: 1
    max_workers: 2
"""
    calls = []

    class FakeGcloud:
        def __init__(self):
            self.polls = {}

        def __call__(self, cmd):
            calls.append(cmd)
            verb = cmd[4]
            name = cmd[5]
            if verb == "create":
                self.polls[name] = 1
                return "ok"
            if verb == "describe":
                if self.polls.get(name, 0) > 0:
                    self.polls[name] -= 1
                    return "CREATING"
                return "READY"
            if verb == "ssh":
                return "started"
            if verb == "delete":
                return "ok"
            raise AssertionError(f"unexpected verb {verb}")

    p = tmp_path / "gce.yaml"
    p.write_text(gce_yaml)
    launcher = ClusterLauncher(
        ClusterConfig.from_yaml(str(p)), runner=FakeGcloud()
    )
    addr = launcher.up()
    assert addr.endswith(":6379")
    ssh_calls = [c for c in calls if c[4] == "ssh"]
    assert len(ssh_calls) == 1
    assert "ray-tpu start --head" in " ".join(ssh_calls[0])
    # head + 1 min worker exist and are READY
    states = [
        launcher.provider.node_state(pid)
        for pid in launcher.provider.non_terminated_nodes()
    ]
    assert states and all(s == "READY" for s in states)
    launcher.down()
    deletes = [c for c in calls if c[4] == "delete"]
    assert len(deletes) == 2  # head + worker
