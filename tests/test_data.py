"""ray_tpu.data tests (reference model: python/ray/data/tests)."""

import numpy as np
import pytest

import ray_tpu.data as rd


def test_range_map_filter_take(ray_start_regular):
    ds = rd.range(100, parallelism=4)
    out = (
        ds.map(lambda r: {"id": r["id"] * 2})
        .filter(lambda r: r["id"] % 4 == 0)
        .take(5)
    )
    assert out == [{"id": 0}, {"id": 4}, {"id": 8}, {"id": 12}, {"id": 16}]


def test_map_batches_and_count(ray_start_regular):
    ds = rd.range(64, parallelism=4).map_batches(
        lambda b: {"sq": b["id"] ** 2}, batch_format="numpy"
    )
    assert ds.count() == 64
    rows = ds.take_all()
    assert rows[5] == {"sq": 25}


def test_map_batches_class_actor_pool(ray_start_regular):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"y": batch["id"] + self.c}

    ds = rd.range(32, parallelism=4).map_batches(
        AddConst, fn_constructor_args=(100,), concurrency=2
    )
    rows = ds.take_all()
    assert sorted(r["y"] for r in rows) == list(range(100, 132))


def test_from_items_flat_map_union_zip(ray_start_regular):
    a = rd.from_items([1, 2, 3], parallelism=2)
    doubled = a.flat_map(lambda v: [v, v])
    assert doubled.count() == 6
    u = a.union(rd.from_items([4, 5], parallelism=1))
    assert sorted(u.take_all()) == [1, 2, 3, 4, 5]
    z = rd.range(4, parallelism=2).zip(
        rd.range(4, parallelism=2).map(lambda r: {"other": r["id"] + 10})
    )
    rows = z.take_all()
    assert rows[2] == {"id": 2, "other": 12}


def test_sort_and_shuffle(ray_start_regular):
    ds = rd.from_items(
        [{"k": v} for v in [5, 3, 8, 1, 9, 2, 7, 4, 6, 0]], parallelism=3
    )
    assert [r["k"] for r in ds.sort("k").take_all()] == list(range(10))
    assert [r["k"] for r in ds.sort("k", descending=True).take_all()] == list(
        reversed(range(10))
    )
    shuffled = ds.random_shuffle(seed=0).take_all()
    assert sorted(r["k"] for r in shuffled) == list(range(10))


def test_groupby_aggregate(ray_start_regular):
    ds = rd.from_items(
        [{"g": i % 3, "v": i} for i in range(12)], parallelism=3
    )
    out = {r["g"]: r["v_sum"] for r in ds.groupby("g").sum("v").take_all()}
    assert out == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    means = {r["g"]: r["v_mean"] for r in ds.groupby("g").mean("v").take_all()}
    assert means[0] == 4.5


def test_groupby_string_keys_cross_process(ray_start_regular):
    # String keys exercise the deterministic-hash path: python hash() is
    # per-process randomized and would split one key across partitions.
    ds = rd.from_items(
        [{"city": c, "x": 1} for c in ["NYC", "SF", "NYC", "LA", "SF", "NYC"]],
        parallelism=3,
    )
    out = {r["city"]: r["x_sum"] for r in ds.groupby("city").sum("x").take_all()}
    assert out == {"NYC": 3, "SF": 2, "LA": 1}


def test_repartition_limit_schema(ray_start_regular):
    ds = rd.range(100, parallelism=5).repartition(3)
    blocks = list(ds.iter_blocks())
    assert len(blocks) == 3
    assert sum(b.num_rows for b in blocks) == 100
    assert ds.limit(7).count() == 7
    assert ds.schema().names == ["id"]


def test_iter_batches_sizes(ray_start_regular):
    ds = rd.range(50, parallelism=4)
    batches = list(ds.iter_batches(batch_size=16, batch_format="numpy"))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [16, 16, 16, 2]
    assert np.array_equal(batches[0]["id"], np.arange(16))


def test_read_write_parquet_csv(ray_start_regular, tmp_path):
    ds = rd.range(20, parallelism=2).map(lambda r: {"id": r["id"], "x": r["id"] * 1.5})
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rd.read_parquet(pq_dir)
    assert back.count() == 20
    assert back.sort("id").take(2) == [
        {"id": 0, "x": 0.0},
        {"id": 1, "x": 1.5},
    ]
    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    assert rd.read_csv(csv_dir).count() == 20


def test_read_text_binary_numpy(ray_start_regular, tmp_path):
    """read_text / read_binary_files / read_numpy datasources (reference:
    ray.data.read_text / read_binary_files / read_numpy)."""
    import numpy as np

    import ray_tpu.data as rd

    (tmp_path / "a.txt").write_text("alpha\n\nbeta\n")
    (tmp_path / "b.txt").write_text("gamma\n")
    ds = rd.read_text([str(tmp_path / "a.txt"), str(tmp_path / "b.txt")])
    texts = sorted(r["text"] for r in ds.take_all())
    assert texts == ["alpha", "beta", "gamma"]

    (tmp_path / "x.bin").write_bytes(b"\x00\x01payload")
    ds = rd.read_binary_files(str(tmp_path / "x.bin"), include_paths=True)
    rows = ds.take_all()
    assert rows[0]["bytes"] == b"\x00\x01payload"
    assert rows[0]["path"].endswith("x.bin")

    np.save(tmp_path / "arr.npy", np.arange(6, dtype=np.int64))
    ds = rd.read_numpy(str(tmp_path / "arr.npy"))
    vals = [r["data"] for r in ds.take_all()]
    assert vals == list(range(6))


def test_streaming_split_covers_all_rows(ray_start_regular):
    ds = rd.range(40, parallelism=4)
    shards = ds.streaming_split(2)
    seen = []
    for s in shards:
        for b in s.iter_batches(batch_size=None):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(40))
    # second epoch re-iterates
    again = []
    for b in shards[0].iter_batches(batch_size=None):
        again.extend(b["id"].tolist())
    assert len(again) > 0


def test_streaming_split_in_trainer(ray_start_regular, tmp_path):
    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    def train_fn(config):
        shard = train.get_dataset_shard("train")
        total = 0
        for batch in shard.iter_batches(batch_size=8):
            total += int(batch["id"].sum())
        train.report({"total": total})

    result = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t_data", storage_path=str(tmp_path)),
        datasets={"train": rd.range(32, parallelism=4)},
    ).fit()
    # both shards together cover 0..31; rank0 metric is its own partial sum
    assert result.metrics["total"] > 0


def test_sort_empty_and_single_block(ray_start_regular):
    assert rd.from_items([], parallelism=1).count() == 0
    ds = rd.from_items([{"k": 2}, {"k": 1}], parallelism=1)
    assert [r["k"] for r in ds.sort("k").take_all()] == [1, 2]


def test_read_images(ray_start_regular, tmp_path):
    """read_images decodes to an 'image' tensor column (reference:
    ray.data.read_images); size= resizes mixed-size files into one stacked
    fixed-shape column and include_paths records provenance."""
    from PIL import Image

    for i, wh in enumerate([(16, 12), (8, 8), (16, 12)]):
        Image.new("RGB", wh, color=(i * 40, 10, 200)).save(
            tmp_path / f"img_{i}.png"
        )
    ds = rd.read_images(
        str(tmp_path), size=(10, 14), mode="RGB", include_paths=True,
        parallelism=2,
    )
    rows = ds.take_all()
    assert len(rows) == 3
    batch = next(iter(ds.iter_batches(batch_size=3)))
    assert batch["image"].shape == (3, 10, 14, 3)
    assert batch["image"].dtype == np.uint8
    assert sorted(p.split("_")[-1] for p in batch["path"].tolist()) == [
        "0.png", "1.png", "2.png",
    ]


def test_read_webdataset(ray_start_regular, tmp_path):
    """read_webdataset groups tar members into samples by key and decodes
    txt/cls/json fields (reference: ray.data.read_webdataset)."""
    import io
    import json as _json
    import tarfile

    shard = tmp_path / "shard-000.tar"
    with tarfile.open(shard, "w") as tf:
        for key, cls in [("s0", 3), ("s1", 7)]:
            for field, data in [
                ("jpg", b"\xff\xd8fakejpeg"),
                ("cls", str(cls).encode()),
                ("txt", f"caption {key}".encode()),
                ("json", _json.dumps({"k": key}).encode()),
            ]:
                info = tarfile.TarInfo(f"{key}.{field}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    rows = rd.read_webdataset(str(shard)).take_all()
    assert [r["__key__"] for r in rows] == ["s0", "s1"]
    assert rows[0]["cls"] == 3 and rows[1]["cls"] == 7
    assert rows[0]["txt"] == "caption s0"
    assert rows[0]["json"]["k"] == "s0"
    assert rows[0]["jpg"].startswith(b"\xff\xd8")


def test_read_webdataset_no_cross_shard_merge(ray_start_regular, tmp_path):
    """Equal sample keys in different shards stay separate rows; dotfiles
    are skipped; mixed-shape read_images without size= raises with a fix."""
    import io
    import tarfile

    for shard_i in range(2):
        with tarfile.open(tmp_path / f"s{shard_i}.tar", "w") as tf:
            for name, data in [
                ("000000.cls", str(shard_i).encode()),
                ("._000000.jpg", b"applejunk"),
                (".DS_Store", b"junk"),
            ]:
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    rows = rd.read_webdataset(
        [str(tmp_path / "s0.tar"), str(tmp_path / "s1.tar")], parallelism=1
    ).take_all()
    assert sorted(r["cls"] for r in rows) == [0, 1]  # two rows, not one
    assert all(set(r) == {"__key__", "cls"} for r in rows)  # dotfiles skipped

    from PIL import Image

    Image.new("L", (8, 8)).save(tmp_path / "grey.png")
    Image.new("RGB", (8, 8)).save(tmp_path / "rgb.png")
    with pytest.raises(Exception, match="size"):
        rd.read_images(
            [str(tmp_path / "grey.png"), str(tmp_path / "rgb.png")],
            parallelism=1,
        ).take_all()
