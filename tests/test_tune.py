"""ray_tpu.tune tests (reference model: python/ray/tune/tests)."""

import pytest

from ray_tpu import tune
from ray_tpu.tune.schedulers import CONTINUE, STOP, AsyncHyperBandScheduler
from ray_tpu.tune.search import BasicVariantGenerator


def test_basic_variant_grid_and_samples():
    gen = BasicVariantGenerator(seed=0)
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0.0, 1.0),
        "fixed": 7,
        "nested": {"bs": tune.grid_search([8, 16])},
    }
    configs = gen.generate(space, num_samples=2)
    assert len(configs) == 8  # 2 grid x 2 grid x 2 samples
    assert all(c["fixed"] == 7 for c in configs)
    assert {c["lr"] for c in configs} == {0.1, 0.01}
    assert {c["nested"]["bs"] for c in configs} == {8, 16}
    assert all(0.0 <= c["wd"] <= 1.0 for c in configs)


def test_asha_stops_bad_trials():
    sched = AsyncHyperBandScheduler(
        metric="score", mode="max", grace_period=1, reduction_factor=2, max_t=16
    )
    # Good trial reaches rung first and sets the bar.
    assert sched.on_trial_result("good", {"training_iteration": 1, "score": 1.0}) == CONTINUE
    assert sched.on_trial_result("bad", {"training_iteration": 1, "score": 0.1}) == STOP
    # max_t reached -> stop regardless
    assert sched.on_trial_result("good", {"training_iteration": 16, "score": 9.9}) == STOP


def test_tuner_grid_search_end_to_end(ray_start_regular, tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 5, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=__import__("ray_tpu.air", fromlist=["RunConfig"]).RunConfig(
            name="exp1", storage_path=str(tmp_path)
        ),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["score"] == 15
    assert best.metrics["training_iteration"] == 3


def test_tuner_trial_error_isolated(ray_start_regular, tmp_path):
    def trainable(config):
        if config["x"] == 2:
            raise RuntimeError("bad trial")
        tune.report({"score": config["x"]})

    from ray_tpu.air import RunConfig

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="exp_err", storage_path=str(tmp_path)),
    ).fit()
    assert grid.num_errors == 1
    assert grid.get_best_result().metrics["score"] == 3


def test_tuner_with_asha_early_stops(ray_start_regular, tmp_path):
    def trainable(config):
        for i in range(8):
            tune.report({"score": config["x"] * (i + 1)})

    from ray_tpu.air import RunConfig

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([10, 1, 1, 1])},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            scheduler=AsyncHyperBandScheduler(
                grace_period=2, reduction_factor=2, max_t=8
            ),
            max_concurrent_trials=2,
        ),
        run_config=RunConfig(name="exp_asha", storage_path=str(tmp_path)),
    ).fit()
    stopped = [t for t in grid._trials if t.early_stopped]
    assert len(stopped) >= 1  # the x=1 stragglers get culled
    assert grid.get_best_result().metrics["score"] >= 80 - 10


def test_tuner_restore_reruns_unfinished(ray_start_regular, tmp_path):
    import os

    def trainable(config):
        tune.report({"score": config["x"]})

    from ray_tpu.air import RunConfig

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="exp_restore", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    exp_dir = os.path.join(str(tmp_path), "exp_restore")
    restored = tune.Tuner.restore(exp_dir, trainable)
    grid2 = restored.fit()
    # everything already TERMINATED -> same results, no re-run
    assert len(grid2) == 2
    assert grid2.get_best_result(metric="score", mode="max").metrics["score"] == 2


def test_trainer_under_tuner(ray_start_regular, tmp_path):
    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    def train_fn(config):
        ctx = train.get_context()
        train.report({"acc": config["lr"] * 10, "world": ctx.get_world_size()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {"lr": tune.grid_search([0.1, 0.5])}},
        tune_config=tune.TuneConfig(metric="acc", mode="max", max_concurrent_trials=1),
        run_config=RunConfig(name="exp_trainer", storage_path=str(tmp_path)),
    ).fit()
    best = grid.get_best_result()
    assert best.metrics["acc"] == 5.0
    assert best.metrics["world"] == 2


def test_tpe_searcher_pure_protocol():
    """TPE model quality without the runtime: on a deterministic quadratic,
    TPE's post-startup suggestions concentrate near the optimum and beat
    random search under the same budget (seed-matched)."""
    import random

    from ray_tpu.tune import TPESearcher
    from ray_tpu.tune.search import _walk

    space = {"x": tune.uniform(-10.0, 10.0)}

    def objective(cfg):
        return -((cfg["x"] - 3.0) ** 2)  # max at x=3

    def run_tpe(seed):
        s = TPESearcher(metric="score", mode="max", n_initial=8, seed=seed)
        s.set_search_space(space)
        best = -float("inf")
        for i in range(40):
            cfg = s.suggest(f"t{i}")
            score = objective(cfg)
            best = max(best, score)
            s.on_trial_complete(f"t{i}", {"score": score})
        return best

    def run_random(seed):
        rng = random.Random(seed)
        _, domains = _walk(space, ())
        best = -float("inf")
        for _ in range(40):
            x = domains[0][1].sample(rng)
            best = max(best, objective({"x": x}))
        return best

    tpe_wins = sum(
        1 for seed in range(5) if run_tpe(seed) >= run_random(seed)
    )
    assert tpe_wins >= 4  # dominates random under a matched budget


def test_tpe_searcher_through_tuner(ray_start_regular, tmp_path):
    """Tuner(search_alg=TPESearcher): trials are suggested on demand and
    results feed the model back through the controller."""
    from ray_tpu.air import RunConfig
    from ray_tpu.tune import TPESearcher

    def trainable(config):
        x = config["x"]
        tune.report({"score": -((x - 3.0) ** 2)})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(-10.0, 10.0)},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            num_samples=12,
            search_alg=TPESearcher(n_initial=4, seed=0),
            max_concurrent_trials=2,
        ),
        run_config=RunConfig(name="exp_tpe", storage_path=str(tmp_path)),
    ).fit()
    results = [r for r in grid]
    assert len(results) == 12
    best = grid.get_best_result()
    # With 8 adaptive suggestions after 4 random startups the best x should
    # land well inside (-10, 10)'s central region around 3.
    assert best.metrics["score"] > -9.0
